"""IR-level pipeline parallelism (distributed/pipeline/): the stage
partitioner over the static Program op list, the micro-batch schedule
tables, the pipelined runtime's EXACT gradient parity against the
unpipelined step, (data, pp) mesh placement, planner integration
(PP as a placement dimension under hard-HBM rejection), and the
TPU8xx cross-stage verifier family.

Parity model: pipelining reorders WHEN each microbatch's forward and
backward run, never WHAT they compute — per-microbatch contributions
are reduced in a fixed order, so every schedule must be bitwise
identical to the sequential microbatched step, and both must match an
independent jax.grad over the raw op-list replay.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn, static
from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.distributed.pipeline import (
    SCHEDULES, PipelinedProgram, analytical_bubble, build_schedule,
    partition_program, peak_inflight, simulate)
from paddle_tpu.static import verifier


def _mlp_program(n_blocks=4, d=8, rows=4, seed=7):
    """Stacked Linear+GELU chain traced at MICROBATCH shape [rows, d]."""
    paddle.seed(seed)
    blocks = []
    for _ in range(n_blocks):
        blocks += [nn.Linear(d, d), nn.GELU()]
    model = nn.Sequential(*blocks)
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [rows, d], "float32")
        y = static.data("y", [rows, d], "float32")
        loss = ((model(x) - y) ** 2).mean()
    return prog, loss


def _feed(prog, m, seed=3):
    """Random feed at m x the traced microbatch leading dim."""
    rng = np.random.RandomState(seed)
    out = {}
    for name, vid in prog.feed_vars.items():
        shape = list(prog._feed_shapes[name])
        shape[0] *= m
        dt = str(prog._feed_dtypes[name])
        if dt.startswith("int"):
            out[name] = rng.randint(0, 8, size=shape).astype(dt)
        else:
            out[name] = rng.randn(*shape).astype(dt)
    return out


def _ref_loss_grads(prog, loss_id, feed, m, params=None):
    """Independent reference: jax.grad over the raw op-list replay,
    microbatch-mean — no pipeline machinery involved."""
    names = sorted(prog.feed_vars)
    feed_ids = [prog.feed_vars[n] for n in names]
    cap_ids = list(prog._captured.keys())
    base = {pid: t._data for pid, t in prog._captured.items()}
    if params:
        base.update(params)
    diff_ids = [pid for pid in cap_ids
                if jnp.issubdtype(jnp.asarray(base[pid]).dtype,
                                  jnp.inexact)]
    rest = {pid: base[pid] for pid in cap_ids if pid not in diff_ids}

    def total(diff_list):
        caps = dict(zip(diff_ids, diff_list))
        caps.update(rest)
        tot = 0.0
        for j in range(m):
            mb = [jnp.split(jnp.asarray(feed[n]), m)[j] for n in names]
            env = prog._replay_by_ids(feed_ids, mb, cap_ids,
                                      [caps[pid] for pid in cap_ids])
            tot = tot + env[loss_id]
        return tot / m

    loss, grads = jax.value_and_grad(total)(
        [base[pid] for pid in diff_ids])
    return loss, dict(zip(diff_ids, grads))


# ==========================================================================
# stage partitioner
# ==========================================================================
class TestPartitioner:
    def test_uniform_contiguous_cover(self):
        prog, loss = _mlp_program()
        part = partition_program(prog, 4, strategy="uniform",
                                 fetch_ids=[id(loss)])
        ops = prog.global_block().ops
        assert len(part.stages) == 4
        covered = []
        for k, st in enumerate(part.stages):
            assert st.index == k
            assert st.op_stop > st.op_start
            covered.extend(range(st.op_start, st.op_stop))
        assert covered == list(range(len(ops)))

    def test_cost_strategy_balances_seconds(self):
        prog, loss = _mlp_program(n_blocks=8)
        part = partition_program(prog, 4, strategy="cost",
                                 fetch_ids=[id(loss)])
        secs = part.stage_seconds()
        assert len(secs) == 4 and all(s > 0 for s in secs)
        # identical blocks: the greedy prefix cut keeps stages within
        # a small factor of each other
        assert max(secs) <= 4.0 * min(s for s in secs if s > 0)

    def test_custom_split_points(self):
        prog, loss = _mlp_program()
        n = len(prog.global_block().ops)
        cut = n // 2
        part = partition_program(prog, strategy="custom",
                                 split_points=[cut],
                                 fetch_ids=[id(loss)])
        assert part.boundaries == (cut,)
        assert part.stages[0].op_stop == cut
        assert part.stages[1].op_start == cut

    def test_cut_values_pair_across_boundary(self):
        prog, loss = _mlp_program()
        part = partition_program(prog, 2, fetch_ids=[id(loss)])
        s0, s1 = part.stages
        assert s0.send and s0.send == s1.recv
        # cuts are real intermediate values: not feeds, not params
        feeds = set(prog.feed_vars.values())
        for vid in s0.send:
            assert vid not in feeds
            assert vid not in s0.param_ids
        # params partition disjointly
        assert not (set(s0.param_ids) & set(s1.param_ids))

    def test_stage_records_carry_transfer_contract(self):
        prog, loss = _mlp_program()
        part = partition_program(prog, 2, fetch_ids=[id(loss)])
        recs0, recs1 = part.stage_records()
        sends = [r for r in recs0 if r.name == "send"]
        recvs = [r for r in recs1 if r.name == "recv"]
        assert sends and len(sends) == len(recvs)
        for k, (snd, rcv) in enumerate(zip(sends, recvs)):
            assert snd.attrs["peer"] == 1 and rcv.attrs["peer"] == 0
            assert snd.attrs["seq"] == rcv.attrs["seq"] == k
            assert snd.in_shapes[0] == rcv.out_shapes[0]
            assert snd.in_dtypes[0] == rcv.out_dtypes[0]


# ==========================================================================
# schedule tables
# ==========================================================================
class TestSchedules:
    @pytest.mark.parametrize("name", ["fthenb", "1f1b"])
    @pytest.mark.parametrize("S,m", [(2, 4), (4, 8), (4, 16)])
    def test_uniform_bubble_matches_closed_form(self, name, S, m):
        table = build_schedule(name, S, m)
        sim = simulate(table)
        want = (S - 1) / (m + S - 1)
        assert sim["bubble"] == pytest.approx(want, abs=1e-9)
        assert analytical_bubble(name, S, m) == pytest.approx(want)

    def test_every_unit_runs_once(self):
        for name in SCHEDULES:
            S, m = 4, 6
            table = build_schedule(name, S, m)
            assert len(table) == S
            for s in range(S):
                for kind in ("F", "B"):
                    units = [st for st in table[s] if st.kind == kind]
                    assert all(st.stage == s for st in units)
                    assert sorted(st.mb for st in units) == list(range(m))

    def test_1f1b_memory_win_over_fthenb(self):
        S, m = 4, 16
        depth_ft = peak_inflight(build_schedule("fthenb", S, m))
        depth_11 = peak_inflight(build_schedule("1f1b", S, m))
        assert depth_ft[0] == m
        assert depth_11[0] == min(m, S)

    def test_zb_no_worse_than_1f1b(self):
        S, m = 4, 8
        zb = simulate(build_schedule("zb", S, m))
        f11 = simulate(build_schedule("1f1b", S, m))
        assert zb["makespan"] <= f11["makespan"] + 1e-9
        assert analytical_bubble("zb", S, m) == pytest.approx(
            zb["bubble"])


# ==========================================================================
# runtime: exact parity
# ==========================================================================
class TestRuntimeParity:
    @pytest.mark.parametrize("schedule", SCHEDULES)
    def test_bitwise_vs_unpipelined(self, schedule):
        prog, loss = _mlp_program()
        part = partition_program(prog, 2, fetch_ids=[id(loss)])
        pp = PipelinedProgram(part, schedule=schedule,
                              loss_id=id(loss))
        feed = _feed(prog, m=4)
        l_pp, g_pp, stats = pp.train_step(feed, 4)
        l_ref, g_ref = pp.run_unpipelined(feed, 4)
        # bitwise: pipelining reorders execution, not arithmetic
        assert np.asarray(l_pp).tobytes() == np.asarray(l_ref).tobytes()
        assert set(g_pp) == set(g_ref)
        for pid in g_ref:
            assert np.asarray(g_pp[pid]).tobytes() == \
                np.asarray(g_ref[pid]).tobytes()
        assert stats["schedule"] == schedule
        assert stats["num_stages"] == 2

    def test_matches_independent_jax_grad(self):
        prog, loss = _mlp_program()
        part = partition_program(prog, 2, fetch_ids=[id(loss)])
        pp = PipelinedProgram(part, schedule="1f1b", loss_id=id(loss))
        feed = _feed(prog, m=4)
        l_pp, g_pp, _ = pp.train_step(feed, 4)
        l_ref, g_ref = _ref_loss_grads(prog, id(loss), feed, 4)
        np.testing.assert_allclose(np.asarray(l_pp),
                                   np.asarray(l_ref), rtol=1e-6)
        assert set(g_pp) == set(g_ref)
        for pid in g_ref:
            np.testing.assert_allclose(np.asarray(g_pp[pid]),
                                       np.asarray(g_ref[pid]),
                                       rtol=1e-5, atol=1e-6)

    def test_forward_only(self):
        prog, loss = _mlp_program()
        part = partition_program(prog, 2, fetch_ids=[id(loss)])
        pp = PipelinedProgram(part, schedule="fthenb", loss_id=id(loss))
        feed = _feed(prog, m=2)
        fetched = pp.forward(feed, 2)
        assert id(loss) in fetched and len(fetched[id(loss)]) == 2

    def test_loss_must_live_on_last_stage(self):
        prog, loss = _mlp_program()
        part = partition_program(prog, 2, fetch_ids=[id(loss)])
        with pytest.raises(ValueError):
            PipelinedProgram(part, loss_id=123456789)


# ==========================================================================
# (data, pp) mesh placement + 4-stage GPT training parity
# ==========================================================================
@pytest.fixture
def dp_pp_mesh():
    old = mesh_mod._global_mesh
    mesh = mesh_mod.build_mesh({"data": 2, "pp": 4})
    mesh_mod.set_mesh(mesh)
    yield mesh
    mesh_mod.set_mesh(old)


def _gpt_program(batch=2, seq=8):
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.nn import functional as F
    import paddle_tpu.ops as ops
    paddle.seed(11)
    model = GPTForCausalLM(GPTConfig(
        vocab_size=32, hidden_size=16, num_layers=4, num_heads=2,
        max_seq_len=16, use_flash_attention=False))
    prog = static.Program()
    with static.program_guard(prog):
        ids = static.data("ids", [batch, seq], "int64")
        logits = model(ids)
        if isinstance(logits, (tuple, list)):
            logits = logits[0]
        v = logits.shape[-1]
        loss = F.cross_entropy(
            ops.reshape(logits[:, :-1, :], [-1, v]),
            ops.reshape(ids[:, 1:], [-1])).mean()
    return prog, loss


class TestGPTMeshTraining:
    def test_4stage_gpt_trains_with_loss_parity(self, dp_pp_mesh):
        """The acceptance bar: a 4-layer GPT trained for 3 SGD steps on
        the (data=2, pp=4) mesh tracks the single-device unpipelined
        reference loss step for step."""
        prog, loss = _gpt_program()
        part = partition_program(prog, 4, fetch_ids=[id(loss)])
        pp = PipelinedProgram(part, schedule="1f1b", loss_id=id(loss),
                              mesh=dp_pp_mesh, pp_axis="pp",
                              data_axis="data")
        m, lr = 4, 0.1
        feed = _feed(prog, m=m, seed=5)
        ref_params = None
        losses, ref_losses = [], []
        for _ in range(3):
            l_pp, g_pp, _ = pp.train_step(feed, m)
            l_ref, g_ref = _ref_loss_grads(prog, id(loss), feed, m,
                                           params=ref_params)
            losses.append(float(np.asarray(l_pp)))
            ref_losses.append(float(np.asarray(l_ref)))
            # SGD on both sides: the pipelined program's captured
            # params, and the reference's private copies
            if ref_params is None:
                ref_params = {pid: prog._captured[pid]._data
                              for pid in prog._captured}
            for pid, g in g_pp.items():
                t = prog._captured[pid]
                t._swap_payload(t._data - lr * jnp.asarray(g))
            ref_params = {
                pid: (ref_params[pid] - lr * jnp.asarray(g_ref[pid])
                      if pid in g_ref else ref_params[pid])
                for pid in ref_params}
        np.testing.assert_allclose(losses, ref_losses, rtol=1e-4)
        assert losses[-1] < losses[0]  # it actually trains

    def test_pipeline_only_mesh_matches_unmeshed(self, dp_pp_mesh):
        prog, loss = _mlp_program()
        part = partition_program(prog, 2, fetch_ids=[id(loss)])
        feed = _feed(prog, m=2)
        # a (2, 2) sub-mesh over 4 of the 8 virtual devices
        mesh = jax.sharding.Mesh(
            np.array(jax.devices()[:4]).reshape(2, 2), ("data", "pp"))
        on_mesh = PipelinedProgram(part, schedule="1f1b",
                                   loss_id=id(loss), mesh=mesh,
                                   pp_axis="pp", data_axis="data")
        plain = PipelinedProgram(part, schedule="1f1b",
                                 loss_id=id(loss))
        l_m, g_m, _ = on_mesh.train_step(feed, 2)
        l_p, g_p, _ = plain.train_step(feed, 2)
        np.testing.assert_allclose(np.asarray(l_m), np.asarray(l_p),
                                   rtol=1e-6)
        for pid in g_p:
            np.testing.assert_allclose(np.asarray(g_m[pid]),
                                       np.asarray(g_p[pid]),
                                       rtol=1e-5, atol=1e-7)


# ==========================================================================
# planner integration: PP under hard-HBM rejection
# ==========================================================================
class TestPlannerIntegration:
    def test_pp_wins_when_hbm_rejects_tp_fsdp(self, dp_pp_mesh):
        from paddle_tpu.distributed.planner import plan
        prog, loss = _mlp_program(n_blocks=8, d=32)
        # capacity below what any whole-model-per-device candidate
        # needs, but 1/4 of the params per stage fits
        param_bytes = sum(
            float(np.prod(t._data.shape)) * 4
            for t in prog._captured.values())
        capacity = param_bytes * 4.0 * 0.6   # (2 + opt) * 0.6 < full
        result = plan(prog, dp_pp_mesh, capacity_bytes=capacity)
        win = result.winner
        assert win.candidate.origin == "pipeline", \
            [(c.candidate.name, c.score.rejected) for c in result.ranked]
        assert result.pipeline is not None
        assert result.pipeline.num_stages == 4
        assert result.pipeline.schedule in SCHEDULES
        assert 0.0 < result.pipeline.bubble_fraction < 1.0
        assert "pipeline" in result.summary()

    def test_pp_not_offered_without_pipeline_axis(self):
        from paddle_tpu.distributed.pipeline.planning import \
            pipeline_candidates
        prog, loss = _mlp_program()
        mesh = mesh_mod.build_mesh({"data": 8})
        assert pipeline_candidates(prog, mesh) == []

    def test_roomy_capacity_prefers_pure_dp(self, dp_pp_mesh):
        from paddle_tpu.distributed.planner import plan
        prog, loss = _mlp_program()
        result = plan(prog, dp_pp_mesh, capacity_bytes=1e12)
        assert result.winner.candidate.origin != "pipeline"
        assert result.pipeline is None


# ==========================================================================
# verifier: TPU8xx cross-stage desync
# ==========================================================================
class TestStageVerifier:
    def _records(self):
        prog, loss = _mlp_program()
        part = partition_program(prog, 2, fetch_ids=[id(loss)])
        return [list(r) for r in part.stage_records()]

    def test_clean_partition_verifies(self):
        report = verifier.check_stages(self._records())
        assert report.ok, report.render()

    def test_shape_desync_flagged_and_strict_raises(self):
        recs = self._records()
        for r in recs[1]:
            if r.name == "recv":
                r.out_shapes = ((9, 9),)
                break
        report = verifier.check_stages(recs)
        assert "TPU802" in report.codes()
        with pytest.raises(verifier.ProgramVerifierError):
            verifier.enforce(report, "strict")

    def test_dropped_recv_flagged(self):
        recs = self._records()
        recs[1] = [r for r in recs[1] if r.name != "recv"]
        report = verifier.check_stages(recs)
        assert "TPU801" in report.codes()

    def test_runtime_strict_check_rejects_tampered_partition(self):
        prog, loss = _mlp_program()
        part = partition_program(prog, 2, fetch_ids=[id(loss)])
        # tamper the partition's own contract: claim a different dtype
        # on the boundary recv
        recs = [list(r) for r in part.stage_records()]
        for r in recs[1]:
            if r.name == "recv":
                r.out_dtypes = ("int32",)
                break
        report = verifier.check_stages(recs)
        assert "TPU802" in report.codes()
