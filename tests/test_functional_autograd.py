"""Higher-order functional autograd (jacobian/hessian/jvp/vjp), dlpack
interchange, and paddle.hub.

Reference contracts: python/paddle/autograd/autograd.py (:450/:544),
python/paddle/incubate/autograd/functional.py (:22/:80/:143),
python/paddle/utils/dlpack.py, python/paddle/hapi/hub.py.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.incubate import autograd as iag
from paddle_tpu.utils import dlpack


def _x(vals):
    t = paddle.to_tensor(np.asarray(vals, np.float32))
    t.stop_gradient = False
    return t


class TestJacobian:
    def test_diag_square(self):
        x = _x([1.0, 2.0, 3.0])
        J = paddle.autograd.jacobian(x * x, x)
        assert J.shape == (3, 3)
        np.testing.assert_allclose(np.asarray(J[:].numpy()),
                                   np.diag([2.0, 4.0, 6.0]), rtol=1e-6)

    def test_full_matrix_vs_jax(self):
        import jax
        import jax.numpy as jnp
        W = np.random.RandomState(0).randn(3, 4).astype(np.float32)
        x = _x(np.random.RandomState(1).randn(4))
        y = paddle.matmul(paddle.to_tensor(W), x).tanh()
        J = paddle.autograd.jacobian(y, x)
        ref = jax.jacrev(lambda v: jnp.tanh(W @ v))(jnp.asarray(
            x.numpy()))
        np.testing.assert_allclose(np.asarray(J[:].numpy()),
                                   np.asarray(ref), rtol=1e-5, atol=1e-6)

    def test_batched(self):
        xb = _x(np.random.RandomState(2).randn(5, 3))
        yb = xb * xb
        J = paddle.autograd.jacobian(yb, xb, batch_axis=0)
        assert J.shape == (5, 3, 3)
        full = np.asarray(J[:].numpy())
        for b in range(5):
            np.testing.assert_allclose(
                full[b], np.diag(2 * np.asarray(xb.numpy())[b]),
                rtol=1e-5)

    def test_tuple_nesting(self):
        x = _x([1.0, 2.0])
        z = _x([3.0])
        Js = paddle.autograd.jacobian(x * x, (x, z))
        assert isinstance(Js, tuple) and len(Js) == 2
        np.testing.assert_allclose(np.asarray(Js[1][:].numpy()), 0.0)


class TestHessian:
    def test_cubic(self):
        x = _x([1.0, 2.0])
        s = (x * x * x).sum()
        H = paddle.autograd.hessian(s, x)
        np.testing.assert_allclose(np.asarray(H[:].numpy()),
                                   np.diag([6.0, 12.0]), rtol=1e-6)

    def test_cross_terms_vs_jax(self):
        import jax
        import jax.numpy as jnp
        x = _x([0.5, -1.0, 2.0])
        s = (x[0] * x[1] * x[2] + (x * x).sum())
        H = paddle.autograd.hessian(s, x)
        ref = jax.hessian(
            lambda v: v[0] * v[1] * v[2] + (v * v).sum())(
                jnp.asarray(x.numpy()))
        np.testing.assert_allclose(np.asarray(H[:].numpy()),
                                   np.asarray(ref), rtol=1e-5, atol=1e-6)

    def test_nonscalar_rejected(self):
        x = _x([1.0, 2.0])
        with pytest.raises(ValueError):
            paddle.autograd.hessian(x * x, x)


class TestVjpJvp:
    def test_vjp(self):
        xs = paddle.to_tensor(np.array([1.0, 3.0], np.float32))
        v = paddle.to_tensor(np.array([2.0, 0.5], np.float32))
        ys, g = iag.vjp(lambda a: a * a, xs, v)
        np.testing.assert_allclose(ys.numpy(), [1.0, 9.0], rtol=1e-6)
        np.testing.assert_allclose(g.numpy(), [4.0, 3.0], rtol=1e-6)

    def test_jvp_equals_forward_mode(self):
        import jax
        import jax.numpy as jnp
        xs = paddle.to_tensor(np.array([0.3, -1.2, 2.0], np.float32))
        v = paddle.to_tensor(np.array([1.0, 0.5, -2.0], np.float32))

        def f(a):
            return (a * a).sum() * a  # non-diagonal jacobian

        _, jv = iag.jvp(f, xs, v)
        _, ref = jax.jvp(
            lambda a: (a * a).sum() * a,
            (jnp.asarray(xs.numpy()),), (jnp.asarray(v.numpy()),))
        np.testing.assert_allclose(jv.numpy(), np.asarray(ref),
                                   rtol=1e-5, atol=1e-6)

    def test_incubate_jacobian_class_func_first(self):
        # reference incubate signature: Jacobian(func, xs, is_batched)
        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        J = iag.Jacobian(lambda a: a * a, x)
        np.testing.assert_allclose(np.asarray(J[:].numpy()),
                                   np.diag([2.0, 4.0]), rtol=1e-6)
        assert J.shape == (2, 2)

    def test_incubate_hessian_class_multi_input_flattens(self):
        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        z = paddle.to_tensor(np.array([3.0], np.float32))

        def f(a, b):
            return (a * a).sum() + a.sum() * b.sum()

        H = iag.Hessian(f, (x, z))
        assert H.shape == (3, 3)
        full = np.asarray(H[:].numpy())
        expect = np.array([[2.0, 0.0, 1.0],
                           [0.0, 2.0, 1.0],
                           [1.0, 1.0, 0.0]], np.float32)
        np.testing.assert_allclose(full, expect, rtol=1e-5, atol=1e-6)

    def test_vjp_unused_input_zero_filled_and_flags_restored(self):
        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        z = paddle.to_tensor(np.array([5.0], np.float32))
        assert x.stop_gradient and z.stop_gradient  # frozen going in
        ys, grads = iag.vjp(lambda a, b: a * a, (x, z),
                            paddle.to_tensor(np.ones(2, np.float32)))
        np.testing.assert_allclose(grads[0].numpy(), [2.0, 4.0])
        np.testing.assert_allclose(grads[1].numpy(), [0.0])  # not None
        assert x.stop_gradient and z.stop_gradient  # restored

    def test_hessian_tuple_xs_cross_blocks(self):
        x = _x([1.0, 2.0])
        z = _x([3.0])
        s = (x * x).sum() + x.sum() * z.sum()
        H = paddle.autograd.hessian(s, (x, z))
        assert isinstance(H, tuple) and isinstance(H[0], tuple)
        np.testing.assert_allclose(np.asarray(H[0][0][:].numpy()),
                                   np.diag([2.0, 2.0]), rtol=1e-6)
        # the cross-partial block d2s/dx dz = [1, 1]
        np.testing.assert_allclose(
            np.asarray(H[0][1][:].numpy()).reshape(-1), [1.0, 1.0],
            rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(H[1][0][:].numpy()).reshape(-1), [1.0, 1.0],
            rtol=1e-6)

    def test_single_row_getitem_lazy(self):
        x = _x([1.0, 2.0, 3.0])
        J = paddle.autograd.jacobian(x * x, x)
        row = J[1]
        np.testing.assert_allclose(row.numpy(), [0.0, 4.0, 0.0],
                                   rtol=1e-6)
        assert len(J._rows) == 1  # only the accessed row was computed


class TestDlpack:
    def test_roundtrip_numpy(self):
        t = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
        back = dlpack.from_dlpack(np.asarray(t.numpy()))
        np.testing.assert_allclose(back.numpy(), t.numpy())

    def test_torch_interop(self):
        torch = pytest.importorskip("torch")
        t = paddle.to_tensor(np.arange(4, dtype=np.float32))
        tt = torch.utils.dlpack.from_dlpack(dlpack.to_dlpack(t))
        np.testing.assert_allclose(tt.numpy(), t.numpy())
        back = dlpack.from_dlpack(torch.arange(4).float())
        np.testing.assert_allclose(back.numpy(), [0, 1, 2, 3])

    def test_type_error(self):
        with pytest.raises(TypeError):
            dlpack.to_dlpack(np.zeros(3))


class TestHub:
    @pytest.fixture()
    def repo(self, tmp_path):
        (tmp_path / "hubconf.py").write_text(
            "dependencies = ['numpy']\n"
            "def lenet(**kw):\n"
            "    '''A LeNet entrypoint.'''\n"
            "    import paddle_tpu as p\n"
            "    return p.vision.models.LeNet(**kw)\n"
            "def _private():\n    pass\n")
        return str(tmp_path)

    def test_list_help_load(self, repo):
        assert paddle.hub.list(repo, source="local") == ["lenet"]
        assert "LeNet" in paddle.hub.help(repo, "lenet", source="local")
        m = paddle.hub.load(repo, "lenet", source="local")
        assert type(m).__name__ == "LeNet"

    def test_remote_sources_gated(self, repo):
        with pytest.raises(RuntimeError, match="egress"):
            paddle.hub.load("owner/repo", "m", source="github")
        with pytest.raises(ValueError, match="Unknown source"):
            paddle.hub.list(repo, source="ftp")

    def test_missing_entry_and_dependency(self, repo, tmp_path):
        with pytest.raises(RuntimeError, match="Cannot find callable"):
            paddle.hub.load(repo, "nope", source="local")
        bad = tmp_path / "bad"
        bad.mkdir()
        (bad / "hubconf.py").write_text(
            "dependencies = ['definitely_not_a_module_xyz']\n")
        with pytest.raises(RuntimeError, match="Missing dependencies"):
            paddle.hub.list(str(bad), source="local")
