"""Program verifier (static/verifier.py) — ISSUE 15.

Contracts under test:

* the fixture corpus: every must-flag program under
  ``tests/fixtures/verifier/`` produces exactly its EXPECT codes, and
  every must-not-flag program produces ZERO findings;
* ``FLAGS_verify_programs=strict`` raises ``ProgramVerifierError``
  BEFORE compile — on a branch-mismatched-collective program and on a
  donated-then-host-read program — with the op and source location in
  the message;
* the wiring: all three compile paths (``static.Program`` / Executor,
  ``to_static``, SOT segment flush) run the verifier behind the flag;
* the framework's own traced ladder programs verify clean
  (``python -m tools.tpulint --programs``), including the fusion
  pass's rewritten plans;
* ``tools.tpulint --diff`` lints only changed files.
"""
import importlib.util
import os
import subprocess
import sys
import warnings

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import paddle_tpu as paddle  # noqa: E402
from paddle_tpu import jit, nn, static  # noqa: E402
from paddle_tpu.static import verifier  # noqa: E402

FIXTURES = os.path.join(REPO, "tests", "fixtures", "verifier")


def _load_fixture(name):
    spec = importlib.util.spec_from_file_location(
        f"verifier_fixture_{name}", os.path.join(FIXTURES, name))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


_FIXTURE_FILES = sorted(
    f for f in os.listdir(FIXTURES)
    if f.endswith(".py") and f != "__init__.py")


@pytest.fixture(autouse=True)
def _default_flag():
    prev = paddle.get_flags(["FLAGS_verify_programs"])[
        "FLAGS_verify_programs"]
    yield
    paddle.set_flags({"FLAGS_verify_programs": prev})


# ==========================================================================
# fixture corpus
# ==========================================================================
class TestFixtureCorpus:
    def test_corpus_is_nonempty_and_covers_every_pass(self):
        expected = set()
        for f in _FIXTURE_FILES:
            expected.update(_load_fixture(f).EXPECT)
        # one must-flag fixture per pass family at minimum
        assert {"TPU401", "TPU402", "TPU403", "TPU404",      # collective
                "TPU451", "TPU452", "TPU453", "TPU454",      # cross-rank
                "TPU501", "TPU502", "TPU503",                # sharding
                "TPU601",                                    # donation
                "TPU700", "TPU701", "TPU702", "TPU703",
                "TPU704", "TPU705",                          # contract
                "TPU751", "TPU752", "TPU753", "TPU754",      # alias
                "TPU801", "TPU802", "TPU803",                # stages
                "TPU901", "TPU902"} <= expected              # memory
        assert any(not _load_fixture(f).EXPECT
                   for f in _FIXTURE_FILES), "no must-not-flag fixtures"

    @pytest.mark.parametrize("name", _FIXTURE_FILES)
    def test_fixture(self, name):
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")   # spmd fallback chatter
            mod = _load_fixture(name)
            report = mod.build()
        assert sorted(set(report.codes())) == sorted(set(mod.EXPECT)), \
            report.render()

    def test_every_code_documented(self):
        for f in _FIXTURE_FILES:
            for code in _load_fixture(f).EXPECT:
                assert code in verifier.CODES


class TestCollectiveDetails:
    def test_group_axes_mismatch_synthetic(self):
        """Arms whose collectives differ in GROUP/AXES identity (not
        just shape) are a TPU403 — checked over a hand-built branch
        meta, the same structure the control-flow lowerings attach."""
        meta = {"construct": "conditional_block", "branches": [
            [{"name": "all_reduce",
              "attrs": {"group": 1, "axes": ("data",)}, "shape": (4,)}],
            [{"name": "all_reduce",
              "attrs": {"group": 2, "axes": ("tp",)}, "shape": (4,)}],
        ]}
        rec = verifier.Record(
            "conditional_block", in_ids=[1], out_ids=[2],
            in_shapes=[()], out_shapes=[(4,)],
            attrs={"_verifier_branches": meta})
        rep = verifier.check([rec], fetch_ids=[2], in_specs={1: None})
        assert rep.codes() == ["TPU403"]

    def test_tensor_scatter_is_not_a_collective(self):
        """The plain TENSOR op ``scatter`` (indexing) shares a name
        with the distributed primitive; only entries stamped by the
        collective seam (``group`` attr) count — a greedy-decode loop
        writing its output buffer must not warn TPU401."""
        import paddle_tpu.ops as ops
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [4], "float32")
            buf = paddle.to_tensor(np.zeros(4, np.float32))
            i0 = paddle.to_tensor(0)

            def keep(i, b):
                return i < 3

            def body(i, b):
                idx = paddle.to_tensor(np.array([0], np.int64))
                upd = nn.functional.relu(x[:1])
                return [i + 1, ops.scatter(b, idx, upd)]

            _i, out = static.nn.while_loop(keep, body, [i0, buf])
        report = verifier.check(prog, fetch_ids=[id(out)])
        assert "TPU401" not in report.codes(), report.render()

    def test_reduce_op_mismatch_is_content_divergence(self):
        """SUM in one arm, MAX in the other: same name/group/shape but
        genuinely different wire content — TPU403."""
        meta = {"construct": "conditional_block", "branches": [
            [{"name": "all_reduce", "shape": (4,),
              "attrs": {"group": 0, "axes": None, "reduce": "sum"}}],
            [{"name": "all_reduce", "shape": (4,),
              "attrs": {"group": 0, "axes": None, "reduce": "max"}}],
        ]}
        rec = verifier.Record(
            "conditional_block", in_ids=[1], out_ids=[2],
            in_shapes=[()], out_shapes=[(4,)],
            attrs={"_verifier_branches": meta})
        rep = verifier.check([rec], fetch_ids=[2], in_specs={1: None})
        assert rep.codes() == ["TPU403"]

    def test_nested_construct_recursed(self):
        """A mismatched cond NESTED inside an arm is still found."""
        inner = {"construct": "conditional_block", "branches": [
            [{"name": "all_reduce", "attrs": {"group": 0, "axes": None},
              "shape": (4,)}], [],
        ]}
        meta = {"construct": "conditional_block", "branches": [
            [{"name": "multiply", "attrs": {}, "shape": (4,),
              "branches": inner}],
            [{"name": "multiply", "attrs": {}, "shape": (4,),
              "branches": inner}],
        ]}
        rec = verifier.Record(
            "conditional_block", in_ids=[1], out_ids=[2],
            in_shapes=[()], out_shapes=[(4,)],
            attrs={"_verifier_branches": meta})
        rep = verifier.check([rec], fetch_ids=[2], in_specs={1: None})
        assert "TPU402" in rep.codes()


# ==========================================================================
# strict mode: raises BEFORE compile, naming op + source line
# ==========================================================================
class TestStrictMode:
    def test_branch_mismatch_message_names_op_and_line(self):
        mod = _load_fixture("flag_branch_collective_mismatch.py")
        report = mod.build()
        with pytest.raises(verifier.ProgramVerifierError) as ei:
            verifier.enforce(report, "strict")
        msg = str(ei.value)
        assert "TPU402" in msg
        assert "op#" in msg                       # op id
        assert "conditional_block" in msg         # op name
        # source provenance: file.py:line of the recording site
        assert "flag_branch_collective_mismatch.py:" in msg

    def test_warn_mode_warns_instead(self):
        mod = _load_fixture("flag_branch_collective_mismatch.py")
        report = mod.build()
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            verifier.enforce(report, "warn")
        assert any(issubclass(x.category,
                              verifier.ProgramVerifierWarning)
                   for x in w)

    def test_warn_severity_never_raises_strict(self):
        # TPU401 is warn-severity: strict reports it but does not raise
        mod = _load_fixture("flag_while_collective.py")
        report = mod.build()
        assert report.codes() == ["TPU401"]
        with warnings.catch_warnings(record=True):
            warnings.simplefilter("always")
            verifier.enforce(report, "strict")    # no raise

    def test_to_static_strict_raises_before_compile(self):
        """The acceptance drill: a branch-mismatched-collective cond
        inside a to_static function raises the framework's error at
        END OF TRACE — before lowering/XLA compile — naming the op and
        the user source line."""
        import paddle_tpu.distributed as dist
        paddle.set_flags({"FLAGS_verify_programs": "strict"})
        x = paddle.to_tensor(np.ones((4, 8), np.float32))

        def bad(inp):
            def t():
                return dist.all_reduce(inp * 2.0)

            def f():
                return inp * 3.0

            return static.nn.cond(inp.sum() > 0, t, f)

        fn = jit.to_static(bad)
        with pytest.raises(verifier.ProgramVerifierError) as ei:
            fn(x)
        msg = str(ei.value)
        assert "TPU402" in msg and "conditional_block" in msg
        assert "test_program_verifier.py:" in msg

    def test_to_static_donated_host_read_strict(self):
        """Donated-then-host-read: the read breaks the trace; strict
        raises the VERIFIER's error (naming param + site) instead of
        silently falling back to SOT and hitting the stale buffer at
        runtime."""
        paddle.set_flags({"FLAGS_verify_programs": "strict"})

        class M(nn.Layer):
            def __init__(self):
                super().__init__()
                self.lin = nn.Linear(8, 8)

            def step(self, inp):
                out = self.lin(inp).sum()
                _ = self.lin.weight.numpy()       # stale after donation
                return out

        m = M()
        x = paddle.to_tensor(np.ones((4, 8), np.float32))
        fn = jit.to_static(m.step, full_graph=False, donate=True)
        with pytest.raises(verifier.ProgramVerifierError) as ei:
            fn(x)
        msg = str(ei.value)
        assert "TPU601" in msg and "Tensor.numpy()" in msg
        assert "test_program_verifier.py:" in msg

    def test_off_mode_disables_everything(self):
        paddle.set_flags({"FLAGS_verify_programs": "off"})
        assert verifier.mode() == "off"
        mod = _load_fixture("flag_branch_collective_mismatch.py")
        report = mod.build()
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            verifier.enforce(report)              # flag-driven: no-op
        assert not [x for x in w
                    if issubclass(x.category,
                                  verifier.ProgramVerifierWarning)]


# ==========================================================================
# compile-path wiring
# ==========================================================================
class TestWiring:
    def test_program_executor_strict_raises_before_compile(self):
        import paddle_tpu.distributed as dist
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [4], "float32")

            def t():
                return dist.all_reduce(x * 2.0)

            def f():
                return x * 3.0

            out = static.nn.cond(paddle.to_tensor(True), t, f)
        paddle.set_flags({"FLAGS_verify_programs": "strict"})
        exe = static.Executor()
        with pytest.raises(verifier.ProgramVerifierError):
            exe.run(prog, feed={"x": np.ones(4, np.float32)},
                    fetch_list=[out])

    def test_clean_to_static_produces_no_warnings(self):
        paddle.set_flags({"FLAGS_verify_programs": "warn"})
        lin = nn.Linear(8, 8)
        fn = jit.to_static(lambda a: (a @ a.t()).sum())
        x = paddle.to_tensor(np.ones((4, 8), np.float32))
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            fn(x)
        assert not [x for x in w
                    if issubclass(x.category,
                                  verifier.ProgramVerifierWarning)]

    def test_sot_segments_verified_on_flush(self):
        """SOT path: the segment node graph rides the same verifier.
        A clean function flushes without findings; the verification
        happens only on a segment-cache MISS."""
        paddle.set_flags({"FLAGS_verify_programs": "warn"})

        def broken(a):
            h = a * 2.0
            if float(h.sum()) > 0:        # graph break -> SOT segments
                h = h + 1.0
            return h.sum()

        fn = jit.to_static(broken, full_graph=False)
        x = paddle.to_tensor(np.ones((4, 4), np.float32))
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            out = fn(x)
        assert float(out) == pytest.approx(48.0)
        assert not [x for x in w
                    if issubclass(x.category,
                                  verifier.ProgramVerifierWarning)]

    def test_fused_plan_verifies_clean(self):
        """Fused ops must verify clean: the rewritten plan's FusedSteps
        replay like _OpRecords and carry the anchor's loc."""
        from paddle_tpu.compile import fusion
        lin = nn.Linear(16, 16)
        norm = nn.LayerNorm(16)
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [4, 16], "float32")
            h = nn.functional.gelu(lin(norm(x)))
        fetch = [id(h)]
        plan, stats = fusion.fuse_program_ops(
            prog.global_block().ops, fetch)
        assert stats["rewritten"], "fusion matched nothing"
        fused = [s for s in plan if getattr(s, "pattern", "")]
        assert fused and fused[0].loc          # provenance carried
        report = verifier.check(plan, fetch_ids=fetch)
        assert report.codes() == [], report.render()

    def test_record_loc_provenance(self):
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [4], "float32")
            y = x * 2.0
        op = prog.global_block().ops[-1]
        assert op.loc.startswith("test_program_verifier.py:")
        assert op.in_dtypes[0] == "float32"
        assert op.out_dtypes == ("float32",)


# ==========================================================================
# framework programs stay verifier-clean
# ==========================================================================
class TestFrameworkClean:
    def test_ladder_programs_clean(self):
        from tools.tpulint import program_check
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            for label, thunk in program_check.build_programs():
                report = thunk()
                assert report.codes() == [], \
                    f"{label}: {report.render()}"


# ==========================================================================
# tpulint CLI: --programs and --diff
# ==========================================================================
class TestCli:
    def test_diff_mode_no_changes_is_clean(self):
        out = subprocess.run(
            [sys.executable, "-m", "tools.tpulint", "--diff", "HEAD",
             "--no-registry", os.path.join(REPO, "paddle_tpu")],
            cwd=REPO, capture_output=True, text=True)
        # HEAD vs worktree may or may not have changes; either way the
        # mode must run and gate only the changed files
        assert out.returncode in (0, 1), out.stderr
        assert "tpulint" in out.stdout

    def test_diff_paths_filters_to_changed(self):
        from tools.tpulint.cli import diff_paths
        # rev == HEAD~0: identical tree -> subset of working changes
        paths = diff_paths("HEAD", [os.path.join(REPO, "paddle_tpu")])
        for p in paths:
            assert p.endswith(".py") and os.path.isfile(p)

    def test_list_codes_includes_verifier_families(self):
        out = subprocess.run(
            [sys.executable, "-m", "tools.tpulint", "--list-codes"],
            cwd=REPO, capture_output=True, text=True)
        assert out.returncode == 0
        for code in ("TPU402", "TPU501", "TPU601", "TPU700"):
            assert code in out.stdout
