"""StringTensor + strings kernels + FasterTokenizer.

Reference contracts: paddle/phi/core/string_tensor.h (container),
paddle/phi/kernels/strings/ (empty/copy/lower/upper with ASCII vs UTF-8
converters), paddle/fluid/operators/string/faster_tokenizer_op.{h,cc}
(BasicTokenizer/WordPieceTokenizer/BertTokenizer and the batch op).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import strings
from paddle_tpu.core.string_tensor import StringTensor
from paddle_tpu.incubate.nn import BertTokenizer, FasterTokenizer


# ------------------------------------------------------------- container
def test_container_meta_and_indexing():
    st = strings.to_string_tensor([["ab", "cd", "ef"], ["gh", "ij", "kl"]])
    assert st.shape == [2, 3]
    assert st.numel() == 6
    assert st.ndim == 2
    assert st[0, 1] == "cd"
    row = st[1]
    assert isinstance(row, StringTensor)
    assert row.tolist() == ["gh", "ij", "kl"]
    st[0, 0] = "zz"
    assert st.tolist()[0][0] == "zz"
    assert st.place == "cpu"  # strings live on host, like the reference


def test_numpy_bytes_array_decodes_and_hash():
    st = strings.to_string_tensor(np.array([b"ABC", b"def"]))
    assert st.tolist() == ["ABC", "def"]
    assert strings.lower(st).tolist() == ["abc", "def"]  # str, not bytes
    # identity hash: usable as a dict key despite value __eq__
    assert {st: 1}[st] == 1


def test_container_scalar_bytes_reshape():
    st = strings.to_string_tensor("hello")
    assert st.shape == []
    assert st.numel() == 1
    stb = strings.to_string_tensor([b"abc", "def"])
    assert stb.tolist() == ["abc", "def"]
    r = stb.reshape([2, 1])
    assert r.shape == [2, 1]


def test_scalar_tensor_edges():
    st = strings.to_string_tensor("Hello")
    # 0-d case kernels re-box the scalar
    low = strings.lower(st)
    assert low.shape == [] and low.tolist() == "hello"
    # like/empty preserve the scalar shape (numel 1, not 0)
    like = strings.empty_like(st)
    assert like.shape == [] and like.numel() == 1
    # len/iter reject 0-d, matching dense-tensor semantics
    with pytest.raises(TypeError):
        len(st)
    with pytest.raises(TypeError):
        list(st)


def test_ragged_nest_rejected():
    with pytest.raises(ValueError):
        strings.to_string_tensor([["a", "b"], ["c"]])


def test_framework_level_export():
    assert paddle.framework.StringTensor is StringTensor
    st = paddle.framework.to_string_tensor(["x"])
    assert st.tolist() == ["x"]


# --------------------------------------------------------------- kernels
def test_empty_and_copy():
    e = strings.empty([2, 2])
    assert e.tolist() == [["", ""], ["", ""]]
    src = strings.to_string_tensor(["a", "b"])
    c = strings.copy(src)
    src[0] = "changed"
    assert c.tolist() == ["a", "b"]  # deep copy of the buffer
    dst = strings.empty([2])
    dst.copy_(src)
    assert dst.tolist() == ["changed", "b"]
    assert strings.empty_like(src).shape == src.shape


def test_lower_upper_ascii_mode():
    # ASCII mode touches only A-Z/a-z, exactly AsciiToLower/AsciiToUpper
    st = strings.to_string_tensor(["Hello World!", "ÀBÇ déf", "MiXeD123"])
    low = strings.lower(st)  # use_utf8_encoding=False
    up = strings.upper(st)
    # non-ASCII cased letters (À, Ç, é) pass through untouched in ascii mode
    assert low.tolist() == ["hello world!", "ÀbÇ déf", "mixed123"]
    assert up.tolist() == ["HELLO WORLD!", "ÀBÇ DéF", "MIXED123"]


def test_lower_upper_utf8_mode():
    st = strings.to_string_tensor(["Hello", "ÀBÇ", "ΣΟΦΌΣ", "straße"])
    low = st.lower(use_utf8_encoding=True)
    up = st.upper(use_utf8_encoding=True)
    assert low.tolist() == ["hello", "àbç", "σοφόσ", "straße"]
    # 1:1 map: ß→SS is a multi-char expansion, stays ß (uint16 cases_map)
    assert up.tolist() == ["HELLO", "ÀBÇ", "ΣΟΦΌΣ", "STRAßE"]


def test_case_kernels_preserve_shape_and_empty():
    st = strings.to_string_tensor([["Aa", "Bb"], ["Cc", ""]])
    low = strings.lower(st)
    assert low.shape == [2, 2]
    assert low.tolist() == [["aa", "bb"], ["cc", ""]]
    assert strings.lower(strings.empty([0])).numel() == 0


# ---------------------------------------------------------- tokenization
VOCAB = {w: i for i, w in enumerate(
    ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]",
     "the", "quick", "brown", "fox", "jump", "##ed", "##s", "over",
     "lazy", "dog", "un", "##aff", "##able", "!", ",", "你", "好"])}


def test_basic_tokenizer_splits():
    from paddle_tpu.incubate.nn.faster_tokenizer import BasicTokenizer
    bt = BasicTokenizer(do_lower_case=True)
    assert bt.tokenize("The Quick, brown FOX!") == [
        "the", "quick", ",", "brown", "fox", "!"]
    # CJK chars become single tokens; control chars dropped
    assert bt.tokenize("你好\x00world") == ["你", "好", "world"]
    assert bt.tokenize("  \t\n ") == []


def test_wordpiece_greedy_longest_match():
    from paddle_tpu.incubate.nn.faster_tokenizer import WordPieceTokenizer
    wp = WordPieceTokenizer(VOCAB)
    assert wp.tokenize("jumped") == [VOCAB["jump"], VOCAB["##ed"]]
    assert wp.tokenize("unaffable") == [
        VOCAB["un"], VOCAB["##aff"], VOCAB["##able"]]
    # unknown mid-piece → whole word is UNK (reference: return after UNK)
    assert wp.tokenize("jumpxq") == [VOCAB["[UNK]"]]
    # over-long word → UNK
    assert wp.tokenize("a" * 200) == [VOCAB["[UNK]"]]


def test_bert_encode_pair_and_truncate():
    tok = BertTokenizer(VOCAB, do_lower_case=True)
    enc = tok.encode("the quick fox", "the lazy dog")
    ids = enc["input_ids"]
    assert ids[0] == VOCAB["[CLS]"]
    assert ids.count(VOCAB["[SEP]"]) == 2
    assert enc["token_type_ids"] == [0] * 5 + [1] * 4
    # truncation: longest-first pops from the longer sequence
    enc2 = tok.encode("the quick brown fox", "dog", max_seq_len=7)
    assert len(enc2["input_ids"]) == 7
    assert enc2["input_ids"][-1] == VOCAB["[SEP]"]
    # pad_to_max right-pads with pad id
    enc3 = tok.encode("fox", max_seq_len=8, pad_to_max_seq_len=True)
    assert len(enc3["input_ids"]) == 8
    assert enc3["input_ids"][-1] == VOCAB["[PAD]"]


def test_encode_max_seq_len_smaller_than_specials():
    tok = BertTokenizer(VOCAB, do_lower_case=True)
    # truncation would need to remove more than the content tokens; must
    # reject (None), not crash on an empty pop
    assert tok.encode("fox", max_seq_len=1) is None
    enc = tok.encode("quick brown fox", "lazy dog", max_seq_len=3)
    assert enc is None or len(enc["input_ids"]) <= 3


def test_faster_tokenizer_layer_batch():
    ft = FasterTokenizer(VOCAB, do_lower_case=True)
    st = strings.to_string_tensor(["the quick fox", "jumped over the lazy dog !"])
    input_ids, token_type_ids = ft(st)
    assert paddle.is_tensor(input_ids) and paddle.is_tensor(token_type_ids)
    ids = np.asarray(input_ids.numpy())
    assert ids.dtype == np.int32
    assert ids.shape == token_type_ids.numpy().shape
    # row 0 is shorter → right-padded with [PAD]
    assert ids[0, -1] == VOCAB["[PAD]"]
    assert ids[0, 0] == VOCAB["[CLS]"]
    # row 1: jumped → jump ##ed
    row1 = list(ids[1])
    assert VOCAB["jump"] in row1 and VOCAB["##ed"] in row1


def test_faster_tokenizer_pair_batch_mismatch():
    ft = FasterTokenizer(VOCAB)
    with pytest.raises(ValueError):
        ft(["a", "b"], ["only-one"])


def test_tokenizer_feeds_jitted_model():
    """The handoff point: host StringTensor → device ids → jitted embed."""
    import jax
    import jax.numpy as jnp

    ft = FasterTokenizer(VOCAB, do_lower_case=True)
    input_ids, _ = ft(["the quick brown fox", "the lazy dog"])
    table = jnp.arange(len(VOCAB) * 4, dtype=jnp.float32).reshape(-1, 4)

    @jax.jit
    def embed(ids):
        return table[ids].sum(axis=1)

    out = embed(input_ids._value if hasattr(input_ids, "_value")
                else np.asarray(input_ids.numpy()))
    assert out.shape == (2, 4)


def test_load_vocab(tmp_path):
    from paddle_tpu.incubate.nn import load_vocab
    p = tmp_path / "vocab.txt"
    p.write_text("[PAD]\n[UNK]\nhello\nworld\n", encoding="utf-8")
    v = load_vocab(str(p))
    assert v == {"[PAD]": 0, "[UNK]": 1, "hello": 2, "world": 3}
