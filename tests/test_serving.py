"""Continuous-batching serving engine tests.

Reference contract: the block_multi_head_attention serving-op family +
fused_multi_transformer cached decoding — paged-cache generation must
reproduce the model's own greedy decode exactly, across mixed prompt
lengths, admission waves, and block-boundary growth.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference import BlockManager, LlamaPagedEngine
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM


_MODEL_CACHE = {}


def _tiny_model():
    # one shared instance: weights are seeded identically every call and
    # no test mutates them, while engines over one model share compiled
    # tick programs (serving._PAGED_JIT_CACHE) — this suite is decode
    # parity, not compile timing
    if "m" not in _MODEL_CACHE:
        paddle.seed(7)
        cfg = LlamaConfig(vocab_size=97, hidden_size=64,
                          intermediate_size=128, num_layers=2, num_heads=4,
                          max_seq_len=128, use_flash_attention=False)
        _MODEL_CACHE["m"] = LlamaForCausalLM(cfg)
    return _MODEL_CACHE["m"]


def _ref_greedy(model, prompt, n_new):
    ids = paddle.to_tensor(np.asarray([prompt], np.int64))
    out = model.generate(ids, max_new_tokens=n_new, temperature=0.0,
                         use_cache=False)
    return [int(t) for t in np.asarray(out.numpy())[0][len(prompt):]]


class TestBlockManager:
    def test_allocate_release(self):
        bm = BlockManager(5)          # blocks 1..4 usable (0 reserved)
        a = bm.allocate(3)
        assert 0 not in a and len(set(a)) == 3
        assert bm.available == 1
        with pytest.raises(MemoryError):
            bm.allocate(2)
        bm.release(a)
        assert bm.available == 4


class TestPagedEngineParity:
    def test_single_request_matches_model_generate(self):
        model = _tiny_model()
        rng = np.random.RandomState(0)
        prompt = [int(t) for t in rng.randint(1, 97, size=11)]
        eng = LlamaPagedEngine(model, max_batch=2, block_size=4,
                               num_blocks=32, max_blocks_per_seq=16)
        rid = eng.add_request(prompt, max_new_tokens=8)
        out = eng.run_to_completion()
        assert out[rid] == _ref_greedy(model, prompt, 8)

    @pytest.mark.slow
    # slow-marked (~15s, 870s tier-1 budget): paged-vs-dense parity
    # stays in tier-1 via the single-request llama case above and the
    # GPT full-recompute greedy case below; the mixed-length staggered
    # matrix runs in the full suite
    def test_mixed_lengths_and_staggered_admission(self):
        model = _tiny_model()
        rng = np.random.RandomState(1)
        prompts = [[int(t) for t in rng.randint(1, 97, size=n)]
                   for n in (3, 9, 17, 5)]
        eng = LlamaPagedEngine(model, max_batch=2, block_size=4,
                               num_blocks=64, max_blocks_per_seq=16)
        rids = [eng.add_request(p, max_new_tokens=6) for p in prompts]
        out = eng.run_to_completion()
        # only 2 slots: requests 3/4 admitted after earlier ones finish
        for rid, p in zip(rids, prompts):
            assert out[rid] == _ref_greedy(model, p, 6), p

    def test_block_growth_across_boundaries(self):
        model = _tiny_model()
        rng = np.random.RandomState(2)
        prompt = [int(t) for t in rng.randint(1, 97, size=6)]
        # block_size 4: seq grows 6 -> 18, crossing several boundaries
        eng = LlamaPagedEngine(model, max_batch=1, block_size=4,
                               num_blocks=16, max_blocks_per_seq=8)
        rid = eng.add_request(prompt, max_new_tokens=12)
        out = eng.run_to_completion()
        assert out[rid] == _ref_greedy(model, prompt, 12)
        # all blocks released after completion
        assert eng.bm.available == 15

    def test_eos_stops_early(self):
        model = _tiny_model()
        prompt = [5, 9, 2]
        ref = _ref_greedy(model, prompt, 10)
        eos = ref[2]                  # force a stop at the 3rd token
        eng = LlamaPagedEngine(model, max_batch=1, block_size=4,
                               num_blocks=16, max_blocks_per_seq=8,
                               eos_id=eos)
        rid = eng.add_request(prompt, max_new_tokens=10)
        out = eng.run_to_completion()
        assert out[rid] == ref[:3]

    def test_preemption_under_memory_pressure(self):
        """The reviewer's livelock repro: two slots that both need a 3rd
        block with 0 free must not spin — the youngest request is
        preempted (recompute-style), the other finishes, and BOTH still
        produce exactly the model's greedy tokens."""
        model = _tiny_model()
        rng = np.random.RandomState(4)
        p1 = [int(t) for t in rng.randint(1, 97, size=4)]
        p2 = [int(t) for t in rng.randint(1, 97, size=4)]
        eng = LlamaPagedEngine(model, max_batch=2, block_size=4,
                               num_blocks=5, max_blocks_per_seq=4)
        r1 = eng.add_request(p1, max_new_tokens=6)
        r2 = eng.add_request(p2, max_new_tokens=6)
        out = eng.run_to_completion(max_ticks=200)
        assert out[r1] == _ref_greedy(model, p1, 6)
        assert out[r2] == _ref_greedy(model, p2, 6)
        assert eng.bm.available == 4          # everything released

    def test_never_fitting_request_fails_at_submit(self):
        """A request that can never fit this replica's geometry is a
        terminal FAILED status at submit time — nothing raises, no other
        request's results are at risk, and the engine keeps serving."""
        from paddle_tpu.inference import RequestStatus
        model = _tiny_model()
        eng = LlamaPagedEngine(model, max_batch=1, block_size=4,
                               num_blocks=4, max_blocks_per_seq=2)
        bad = eng.add_request(list(range(1, 30)), max_new_tokens=4)
        assert eng.request_status(bad) == RequestStatus.FAILED
        assert bad in eng.rejected and "blocks" in eng.rejected[bad]
        assert "blocks" in eng.outcomes[bad].detail
        # the rejected request never entered the queue
        assert not eng.queue
        rid = eng.add_request([1, 2, 3], max_new_tokens=2)
        out = eng.run_to_completion()
        assert len(out[rid]) == 2
        assert bad not in out

    def test_request_validation(self):
        model = _tiny_model()
        eng = LlamaPagedEngine(model, max_batch=1, block_size=4,
                               num_blocks=8, max_blocks_per_seq=4)
        with pytest.raises(ValueError, match="non-empty"):
            eng.add_request([])
        with pytest.raises(ValueError, match="max_new_tokens"):
            eng.add_request([1], max_new_tokens=0)


class TestSampling:
    def test_seeded_sampling_reproducible_and_greedy_unchanged(self):
        model = _tiny_model()
        rng = np.random.RandomState(8)
        prompt = [int(t) for t in rng.randint(1, 97, size=5)]

        def run(seed, temperature, top_p=0.9):
            eng = LlamaPagedEngine(model, max_batch=1, block_size=4,
                                   num_blocks=16, max_blocks_per_seq=8,
                                   seed=seed)
            rid = eng.add_request(prompt, max_new_tokens=8,
                                  temperature=temperature, top_p=top_p)
            return eng.run_to_completion()[rid]

        # greedy path ignores the seed entirely
        assert run(0, 0.0) == run(123, 0.0) == _ref_greedy(model, prompt, 8)
        # sampling is reproducible per seed, and seeds differ
        s1, s2, s3 = run(7, 1.0), run(7, 1.0), run(9, 1.0)
        assert s1 == s2
        assert any(a != b for a, b in zip(s1, s3)) or s1 != s3

    def test_top_p_validation(self):
        model = _tiny_model()
        eng = LlamaPagedEngine(model, max_batch=1, block_size=4,
                               num_blocks=8, max_blocks_per_seq=4)
        with pytest.raises(ValueError, match="top_p"):
            eng.add_request([1, 2], top_p=0.0)


class TestGPTPagedEngine:
    def test_gpt_matches_full_recompute_greedy(self):
        from paddle_tpu.inference import PagedEngine
        from paddle_tpu.models import GPTConfig, GPTForCausalLM
        paddle.seed(11)
        cfg = GPTConfig(vocab_size=83, hidden_size=64, num_layers=2,
                        num_heads=4, max_seq_len=64, dropout=0.0,
                        use_flash_attention=False)
        model = GPTForCausalLM(cfg)
        model.eval()
        rng = np.random.RandomState(5)
        prompt = [int(t) for t in rng.randint(1, 83, size=7)]

        # reference: full-recompute greedy loop through the model itself
        ids = list(prompt)
        ref = []
        for _ in range(6):
            logits = model(paddle.to_tensor(np.asarray([ids], np.int64)))
            nxt = int(np.argmax(np.asarray(logits.numpy())[0, -1]))
            ref.append(nxt)
            ids.append(nxt)

        eng = PagedEngine(model, max_batch=2, block_size=4,
                          num_blocks=32, max_blocks_per_seq=8)
        rid = eng.add_request(prompt, max_new_tokens=6)
        out = eng.run_to_completion()
        assert out[rid] == ref
