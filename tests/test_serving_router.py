"""Serving-tier drills: router fault handling, phase-split scheduling,
int8-KV / speculative parity, streaming, and sampling determinism.

Contract under test (ISSUE 13 / README "Serving tier"):

* the Router fronts R replicas keyed on the round-11 readiness probes —
  a replica DEGRADED mid-flight strands nothing (requests re-route with
  their paid-for tokens carried), an all-saturated tier sheds AT THE
  ROUTER (replicas never see the burst), a drain mid-stream terminates
  the stream with a terminal status and leaks zero KV blocks;
* ``kv_dtype="int8"`` and ``speculate="ngram"`` are parity-gated:
  greedy outputs identical to the baseline decode path;
* the phase-split scheduler interleaves chunked prefill with decode
  without changing tokens;
* sampled decoding is per-request deterministic: a preempt-then-resume
  run emits exactly the tokens of an unpreempted run under a fixed seed.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.fault import inject
from paddle_tpu.inference import (PagedEngine, ReplicaState, RequestStatus,
                                  ResilienceConfig)
from paddle_tpu.inference.resilience import TERMINAL_STATUSES
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.serving import (NgramProposer, Router, SchedulerConfig,
                                TokenStream)


@pytest.fixture(scope="module")
def model():
    # 1 layer on purpose: this suite compiles MANY distinct programs
    # (fp + int8 caches, chunk + decode + verify, reference forwards) —
    # every serving behavior under test is layer-count independent, and
    # test_serving.py keeps the 2-layer decode-parity coverage
    paddle.seed(7)
    cfg = LlamaConfig(vocab_size=97, hidden_size=48, intermediate_size=96,
                      num_layers=1, num_heads=4, max_seq_len=256,
                      use_flash_attention=False)
    return LlamaForCausalLM(cfg)


@pytest.fixture(autouse=True)
def _clean_faults():
    inject.disarm_all()
    yield
    inject.disarm_all()


def make_engine(model, *, max_batch=2, block_size=4, num_blocks=64,
                max_blocks_per_seq=16, res=None, **eng_kw):
    return PagedEngine(model, max_batch=max_batch, block_size=block_size,
                       num_blocks=num_blocks,
                       max_blocks_per_seq=max_blocks_per_seq,
                       resilience=res, **eng_kw)


def prompt(seed, n=5):
    rng = np.random.RandomState(seed)
    return [int(t) for t in rng.randint(1, 97, size=n)]


def ref_greedy(model, p, n_new):
    """Reference completion from a plain single-replica engine — the
    anchor for 'nothing lost / tokens identical' drills. (Engine-vs-
    model.generate parity is test_serving.py's job; reusing the engine
    here keeps every reference on the file's already-compiled tick
    programs instead of one full-recompute forward per length.)"""
    eng = make_engine(model)
    rid = eng.add_request(p, max_new_tokens=n_new)
    return eng.run_to_completion()[rid]


def assert_no_leaks(replicas):
    for rep in replicas:
        assert rep.bm.available == rep._total_usable, \
            f"{rep.lifecycle.name} leaked KV blocks"
        assert all(s is None for s in rep.slots)


# ------------------------------------------------------------ router drills
class TestRouterRouting:
    def test_balances_and_finishes_across_replicas(self, model):
        router = Router([make_engine(model) for _ in range(2)]).warmup()
        prompts = [prompt(i, n=4 + i) for i in range(6)]
        rids = [router.add_request(p, max_new_tokens=5) for p in prompts]
        router.run_to_completion()
        ocs = router.drain_outcomes()
        for rid, p in zip(rids, prompts):
            assert ocs[rid].status == RequestStatus.FINISHED
            assert ocs[rid].tokens == ref_greedy(model, p, 5)
        stats = router.stats()
        assert all(r["routed"] > 0 for r in stats["per_replica"])
        assert_no_leaks(router.replicas)

    def test_not_ready_replica_out_of_rotation(self, model):
        a, b = make_engine(model), make_engine(model)
        router = Router([a, b]).warmup()
        a.lifecycle.degrade("drill")
        rids = [router.add_request(prompt(i), max_new_tokens=3)
                for i in range(3)]
        router.run_to_completion()
        ocs = router.drain_outcomes()
        assert all(ocs[r].status == RequestStatus.FINISHED for r in rids)
        assert router.stats()["per_replica"][0]["routed"] == 0
        assert router.stats()["per_replica"][1]["routed"] == 3

    def test_degraded_mid_flight_reroutes_nothing_lost(self, model):
        """The headline drill: a replica tick-crashes with requests in
        flight; the router re-routes them (generated prefix carried) and
        the client-visible outcome is the SAME greedy completion."""
        router = Router([make_engine(model) for _ in range(2)]).warmup()
        p = prompt(3, n=6)
        rid = router.add_request(p, max_new_tokens=8)
        router.step()                       # admitted + first tokens
        rr = router._by_rid[rid]
        assert rr.replica_idx is not None
        victim = router.replicas[rr.replica_idx]
        with inject.armed("serving.crash_at_tick",
                          tick=victim._ticks + 1):
            router.run_to_completion()
        oc = router.drain_outcomes()[rid]
        assert oc.status == RequestStatus.FINISHED
        assert oc.tokens == ref_greedy(model, p, 8)
        assert victim.lifecycle.state == ReplicaState.DEGRADED
        stats = router.stats()
        assert sum(r["rerouted_away"] for r in stats["per_replica"]) >= 1
        assert_no_leaks(router.replicas)

    def test_stream_attached_after_reroute_replays_carried_tokens(
            self, model):
        """A stream opened (or read) after a re-route must replay the
        tokens generated on the failed replica — the hand-off is
        invisible in the stream, not a gap."""
        router = Router([make_engine(model) for _ in range(2)]).warmup()
        p = prompt(8, n=6)
        rid = router.add_request(p, max_new_tokens=8)
        router.step()                       # some tokens on replica A
        victim = router.replicas[router._by_rid[rid].replica_idx]
        with inject.armed("serving.crash_at_tick",
                          tick=victim._ticks + 1):
            router.step()                   # crash + re-route
        toks = list(router.stream(rid))     # attached AFTER the crash
        assert toks == ref_greedy(model, p, 8)

    def test_all_overloaded_sheds_at_router_not_in_replicas(self, model):
        """Saturate every replica's bounded queue, then burst: the burst
        becomes router-level SHED outcomes; replicas never see it (no
        replica-side sheds, queues never exceed their bound)."""
        reps = [make_engine(model, res=ResilienceConfig(max_queue=2))
                for _ in range(2)]
        router = Router(reps).warmup()
        # fill both admission queues to their bound (nothing ticks in
        # between, so 2 queued per replica saturates the tier)
        fill = [router.add_request(prompt(10 + i), max_new_tokens=4)
                for i in range(4)]
        routed_before = [r["routed"] for r in
                         router.stats()["per_replica"]]
        burst = [router.add_request(prompt(50 + i), max_new_tokens=4)
                 for i in range(5)]
        ocs = {rid: router.outcomes[rid] for rid in burst}
        assert all(oc.status == RequestStatus.SHED for oc in ocs.values())
        assert all("router" in oc.detail for oc in ocs.values())
        assert router.shed_at_router == 5
        # replicas never saw the burst: routed counters unchanged, and
        # no replica-side shed ever happened
        assert [r["routed"] for r in
                router.stats()["per_replica"]] == routed_before
        router.run_to_completion()
        ocs = router.drain_outcomes()
        for rid in fill:
            assert ocs[rid].status == RequestStatus.FINISHED
        for rep in reps:
            assert not any(
                oc.status == RequestStatus.SHED
                for oc in rep.outcomes.values())
        assert_no_leaks(reps)

    def test_drain_during_streaming_terminates_with_status(self, model):
        """Replica drained while a client streams from it: the stream
        ends (no hang, no raise) with a terminal status, and no replica
        leaks KV blocks."""
        reps = [make_engine(model) for _ in range(2)]
        router = Router(reps).warmup()
        p = prompt(4, n=6)
        rid = router.add_request(p, max_new_tokens=8)
        stream = router.stream(rid)
        first = next(stream)               # pumps until a token arrives
        serving_rep = router.replicas[router._by_rid[rid].replica_idx]
        serving_rep.drain()                # finishes in-flight decodes
        rest = list(stream)
        assert stream.status in TERMINAL_STATUSES
        assert stream.status == RequestStatus.FINISHED
        assert [first] + rest == ref_greedy(model, p, 8)
        assert serving_rep.lifecycle.state == ReplicaState.STOPPED
        assert_no_leaks(reps)

    def test_drain_before_admission_reroutes_queued_request(self, model):
        """A drain cancels queued requests 'their clients retry on
        another replica' — the router IS that client: the queued request
        re-routes and still finishes with the right tokens."""
        reps = [make_engine(model) for _ in range(2)]
        router = Router(reps).warmup()
        p1, p2, p3 = prompt(5), prompt(6), prompt(7)
        # aim all at replica 0 by degrading replica 1 momentarily
        reps[1].lifecycle.degrade("hold")
        r1 = router.add_request(p1, max_new_tokens=6)
        r2 = router.add_request(p2, max_new_tokens=6)
        r3 = router.add_request(p3, max_new_tokens=6)
        reps[1].recover()
        assert router._by_rid[r3].replica_idx == 0   # queued behind r1/r2
        reps[0].drain()          # r1/r2 finish, queued r3 CANCELLED
        router.run_to_completion()
        ocs = router.drain_outcomes()
        assert ocs[r1].status == RequestStatus.FINISHED
        assert ocs[r3].status == RequestStatus.FINISHED
        assert ocs[r3].tokens == ref_greedy(model, p3, 6)
        # the drained-before-admission request was re-routed
        assert router.stats()["per_replica"][0]["rerouted_away"] >= 1
        assert_no_leaks(reps)

    def test_router_drain_terminates_everything(self, model):
        router = Router([make_engine(model) for _ in range(2)]).warmup()
        rids = [router.add_request(prompt(20 + i), max_new_tokens=6)
                for i in range(5)]
        router.step()
        router.drain()
        ocs = router.drain_outcomes()
        for rid in rids:
            assert ocs[rid].status in TERMINAL_STATUSES
        assert_no_leaks(router.replicas)


# --------------------------------------------------- int8 / speculative
class TestQuantizedKVParity:
    def test_int8_greedy_identical_and_smaller(self, model):
        prompts = [prompt(i, n=n) for i, n in enumerate((11, 23, 5, 17))]
        base = make_engine(model)
        eng8 = make_engine(model, kv_dtype="int8")
        b_rids = [base.add_request(p, max_new_tokens=10) for p in prompts]
        q_rids = [eng8.add_request(p, max_new_tokens=10) for p in prompts]
        b_out = base.run_to_completion()
        q_out = eng8.run_to_completion()
        for br, qr in zip(b_rids, q_rids):
            assert q_out[qr] == b_out[br]
        # resident KV per token shrinks (payload int8 + fp32 scales
        # vs the model dtype pages): the resident-batch multiplier
        assert eng8.kv_bytes_per_token < base.kv_bytes_per_token
        assert eng8.health()["kv_dtype"] == "int8"

    def test_int8_survives_preemption_and_growth(self, model):
        # tight blocks: eviction + re-prefill exercise quantized rewrite
        p1, p2 = prompt(30, n=4), prompt(31, n=4)
        eng = make_engine(model, num_blocks=5, max_blocks_per_seq=4,
                          kv_dtype="int8")
        r1 = eng.add_request(p1, max_new_tokens=6)
        r2 = eng.add_request(p2, max_new_tokens=6)
        out = eng.run_to_completion(max_ticks=200)
        assert out[r1] == ref_greedy(model, p1, 6)
        assert out[r2] == ref_greedy(model, p2, 6)


class TestSpeculativeDecode:
    def test_ngram_proposer_finds_repeats(self):
        prop = NgramProposer(k=3, max_n=3)
        # trailing (7, 8) occurred earlier, followed by 9, 1, 2
        assert prop.propose([7, 8, 9, 1, 2, 7, 8]) == [9, 1, 2]
        assert prop.propose([1, 2, 3]) == []       # no repeat, no draft

    def test_spec_greedy_identical_with_acceptance(self, model):
        # repetitive prompts so the n-gram draft actually accepts
        prompts = [p * 3 for p in
                   (prompt(40, n=4), prompt(41, n=6), prompt(42, n=3))]
        base = make_engine(model)
        spec = make_engine(model, speculate="ngram", speculate_k=4)
        b_rids = [base.add_request(p, max_new_tokens=12) for p in prompts]
        s_rids = [spec.add_request(p, max_new_tokens=12) for p in prompts]
        b_out = base.run_to_completion()
        s_out = spec.run_to_completion()
        for br, sr in zip(b_rids, s_rids):
            assert s_out[sr] == b_out[br]
        assert spec.spec_proposed > 0
        assert spec.health()["spec_acceptance_rate"] is not None

    def test_spec_saves_ticks_on_repetitive_text(self, model):
        # a prompt whose greedy continuation is periodic for THIS model
        # (period-3 loop, verified when the fixture was seeded):
        # acceptance must compress ticks
        p = [11, 74, 85] * 4
        base = make_engine(model)
        spec = make_engine(model, speculate="ngram", speculate_k=4)
        rb = base.add_request(p, max_new_tokens=12)
        rs = spec.add_request(p, max_new_tokens=12)
        assert base.run_to_completion()[rb] == \
            spec.run_to_completion()[rs]
        assert spec._ticks < base._ticks
        assert spec.spec_accepted > 0

    def test_spec_sampling_slots_match_plain_sampling(self, model):
        # temperature>0 slots ride the verify program with acceptance
        # disabled — tokens must equal the plain decode path's sampling
        p = prompt(43, n=6)

        def run(**kw):
            eng = make_engine(model, seed=11, **kw)
            rid = eng.add_request(p, max_new_tokens=8, temperature=0.9,
                                  top_p=0.9)
            return eng.run_to_completion()[rid]

        assert run() == run(speculate="ngram", speculate_k=4)

    def test_spec_near_block_table_capacity_falls_back(self, model):
        """A sequence within k of its max_blocks_per_seq ceiling must
        not feed a (seq+k) verify (block-table lookups would clamp into
        a foreign block); the engine decodes plainly through the
        boundary instead of crashing the tick."""
        # cap = 4 blocks * 4 = 16 positions; prompt 8 + 8 new == cap
        p = prompt(45, n=8)
        base = make_engine(model, num_blocks=64, max_blocks_per_seq=4)
        spec = make_engine(model, num_blocks=64, max_blocks_per_seq=4,
                           speculate="ngram", speculate_k=4)
        rb = base.add_request(p, max_new_tokens=8)
        rs = spec.add_request(p, max_new_tokens=8)
        b = base.run_to_completion()
        s = spec.run_to_completion()
        assert spec.tick_failures == 0
        assert spec.lifecycle.state != ReplicaState.DEGRADED
        assert s[rs] == b[rb]

    def test_spec_with_eos_stops_exactly(self, model):
        p = prompt(44, n=5)
        base = make_engine(model)
        rb = base.add_request(p, max_new_tokens=10)
        b_toks = base.run_to_completion()[rb]
        eos = b_toks[3]
        base2 = make_engine(model, eos_id=eos)
        spec = make_engine(model, eos_id=eos, speculate="ngram")
        r2 = base2.add_request(p, max_new_tokens=10)
        r3 = spec.add_request(p, max_new_tokens=10)
        assert base2.run_to_completion()[r2] == \
            spec.run_to_completion()[r3]


# ------------------------------------------------- phase-split scheduler
class TestPhaseSplitScheduler:
    def test_budgeted_prefill_same_tokens(self, model):
        long_p = prompt(50, n=40)
        short_p = prompt(51, n=4)
        base = make_engine(model, max_batch=2)
        split = make_engine(
            model, max_batch=2,
            scheduler=SchedulerConfig(prefill_token_budget=4))
        b1 = base.add_request(long_p, max_new_tokens=6)
        b2 = base.add_request(short_p, max_new_tokens=6)
        s1 = split.add_request(long_p, max_new_tokens=6)
        s2 = split.add_request(short_p, max_new_tokens=6)
        b_out = base.run_to_completion()
        s_out = split.run_to_completion()
        assert s_out[s1] == b_out[b1]
        assert s_out[s2] == b_out[b2]
        # the budget actually deferred chunks across ticks
        assert split.scheduler.deferred_chunks > 0
        assert split._ticks > base._ticks

    def test_decode_not_starved_by_long_prompt(self, model):
        """Decode-priority: while a 40-token prompt trickles through a
        4-token/tick budget, the already-running request keeps emitting
        a token EVERY tick."""
        split = make_engine(
            model, max_batch=2,
            scheduler=SchedulerConfig(prefill_token_budget=4))
        fast = split.add_request(prompt(52, n=4), max_new_tokens=30)
        split.step()                        # fast prefilled + 1 token
        split.add_request(prompt(53, n=40), max_new_tokens=4)
        split.step()                        # long admitted, chunk 1 of 10
        assert 1 in split._prefilling
        n0 = len(split.slots[0].generated)
        ticks = 0
        while 1 in split._prefilling and ticks < 50:
            split.step()
            ticks += 1
        assert ticks > 1                    # prompt really was chunked
        fast_req = split.slots[0]
        assert fast_req is not None and fast_req.rid == fast
        # decode never starved: one token EVERY tick of the prefill
        assert len(fast_req.generated) == n0 + ticks
        assert split.scheduler.phase_share()["prefill"] is not None
        split.drain()

    def test_token_accounting(self, model):
        eng = make_engine(
            model, scheduler=SchedulerConfig(prefill_token_budget=8))
        eng.add_request(prompt(54, n=10), max_new_tokens=4)
        eng.run_to_completion()
        assert eng.scheduler.prefill_tokens > 0
        assert eng.scheduler.decode_tokens > 0


# ----------------------------------------------------------- streaming
class TestStreaming:
    def test_engine_stream_yields_all_tokens(self, model):
        p = prompt(60, n=7)
        eng = make_engine(model)
        rid = eng.add_request(p, max_new_tokens=8)
        s = eng.stream(rid)
        assert isinstance(s, TokenStream)
        toks = list(s)
        assert toks == ref_greedy(model, p, 8)
        assert s.status == RequestStatus.FINISHED

    def test_stream_attached_late_replays_history(self, model):
        p = prompt(61, n=6)
        eng = make_engine(model)
        rid = eng.add_request(p, max_new_tokens=8)
        eng.step()
        eng.step()                          # some tokens already out
        toks = list(eng.stream(rid))
        assert toks == ref_greedy(model, p, 8)

    def test_stream_of_shed_request_terminates_empty(self, model):
        eng = make_engine(
            model, max_batch=1,
            res=ResilienceConfig(max_queue=8, queue_high_water=1))
        rids = [eng.add_request(prompt(62 + i), max_new_tokens=4)
                for i in range(4)]
        s = eng.stream(rids[-1])            # newest: first to shed
        eng.step()
        toks = list(s)
        assert toks == []
        assert s.status == RequestStatus.SHED
        eng.drain()

    def test_router_stream_matches_greedy(self, model):
        p = prompt(65, n=9)
        router = Router([make_engine(model) for _ in range(2)]).warmup()
        rid = router.add_request(p, max_new_tokens=8)
        toks = list(router.stream(rid))
        assert toks == ref_greedy(model, p, 8)


# --------------------------------------- sampling determinism (bugfix)
class TestSamplingDeterminismUnderPreemption:
    def _sampled_run(self, model, preempt: bool):
        """Two sampled requests; with ``preempt`` the pool is tight
        enough that one is evicted mid-flight and re-prefilled."""
        kw = (dict(num_blocks=5, max_blocks_per_seq=4) if preempt
              else dict(num_blocks=64, max_blocks_per_seq=16))
        eng = make_engine(model, seed=123, **kw)
        evictions = []
        orig = eng._evict
        eng._evict = lambda slot: (evictions.append(slot),
                                   orig(slot))[-1]
        p1, p2 = prompt(70, n=4), prompt(71, n=4)
        r1 = eng.add_request(p1, max_new_tokens=6, temperature=1.0,
                             top_p=0.9)
        r2 = eng.add_request(p2, max_new_tokens=6, temperature=1.0,
                             top_p=0.9)
        out = eng.run_to_completion(max_ticks=300)
        return out[r1], out[r2], len(evictions)

    def test_preempted_sampled_request_resumes_same_tokens(self, model):
        """The regression (ISSUE 13 bugfix): re-admission re-prefills
        the generated prefix but used to REPLAY the engine-global RNG
        stream from a shifted position, so a preempted sampled request
        diverged from its unpreempted self. Keys are per (request,
        position) now — preemption is invisible in the tokens."""
        base1, base2, ev0 = self._sampled_run(model, preempt=False)
        got1, got2, ev = self._sampled_run(model, preempt=True)
        assert ev0 == 0 and ev >= 1         # the tight run really evicted
        assert got1 == base1
        assert got2 == base2

    def test_fixed_seed_reproducible_across_engines(self, model):
        p = prompt(72, n=5)

        def run(seed):
            eng = make_engine(model, seed=seed)
            rid = eng.add_request(p, max_new_tokens=6, temperature=0.8,
                                  top_p=0.95)
            return eng.run_to_completion()[rid]

        assert run(5) == run(5)
        assert run(5) != run(6)


# ------------------------------------------------------- loadgen rider
class TestLoadgenRouterMode:
    def test_run_load_through_router_accounts_everything(self, model):
        import os
        import sys
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        from tools.loadgen import run_load

        router = Router(
            [make_engine(model, max_batch=2,
                         res=ResilienceConfig(max_queue=4))
             for _ in range(2)]).warmup()
        report = run_load(router, offered_rps=500.0, n_requests=12,
                          vocab_size=97, prompt_len_range=(4, 10),
                          max_new_tokens=4, seed=3)
        router.drain()
        assert report["submitted"] == 12
        assert report["overloaded"] == 0     # router never raises
        assert report["finished"] + report["shed"] == 12
        assert report["router"] is not None
        routed = sum(r["routed"]
                     for r in report["router"]["per_replica"])
        assert routed + report["router"]["shed_at_router"] >= 12
        assert_no_leaks(router.replicas)
