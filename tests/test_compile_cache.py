"""Persistent compilation cache + AOT warmup (paddle_tpu/compile/).

Covers the ISSUE-5 acceptance criteria:
- a second process reusing the cache performs ZERO framework compiles
  for an already-seen signature (trace count 0, pcc_hits_total 1);
- a corrupted cache entry (flip / truncate / torn publish / failed
  rename) is quarantined and recompiled without user-visible failure;
plus the store unit behavior (CRC verify, LRU budget, manifest
tolerance), all three integration sites (to_static, SOT segments,
loaded artifacts/Predictor), and the warm CLI flow.
"""
import glob
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.compile as pcc
from paddle_tpu import jit, nn
from paddle_tpu.fault import inject
from paddle_tpu.observability import REGISTRY
from paddle_tpu.static import InputSpec

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures")
if FIXTURES not in sys.path:
    sys.path.insert(0, FIXTURES)

import pcc_targets  # noqa: E402


@pytest.fixture
def cache_env(tmp_path):
    """Metrics on + cache on, pointed at a per-test directory; restores
    everything afterwards."""
    cache_dir = str(tmp_path / "pcc")
    paddle.set_flags({"FLAGS_enable_metrics": True,
                      "FLAGS_compile_cache": True,
                      "FLAGS_compile_cache_dir": cache_dir})
    REGISTRY.reset()
    yield cache_dir
    paddle.set_flags({"FLAGS_enable_metrics": False,
                      "FLAGS_compile_cache": False,
                      "FLAGS_compile_cache_dir": "",
                      "FLAGS_compile_cache_manifest": ""})
    REGISTRY.reset()
    inject.disarm_all()


def _entry_files(cache_dir):
    return sorted(glob.glob(os.path.join(cache_dir, "*.pcc")))


def _subproc_env():
    """Child env identical to the pytest process (same JAX_PLATFORMS and
    virtual-device XLA_FLAGS — the topology is part of the cache key)."""
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["PYTHONPATH"] = (REPO + os.pathsep + FIXTURES + os.pathsep
                         + env.get("PYTHONPATH", ""))
    return env


# ---------------------------------------------------------------------------
# store unit behavior
# ---------------------------------------------------------------------------
class TestCacheStore:
    def test_roundtrip(self, cache_env):
        c = pcc.CompileCache(cache_env)
        assert c.put("k1", b"payload-bytes", {"site": "test", "n": 3})
        meta, payload = c.get("k1", site="test")
        assert payload == b"payload-bytes"
        assert meta["site"] == "test" and meta["n"] == 3

    def test_absent_is_miss(self, cache_env):
        c = pcc.CompileCache(cache_env)
        assert c.get("nope", site="test") is None
        assert REGISTRY.get("paddle_tpu_pcc_misses_total").total() == 1

    @pytest.mark.parametrize("damage", ["flip_meta", "flip_payload",
                                        "truncate", "magic"])
    def test_corruption_quarantined(self, cache_env, damage):
        c = pcc.CompileCache(cache_env)
        c.put("k1", b"x" * 256, {"site": "test"})
        path = _entry_files(cache_env)[0]
        data = bytearray(open(path, "rb").read())
        if damage == "flip_meta":
            data[12] ^= 0xFF
        elif damage == "flip_payload":
            data[-10] ^= 0xFF
        elif damage == "truncate":
            data = data[:len(data) // 2]
        else:
            data[0] ^= 0xFF
        open(path, "wb").write(bytes(data))
        assert c.get("k1", site="test") is None
        assert not _entry_files(cache_env)          # moved aside
        qdir = os.path.join(cache_env, "quarantine")
        assert len(os.listdir(qdir)) == 1           # evidence kept
        assert REGISTRY.get(
            "paddle_tpu_pcc_quarantined_total").total() == 1

    def test_torn_publish_leaves_no_entry(self, cache_env):
        c = pcc.CompileCache(cache_env)
        with inject.armed("pcc.write_truncate_after_bytes", after_bytes=20):
            assert not c.put("k1", b"y" * 500, {"site": "test"})
        assert not _entry_files(cache_env)
        assert c.get("k1", site="test") is None     # miss, no crash

    def test_rename_fail_leaves_no_entry(self, cache_env):
        c = pcc.CompileCache(cache_env)
        with inject.armed("io.rename_fail"):
            assert not c.put("k1", b"z" * 500, {"site": "test"})
        assert not _entry_files(cache_env)

    def test_lru_budget_evicts_oldest(self, cache_env):
        c = pcc.CompileCache(cache_env, size_limit_mb=1)
        for i in range(5):
            c.put(f"k{i}", b"x" * 300_000, {"site": "test"})
        assert c.total_bytes() <= 1 << 20
        live = {e["key"] for e in c.entries()}
        assert "k4" in live and "k0" not in live
        assert REGISTRY.get("paddle_tpu_pcc_evicted_total").total() >= 1

    def test_lru_touch_protects_hot_entry(self, cache_env):
        c = pcc.CompileCache(cache_env, size_limit_mb=1)
        c.put("hot", b"x" * 300_000, {"site": "test"})
        for i in range(3):
            c.get("hot", site="test")               # keep it recent
            c.put(f"cold{i}", b"x" * 300_000, {"site": "test"})
        assert "hot" in {e["key"] for e in c.entries()}

    def test_torn_manifest_tolerated(self, cache_env):
        c = pcc.CompileCache(cache_env)
        c.put("k1", b"p", {"site": "test"})
        with open(os.path.join(cache_env, "manifest.json"), "w") as f:
            f.write("{not json")
        assert c.get("k1", site="test")[1] == b"p"
        assert len(c.entries()) == 1                # rebuilt from scan


# ---------------------------------------------------------------------------
# to_static integration
# ---------------------------------------------------------------------------
class TestToStaticCache:
    def test_second_instance_hits_without_compiling(self, cache_env):
        x, y = pcc_targets.example_inputs()
        o1 = jit.to_static(pcc_targets.affine_fn, full_graph=True)(x, y)
        compiles = REGISTRY.get("paddle_tpu_to_static_compile_total")
        assert compiles.total() == 1
        assert REGISTRY.get("paddle_tpu_pcc_misses_total").value(
            site="to_static") == 1
        o2 = jit.to_static(pcc_targets.affine_fn, full_graph=True)(x, y)
        assert compiles.total() == 1                # no new trace/compile
        assert REGISTRY.get("paddle_tpu_pcc_hits_total").value(
            site="to_static") == 1
        np.testing.assert_allclose(o1.numpy(), o2.numpy())
        assert REGISTRY.get(
            "paddle_tpu_pcc_time_saved_seconds").total() > 0

    def test_edited_body_does_not_stale_hit(self, cache_env):
        """Two versions of a function at the SAME file/line (an in-place
        edit between runs): the cache must miss on the new body, never
        serve the old executable."""
        def make(body):
            src = f"def f(x):\n    return x * {body}\n"
            ns = {}
            exec(compile(src, "fake_edit.py", "exec"),
                 {"__name__": "fake_edit_mod"}, ns)
            return ns["f"]

        x = paddle.to_tensor(np.ones((3,), np.float32))
        o1 = jit.to_static(make("2.0"), full_graph=True)(x)
        np.testing.assert_allclose(o1.numpy(), [2, 2, 2])
        o2 = jit.to_static(make("3.0"), full_graph=True)(x)
        np.testing.assert_allclose(o2.numpy(), [3, 3, 3])
        assert REGISTRY.get("paddle_tpu_pcc_hits_total").total() == 0
        assert REGISTRY.get("paddle_tpu_pcc_misses_total").value(
            site="to_static") == 2
        # unchanged body still hits
        o3 = jit.to_static(make("2.0"), full_graph=True)(x)
        np.testing.assert_allclose(o3.numpy(), [2, 2, 2])
        assert REGISTRY.get("paddle_tpu_pcc_hits_total").value(
            site="to_static") == 1

    def test_lowering_flag_changes_key(self, cache_env):
        x, y = pcc_targets.example_inputs()
        jit.to_static(pcc_targets.affine_fn, full_graph=True)(x, y)
        try:
            paddle.set_flags({"FLAGS_tpu_matmul_precision": "highest"})
            jit.to_static(pcc_targets.affine_fn, full_graph=True)(x, y)
            # different lowering flags must be a different entry, not a
            # stale hit
            assert REGISTRY.get("paddle_tpu_pcc_misses_total").value(
                site="to_static") == 2
        finally:
            paddle.set_flags({"FLAGS_tpu_matmul_precision": "default"})

    @pytest.mark.parametrize("damage", ["flip", "truncate"])
    def test_corrupt_entry_recompiles_silently(self, cache_env, damage):
        x, y = pcc_targets.example_inputs()
        o1 = jit.to_static(pcc_targets.affine_fn, full_graph=True)(x, y)
        path = _entry_files(cache_env)[0]
        data = bytearray(open(path, "rb").read())
        if damage == "flip":
            data[len(data) // 2] ^= 0xFF
        else:
            data = data[:30]
        open(path, "wb").write(bytes(data))
        o2 = jit.to_static(pcc_targets.affine_fn, full_graph=True)(x, y)
        np.testing.assert_allclose(o1.numpy(), o2.numpy())
        assert REGISTRY.get(
            "paddle_tpu_pcc_quarantined_total").total() == 1
        # the recompile republished a fresh entry
        assert len(_entry_files(cache_env)) == 1

    def test_torn_publish_then_clean_run(self, cache_env):
        x, y = pcc_targets.example_inputs()
        with inject.armed("pcc.write_truncate_after_bytes",
                          after_bytes=40):
            o1 = jit.to_static(pcc_targets.affine_fn, full_graph=True)(x, y)
        assert not _entry_files(cache_env)          # publish failed clean
        o2 = jit.to_static(pcc_targets.affine_fn, full_graph=True)(x, y)
        np.testing.assert_allclose(o1.numpy(), o2.numpy())
        assert len(_entry_files(cache_env)) == 1    # second run published

    def test_disabled_flag_means_no_cache_io(self, cache_env):
        paddle.set_flags({"FLAGS_compile_cache": False})
        x, y = pcc_targets.example_inputs()
        jit.to_static(pcc_targets.affine_fn, full_graph=True)(x, y)
        assert not os.path.exists(cache_env) or not _entry_files(cache_env)
        assert REGISTRY.get("paddle_tpu_pcc_misses_total").total() == 0


# ---------------------------------------------------------------------------
# cross-process proof (the acceptance criterion)
# ---------------------------------------------------------------------------
_CHILD = """
import numpy as np
import paddle_tpu as paddle
from paddle_tpu import jit
import pcc_targets
x, y = pcc_targets.example_inputs()
o = jit.to_static(pcc_targets.affine_fn, full_graph=True)(x, y)
from paddle_tpu.observability import REGISTRY
import json
print(json.dumps({
    "compiles": REGISTRY.get("paddle_tpu_to_static_compile_total").total(),
    "out": np.asarray(o._data).tolist()}))
"""


class TestCrossProcess:
    def test_second_process_zero_compiles(self, cache_env):
        env = _subproc_env()
        env.update({"FLAGS_enable_metrics": "1",
                    "FLAGS_compile_cache": "1",
                    "FLAGS_compile_cache_dir": cache_env})
        proc = subprocess.run([sys.executable, "-c", _CHILD], env=env,
                              cwd=REPO, capture_output=True, text=True,
                              timeout=300)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        child = json.loads(proc.stdout.strip().splitlines()[-1])
        assert child["compiles"] == 1               # child paid the compile
        assert len(_entry_files(cache_env)) == 1

        REGISTRY.reset()
        x, y = pcc_targets.example_inputs()
        o = jit.to_static(pcc_targets.affine_fn, full_graph=True)(x, y)
        # zero framework trace/compiles + exactly one persistent hit
        assert REGISTRY.get(
            "paddle_tpu_to_static_compile_total").total() == 0
        assert REGISTRY.get("paddle_tpu_pcc_hits_total").value(
            site="to_static") == 1
        np.testing.assert_allclose(o.numpy(), np.asarray(child["out"]),
                                   rtol=1e-6)


# ---------------------------------------------------------------------------
# SOT segment integration
# ---------------------------------------------------------------------------
class TestSOTSegmentCache:
    def test_fresh_instance_reuses_segments(self, cache_env):
        x = paddle.to_tensor(np.eye(4, dtype=np.float32))
        with pytest.warns(UserWarning):
            o1 = jit.to_static(pcc_targets.breaking_fn,
                               full_graph=False)(x)
        misses = REGISTRY.get("paddle_tpu_pcc_misses_total").value(
            site="sot")
        assert misses >= 2                          # both segments published
        with pytest.warns(UserWarning):
            o2 = jit.to_static(pcc_targets.breaking_fn,
                               full_graph=False)(x)
        assert REGISTRY.get("paddle_tpu_pcc_hits_total").value(
            site="sot") == misses
        np.testing.assert_allclose(o1.numpy(), o2.numpy())

    def test_corrupt_segment_recompiles(self, cache_env):
        x = paddle.to_tensor(np.eye(4, dtype=np.float32))
        with pytest.warns(UserWarning):
            o1 = jit.to_static(pcc_targets.breaking_fn,
                               full_graph=False)(x)
        for path in _entry_files(cache_env):
            data = bytearray(open(path, "rb").read())
            data[len(data) // 2] ^= 0xFF
            open(path, "wb").write(bytes(data))
        with pytest.warns(UserWarning):
            o2 = jit.to_static(pcc_targets.breaking_fn,
                               full_graph=False)(x)
        np.testing.assert_allclose(o1.numpy(), o2.numpy())
        assert REGISTRY.get(
            "paddle_tpu_pcc_quarantined_total").total() >= 2


# ---------------------------------------------------------------------------
# loaded artifacts + Predictor
# ---------------------------------------------------------------------------
class TestArtifactCache:
    def _save(self, tmp_path, batch_dim=-1):
        paddle.seed(7)
        net = nn.Linear(8, 4)
        prefix = str(tmp_path / "model")
        jit.save(net, prefix,
                 input_spec=[InputSpec([batch_dim, 8], "float32")])
        return prefix

    def test_second_load_hits(self, cache_env, tmp_path):
        prefix = self._save(tmp_path)
        x = paddle.to_tensor(np.random.randn(2, 8).astype(np.float32))
        o1 = jit.load(prefix)(x)
        assert REGISTRY.get("paddle_tpu_pcc_misses_total").value(
            site="artifact") == 1
        o2 = jit.load(prefix)(x)
        assert REGISTRY.get("paddle_tpu_pcc_hits_total").value(
            site="artifact") == 1
        np.testing.assert_allclose(o1.numpy(), o2.numpy())

    def test_predictor_rides_the_cache(self, cache_env, tmp_path):
        from paddle_tpu.inference import Config, create_predictor
        prefix = self._save(tmp_path)
        x = np.random.randn(2, 8).astype(np.float32)
        jit.load(prefix)(paddle.to_tensor(x))       # publish
        pred = create_predictor(Config(prefix))
        h = pred.get_input_handle("input_0")
        h.copy_from_cpu(x)
        pred.run()
        assert REGISTRY.get("paddle_tpu_pcc_hits_total").value(
            site="artifact") == 1
        assert pred.get_output_handle("output_0").copy_to_cpu().shape \
            == (2, 4)

    def test_precompile_warms_unseen_shape(self, cache_env, tmp_path):
        prefix = self._save(tmp_path)               # symbolic batch dim
        jit.load(prefix).precompile([InputSpec([5, 8], "float32")])
        assert len(_entry_files(cache_env)) == 1
        o = jit.load(prefix)(
            paddle.to_tensor(np.random.randn(5, 8).astype(np.float32)))
        assert REGISTRY.get("paddle_tpu_pcc_hits_total").value(
            site="artifact") == 1
        assert o.shape == [5, 4]


# ---------------------------------------------------------------------------
# warmup manifest + CLI
# ---------------------------------------------------------------------------
class TestWarmup:
    def test_record_and_warm_in_process(self, cache_env, tmp_path):
        manifest = str(tmp_path / "sigs.jsonl")
        paddle.set_flags({"FLAGS_compile_cache_manifest": manifest})
        x, y = pcc_targets.example_inputs()
        jit.to_static(pcc_targets.affine_fn, full_graph=True)(x, y)
        paddle.set_flags({"FLAGS_compile_cache_manifest": ""})
        recs = pcc.read_manifest(manifest)
        assert recs and recs[0]["target"] == "pcc_targets:affine_fn"

        pcc.get_cache().clear()
        summary = pcc.warm(manifest)
        assert summary["warmed"] == ["pcc_targets:affine_fn"]
        assert not summary["failed"]
        assert len(_entry_files(cache_env)) == 1

        REGISTRY.reset()
        o = jit.to_static(pcc_targets.affine_fn, full_graph=True)(x, y)
        assert REGISTRY.get(
            "paddle_tpu_to_static_compile_total").total() == 0
        assert REGISTRY.get("paddle_tpu_pcc_hits_total").value(
            site="to_static") == 1
        np.testing.assert_allclose(
            o.numpy(), x.numpy() @ y.numpy() + 1.0, rtol=1e-5)

    def test_unresolvable_record_is_skipped(self, cache_env, tmp_path):
        manifest = str(tmp_path / "sigs.jsonl")
        with open(manifest, "w") as f:
            f.write(json.dumps({"kind": "to_static", "target": None,
                                "name": "lambda",
                                "arrays": [[[2, 2], "float32"]]}) + "\n")
        summary = pcc.warm(manifest)
        assert summary["skipped"] == ["lambda"]
        assert not summary["failed"]

    def test_warm_cli(self, cache_env, tmp_path):
        manifest = str(tmp_path / "sigs.jsonl")
        paddle.set_flags({"FLAGS_compile_cache_manifest": manifest})
        x, y = pcc_targets.example_inputs()
        jit.to_static(pcc_targets.affine_fn, full_graph=True)(x, y)
        paddle.set_flags({"FLAGS_compile_cache_manifest": ""})
        pcc.get_cache().clear()

        env = _subproc_env()
        env.pop("FLAGS_compile_cache", None)
        proc = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.compile", "warm", manifest,
             "--cache-dir", cache_env],
            env=env, cwd=REPO, capture_output=True, text=True, timeout=300)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert json.loads(proc.stdout)["warmed"] == [
            "pcc_targets:affine_fn"]
        assert len(_entry_files(cache_env)) == 1

    def test_inspect_and_prune_cli(self, cache_env):
        c = pcc.CompileCache(cache_env)
        c.put("k1", b"x" * 1000, {"site": "test", "tier": "exec"})
        env = _subproc_env()
        env["FLAGS_compile_cache_dir"] = cache_env
        out = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.compile", "inspect"],
            env=env, cwd=REPO, capture_output=True, text=True, timeout=120)
        assert out.returncode == 0, out.stdout + out.stderr
        assert "1 entries" in out.stdout
        out = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.compile", "clear"],
            env=env, cwd=REPO, capture_output=True, text=True, timeout=120)
        assert out.returncode == 0
        assert not _entry_files(cache_env)
