"""Round-2 coverage batch B: LLaMA, inference predictor, sparse, audio,
custom ops.
"""
import math

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn


class TestLlama:
    def _tiny(self, **kw):
        from paddle_tpu.models import llama_tiny
        kw.setdefault("use_flash_attention", False)
        return llama_tiny(**kw)

    def test_trains(self):
        from paddle_tpu.models import LlamaForCausalLM
        paddle.seed(0)
        m = LlamaForCausalLM(self._tiny())
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=m.parameters())
        ids = paddle.to_tensor(
            np.random.randint(0, 512, (2, 32)).astype(np.int64))
        losses = []
        for _ in range(4):
            _, loss = m(ids, labels=ids)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0]

    def test_gqa_shapes_and_grads(self):
        from paddle_tpu.models import LlamaForCausalLM
        paddle.seed(1)
        m = LlamaForCausalLM(self._tiny(num_kv_heads=2))
        attn = m.model.layers[0].self_attn
        # kv projections are narrower than q under GQA
        assert attn.k_proj.weight.shape[-1] < attn.q_proj.weight.shape[-1]
        ids = paddle.to_tensor(
            np.random.randint(0, 512, (2, 16)).astype(np.int64))
        _, loss = m(ids, labels=ids)
        loss.backward()
        assert all(p.grad is not None for p in m.parameters()
                   if not p.stop_gradient)

    def test_rope_properties(self):
        from paddle_tpu.models.llama import rotary_embedding
        x = paddle.to_tensor(np.random.randn(1, 8, 2, 16)
                             .astype(np.float32))
        out = rotary_embedding(x)
        # norms preserved per (pos, head) pair rotation
        np.testing.assert_allclose(
            np.linalg.norm(out.numpy(), axis=-1),
            np.linalg.norm(x.numpy(), axis=-1), atol=1e-5)
        # position 0 is identity
        np.testing.assert_allclose(out.numpy()[:, 0], x.numpy()[:, 0],
                                   atol=1e-6)

    @pytest.mark.slow
    def test_ring_attention_with_tp(self):
        """LLaMA with context_parallel='ring' + mp TP on a sep x mp mesh:
        loss matches the dense single-config model on the same weights.

        Slow-marked (~15s, 870s tier-1 budget): ring==dense equality
        stays in tier-1 via test_moe_sep's ring_flash_attention parity
        and TP via test_fleet_tp's gpt_mp2-matches-serial."""
        from paddle_tpu.distributed import mesh as mesh_mod
        from paddle_tpu.models import LlamaForCausalLM

        old = mesh_mod._global_mesh
        try:
            mesh_mod.set_mesh(mesh_mod.build_mesh({"sep": 4, "mp": 2}))
            paddle.seed(9)
            m = LlamaForCausalLM(self._tiny(context_parallel="ring",
                                            mp_degree=2))
            ids = paddle.to_tensor(
                np.random.randint(0, 512, (2, 32)).astype(np.int64))
            _, loss = m(ids, labels=ids)
            loss.backward()
            assert all(p.grad is not None for p in m.parameters()
                       if not p.stop_gradient)

            dense = LlamaForCausalLM(self._tiny())
            dense.set_state_dict(m.state_dict())
            _, ref = dense(ids, labels=ids)
            np.testing.assert_allclose(float(loss.numpy()),
                                       float(ref.numpy()), rtol=1e-4)
        finally:
            mesh_mod._global_mesh = old

    def test_kv_cache_decode_matches_no_cache(self):
        from paddle_tpu.models import LlamaForCausalLM
        paddle.seed(5)
        ids = paddle.to_tensor(
            np.random.randint(0, 512, (2, 8)).astype(np.int64))
        for kv in (4, 2):     # MHA and GQA
            m = LlamaForCausalLM(self._tiny(num_kv_heads=kv))
            a = m.generate(ids, max_new_tokens=6, use_cache=False).numpy()
            b = m.generate(ids, max_new_tokens=6, use_cache=True).numpy()
            np.testing.assert_array_equal(a, b)

    def test_sampled_decode_rng_parity(self):
        """temperature>0: same seed -> identical samples on both paths
        (the per-token key stream is shared)."""
        from paddle_tpu.models import LlamaForCausalLM
        paddle.seed(6)
        m = LlamaForCausalLM(self._tiny())
        ids = paddle.to_tensor(
            np.random.randint(0, 512, (1, 8)).astype(np.int64))
        paddle.seed(123)
        a = m.generate(ids, max_new_tokens=5, temperature=1.0,
                       use_cache=False).numpy()
        paddle.seed(123)
        b = m.generate(ids, max_new_tokens=5, temperature=1.0,
                       use_cache=True).numpy()
        np.testing.assert_array_equal(a, b)

    def test_generate_greedy_deterministic(self):
        from paddle_tpu.models import LlamaForCausalLM
        paddle.seed(2)
        m = LlamaForCausalLM(self._tiny())
        ids = paddle.to_tensor(
            np.random.randint(0, 512, (1, 4)).astype(np.int64))
        a = m.generate(ids, max_new_tokens=5).numpy()
        b = m.generate(ids, max_new_tokens=5).numpy()
        np.testing.assert_array_equal(a, b)
        assert a.shape == (1, 9)


class TestInferencePredictor:
    def test_round_trip(self, tmp_path):
        from paddle_tpu.inference import Config, create_predictor
        from paddle_tpu.static import InputSpec
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
        x = np.random.randn(2, 8).astype(np.float32)
        ref = net(paddle.to_tensor(x)).numpy()
        prefix = str(tmp_path / "model")
        paddle.jit.save(net, prefix,
                        input_spec=[InputSpec([-1, 8], "float32")])

        pred = create_predictor(Config(prefix))
        h = pred.get_input_handle(pred.get_input_names()[0])
        h.copy_from_cpu(x)
        pred.run()
        out = pred.get_output_handle(pred.get_output_names()[0])
        np.testing.assert_allclose(out.copy_to_cpu(), ref, atol=1e-6)

    def test_multi_input_model(self, tmp_path):
        from paddle_tpu.inference import Config, create_predictor
        from paddle_tpu.static import InputSpec

        class TwoIn(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(8, 4)

            def forward(self, a, b):
                return self.fc(a + b)

        paddle.seed(1)
        net = TwoIn()
        a = np.random.randn(2, 8).astype(np.float32)
        b = np.random.randn(2, 8).astype(np.float32)
        ref = net(paddle.to_tensor(a), paddle.to_tensor(b)).numpy()
        prefix = str(tmp_path / "two")
        paddle.jit.save(net, prefix,
                        input_spec=[InputSpec([-1, 8], "float32"),
                                    InputSpec([-1, 8], "float32")])
        pred = create_predictor(Config(prefix))
        names = pred.get_input_names()
        assert len(names) == 2
        pred.get_input_handle(names[0]).copy_from_cpu(a)
        with pytest.raises(RuntimeError, match="never set"):
            pred.run()
        pred.get_input_handle(names[1]).copy_from_cpu(b)
        pred.run()
        out = pred.get_output_handle("output_0").copy_to_cpu()
        np.testing.assert_allclose(out, ref, atol=1e-6)

    def test_custom_op_attrs_with_custom_backward(self):
        import jax.numpy as jnp

        from paddle_tpu.utils import register_custom_op

        def fwd(a, alpha=1.0):
            return a * alpha

        def bwd(res, g):
            (arrays, out) = res
            return (g * 7.0,)

        op = register_custom_op("my_attr_scaled", fwd, backward=bwd)
        x = paddle.to_tensor(np.ones(2, np.float32), stop_gradient=False)
        out = op(x, alpha=3.0)
        np.testing.assert_allclose(np.asarray(out._data), [3.0, 3.0])
        paddle.ops.sum(out).backward()
        np.testing.assert_allclose(np.asarray(x.grad._data), [7.0, 7.0])

    def test_params_only_rejected(self, tmp_path):
        from paddle_tpu.framework.io import save as fio_save
        from paddle_tpu.inference import Config, create_predictor
        net = nn.Linear(4, 4)
        prefix = str(tmp_path / "weights")
        fio_save(net.state_dict(), prefix + ".pdparams")
        with pytest.raises(ValueError, match="pdmodel"):
            create_predictor(Config(prefix))


class TestSparse:
    def test_coo_round_trip(self):
        import paddle_tpu.sparse as sparse
        idx = np.array([[0, 1, 2], [1, 0, 2]])
        vals = np.array([1.0, 2.0, 3.0], np.float32)
        s = sparse.sparse_coo_tensor(idx, vals, (3, 3))
        assert s.nnz() == 3
        dense = s.to_dense().numpy()
        expect = np.zeros((3, 3), np.float32)
        expect[0, 1], expect[1, 0], expect[2, 2] = 1, 2, 3
        np.testing.assert_array_equal(dense, expect)
        np.testing.assert_array_equal(np.asarray(s.indices()._data), idx)

    def test_csr_round_trip(self):
        import paddle_tpu.sparse as sparse
        # [[1, 0, 2], [0, 0, 3], [4, 0, 0]]
        s = sparse.sparse_csr_tensor(
            [0, 2, 3, 4], [0, 2, 2, 0],
            np.array([1.0, 2.0, 3.0, 4.0], np.float32), (3, 3))
        dense = s.to_dense().numpy()
        expect = np.array([[1, 0, 2], [0, 0, 3], [4, 0, 0]], np.float32)
        np.testing.assert_array_equal(dense, expect)

    def test_spmm_matches_dense(self):
        import paddle_tpu.sparse as sparse
        rng = np.random.RandomState(0)
        dense_m = (rng.rand(8, 8) > 0.7) * rng.randn(8, 8)
        dense_m = dense_m.astype(np.float32)
        idx = np.nonzero(dense_m)
        s = sparse.sparse_coo_tensor(np.stack(idx), dense_m[idx], (8, 8))
        y = rng.randn(8, 4).astype(np.float32)
        out = sparse.matmul(s, paddle.to_tensor(y))
        np.testing.assert_allclose(np.asarray(out._data), dense_m @ y,
                                   atol=1e-5)

    def test_gradients_flow_through_sparse_ops(self):
        import paddle_tpu.sparse as sparse
        vals = paddle.to_tensor(np.array([-1.0, 2.0, 3.0], np.float32),
                                stop_gradient=False)
        s = sparse.sparse_coo_tensor([[0, 1, 2], [1, 0, 2]], vals, (3, 3))
        y = paddle.to_tensor(np.ones((3, 2), np.float32))
        out = sparse.matmul(sparse.relu(s), y)
        paddle.ops.sum(out).backward()
        # d/dvals of sum(relu(vals) @ ones): relu' * 2 per value
        np.testing.assert_allclose(np.asarray(vals.grad._data),
                                   [0.0, 2.0, 2.0])

    def test_sparse_add_gradients_to_both(self):
        import paddle_tpu.sparse as sparse
        va = paddle.to_tensor(np.array([1.0, 2.0], np.float32),
                              stop_gradient=False)
        vb = paddle.to_tensor(np.array([5.0], np.float32),
                              stop_gradient=False)
        a = sparse.sparse_coo_tensor([[0, 1], [0, 1]], va, (2, 2))
        b = sparse.sparse_coo_tensor([[0], [0]], vb, (2, 2))
        out = sparse.add(a, b).to_dense()
        paddle.ops.sum(out * out).backward()
        # dense result [[6,0],[0,2]]: d/dva = 2*[6,2], d/dvb = 2*[6]
        np.testing.assert_allclose(np.asarray(va.grad._data), [12.0, 4.0])
        np.testing.assert_allclose(np.asarray(vb.grad._data), [12.0])

    def test_sparse_add_and_relu(self):
        import paddle_tpu.sparse as sparse
        s1 = sparse.sparse_coo_tensor([[0, 1], [0, 1]],
                                      np.array([-1.0, 2.0], np.float32),
                                      (2, 2))
        s2 = sparse.sparse_coo_tensor([[0], [0]],
                                      np.array([5.0], np.float32), (2, 2))
        out = sparse.add(s1, s2).to_dense().numpy()
        np.testing.assert_array_equal(out, [[4, 0], [0, 2]])
        r = sparse.relu(s1).to_dense().numpy()
        np.testing.assert_array_equal(r, [[0, 0], [0, 2]])


class TestAudio:
    def test_mel_spectrogram_shapes(self):
        from paddle_tpu.audio.features import (LogMelSpectrogram,
                                               MelSpectrogram, MFCC,
                                               Spectrogram)
        x = paddle.to_tensor(np.random.randn(2, 2048).astype(np.float32))
        spec = Spectrogram(n_fft=256)(x)
        assert spec.shape[1] == 129
        mel = MelSpectrogram(sr=16000, n_fft=256, n_mels=40)(x)
        assert mel.shape[1] == 40
        logmel = LogMelSpectrogram(sr=16000, n_fft=256, n_mels=40)(x)
        assert logmel.shape == mel.shape
        mfcc = MFCC(sr=16000, n_mfcc=13, n_fft=256, n_mels=40)(x)
        assert mfcc.shape[1] == 13

    def test_fbank_rows_nonzero(self):
        from paddle_tpu.audio.functional import compute_fbank_matrix
        fb = np.asarray(compute_fbank_matrix(16000, 512, 64)._data)
        assert fb.shape == (64, 257)
        assert (fb.sum(axis=1) > 0).all()

    def test_window(self):
        from paddle_tpu.audio.functional import get_window
        w = np.asarray(get_window("hann", 16)._data)
        np.testing.assert_allclose(w, np.hanning(17)[:16], atol=1e-6)


class TestCustomOp:
    def test_autodiff_backward(self):
        import jax.numpy as jnp

        from paddle_tpu.utils import register_custom_op
        op = register_custom_op("my_square_sum",
                                lambda a: jnp.sum(a * a))
        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32),
                             stop_gradient=False)
        out = op(x)
        out.backward()
        np.testing.assert_allclose(np.asarray(x.grad._data), [2.0, 4.0])

    def test_custom_backward(self):
        import jax.numpy as jnp

        from paddle_tpu.utils import register_custom_op

        def fwd(a):
            return a * 2.0

        def bwd(res, g):
            return (g * 100.0,)     # deliberately not the true grad

        op = register_custom_op("my_scaled", fwd, backward=bwd)
        x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
        paddle.ops.sum(op(x)).backward()
        np.testing.assert_allclose(np.asarray(x.grad._data),
                                   np.full(3, 100.0))

    def test_registered_in_registry(self):
        from paddle_tpu.ops.registry import OPS
        assert "my_square_sum" in OPS and OPS["my_square_sum"].category \
            == "custom"

    def test_duplicate_rejected(self):
        from paddle_tpu.utils import register_custom_op
        with pytest.raises(ValueError, match="already registered"):
            register_custom_op("matmul", lambda a: a)
