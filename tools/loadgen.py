"""Open-loop Poisson load harness for the paged serving engine.

Open-loop means arrivals are driven by a Poisson process fixed up front —
the generator does NOT wait for completions before submitting (a
closed-loop harness hides overload by self-throttling; see the
coordinated-omission literature). The engine is ticked between arrivals;
every submitted request ends in a terminal status, and the report
aggregates the SLO view of the run:

* p50/p99 TTFT (submit → first token) and inter-token latency,
* goodput (tokens/s from FINISHED requests) vs offered load,
* shed / deadline-missed / failed / cancelled counts and submit-time
  ``Overloaded`` backpressure rejections.

Library: ``run_load(engine, offered_rps=..., n_requests=...)`` → dict.
CLI (tiny CPU-sized Llama, sweeps offered load, one JSON line per point):

    python tools/loadgen.py --rates 4,16,64 --requests 32
"""
from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional

import numpy as np


def _percentile(xs: List[float], q: float) -> Optional[float]:
    if not xs:
        return None
    return float(np.percentile(np.asarray(xs, np.float64), q))


def poisson_arrivals(offered_rps: float, n: int, seed: int = 0):
    """Cumulative arrival times (seconds from start) of a Poisson process
    with rate ``offered_rps`` — exponential inter-arrivals, seeded."""
    if offered_rps <= 0:
        raise ValueError("offered_rps must be > 0")
    rng = np.random.RandomState(seed)
    return np.cumsum(rng.exponential(1.0 / offered_rps, size=n))


def run_load(engine, *, offered_rps: float, n_requests: int,
             vocab_size: int = 97,
             prompt_len_range=(4, 24), max_new_tokens: int = 8,
             ttft_deadline_s: Optional[float] = None,
             deadline_s: Optional[float] = None,
             seed: int = 0,
             make_prompt: Optional[Callable[[np.random.RandomState, int],
                                            List[int]]] = None,
             clock: Callable[[], float] = time.monotonic,
             max_wall_s: float = 300.0,
             attribution: bool = True,
             trace_out: Optional[str] = None,
             trace_worst_k: int = 4) -> dict:
    """Drive ``engine`` with an open-loop Poisson arrival stream and
    return the latency/goodput/outcome report (JSON-able dict).

    The engine is ticked whenever it has work; between arrivals with an
    idle engine the harness sleeps in small slices so arrival timing
    stays honest. ``max_wall_s`` is a harness-level backstop (an engine
    bug must fail the drill, not hang it).

    With ``attribution`` (default) the run collects the engine's
    per-tick device spans (``serving.prefill`` / ``serving.decode``, each
    bracketed by the blocking result read) and reports device-time
    attribution: prefill vs decode compute seconds and shares, plus
    device time per tick — the SLO view of *where* the chip's time went,
    not just wall-clock TTFT/ITL. Skipped when a profiler recording
    already owns the span buffer.

    With ``FLAGS_reqtrace`` on (the default) the report also carries
    the p99-TTFT exemplar's wall-segment decomposition
    (``queue/prefill/decode/preempted/rerouted``, summing to its total)
    so a bad percentile points at a concrete request; ``trace_out``
    names a path PREFIX under which the worst-``trace_worst_k``
    request timelines are exported as a chrome trace merged with the
    run's device spans (``<prefix>.trace.json``) plus the raw timelines
    (``<prefix>.reqtrace.json``) — see ``tools/request_trace.py``."""
    from paddle_tpu.inference import Overloaded
    from paddle_tpu.observability import trace as _trace

    own_trace = attribution and not _trace.active()
    if own_trace:
        _trace.clear()
        _trace.activate()

    rng = np.random.RandomState(seed)
    arrivals = poisson_arrivals(offered_rps, n_requests, seed=seed)
    lo, hi = prompt_len_range
    if make_prompt is None:
        def make_prompt(r, i):
            return [int(t) for t in
                    r.randint(1, vocab_size, size=int(r.randint(lo, hi + 1)))]
    prompts = [make_prompt(rng, i) for i in range(n_requests)]

    start = clock()
    real_start = time.monotonic()
    rids: List[int] = []
    overloaded = 0
    i = 0
    try:
        while i < n_requests or engine.has_work():
            now = clock() - start
            # the backstop runs on REAL time: an injected non-advancing
            # clock must still fail the drill rather than hang it
            if time.monotonic() - real_start > max_wall_s:
                raise RuntimeError(
                    f"loadgen exceeded max_wall_s={max_wall_s} with "
                    f"{n_requests - i} arrivals pending")
            while i < n_requests and arrivals[i] <= now:
                try:
                    rids.append(engine.add_request(
                        prompts[i], max_new_tokens=max_new_tokens,
                        ttft_deadline_s=ttft_deadline_s,
                        deadline_s=deadline_s))
                except Overloaded:
                    overloaded += 1
                i += 1
            if engine.has_work():
                engine.step()
            elif i < n_requests:
                time.sleep(min(max(arrivals[i] - now, 0.0), 0.005))
    finally:
        # a failed drill must not leave the global span buffer recording
        if own_trace:
            _trace.deactivate()
    wall = clock() - start
    # span timestamps are perf_counter seconds — utilization must divide
    # by REAL elapsed time, not an injected drill clock
    real_wall = time.monotonic() - real_start

    device = None
    spans = []
    if own_trace:
        spans = _trace.drain()
        ticks = sum(1 for _n, cat, *_ in spans if cat == "serving")
        phase_s = {"prefill": 0.0, "decode": 0.0}
        for name, cat, t0, t1, _tid, _args in spans:
            if cat == "device" and name.startswith("serving."):
                phase = name.split(".", 1)[1]
                if phase in phase_s:
                    phase_s[phase] += t1 - t0
        dev_total = phase_s["prefill"] + phase_s["decode"]
        device = {
            "ticks": ticks,
            "prefill_compute_s": round(phase_s["prefill"], 4),
            "decode_compute_s": round(phase_s["decode"], 4),
            "device_s": round(dev_total, 4),
            "prefill_compute_share": round(
                phase_s["prefill"] / dev_total, 4) if dev_total else None,
            "decode_compute_share": round(
                phase_s["decode"] / dev_total, 4) if dev_total else None,
            "device_s_per_tick": round(dev_total / ticks, 6) if ticks
            else None,
            "device_util_of_wall": round(dev_total / real_wall, 4)
            if real_wall > 0 else None,
        }

    outcomes = engine.drain_outcomes()
    missing = [r for r in rids if r not in outcomes]
    if missing:
        raise RuntimeError(
            f"loadgen invariant violated: {len(missing)} submitted "
            f"request(s) have no terminal outcome: {missing[:5]}")

    by_status: Dict[str, int] = {}
    ttfts: List[float] = []
    itls: List[float] = []
    good_tokens = 0
    for rid in rids:
        oc = outcomes[rid]
        by_status[oc.status] = by_status.get(oc.status, 0) + 1
        if oc.ttft is not None:
            ttfts.append(oc.ttft)
        itls.extend(oc.itls)
        if oc.status == "FINISHED":
            good_tokens += len(oc.tokens)

    finished = by_status.get("FINISHED", 0)

    # ---- request-trace view: p99 exemplar decomposition + worst-k
    # timeline export (reqtrace is FLAGS-gated; both degrade to None) --
    p99_exemplar = None
    scope = getattr(engine, "reqtrace_scope", None)
    if scope is not None:
        from paddle_tpu.observability import reqtrace as _rt
        from tools import request_trace as _rt_tool

        src = _rt_tool.TimelineSource()
        with_ttft = sorted(
            ((outcomes[r].ttft, r) for r in rids
             if outcomes[r].ttft is not None),
            key=lambda p: p[0])
        if with_ttft:
            p99_t, p99_rid = with_ttft[
                min(int(round(0.99 * (len(with_ttft) - 1))),
                    len(with_ttft) - 1)]
            tl = src.resolve(scope, p99_rid)
            if tl is not None:
                seg = _rt.segments(tl)
                p99_exemplar = {
                    "rid": p99_rid, "ttft_s": round(p99_t, 6),
                    "outcome": outcomes[p99_rid].status,
                    "segments_s": {b: round(seg[b], 6)
                                   for b in _rt.SEGMENT_BUCKETS},
                    "total_s": round(seg["total"], 6),
                    "complete": seg["complete"],
                }
        if trace_out:
            import os as _os
            d = _os.path.dirname(trace_out)
            if d:
                _os.makedirs(d, exist_ok=True)
            # worst-k by TTFT, padded with the longest-wall outcomes
            # (an all-shed point has no TTFTs but still needs evidence)
            ranked = [r for _, r in reversed(with_ttft)]
            if len(ranked) < trace_worst_k:
                seen = set(ranked)
                by_wall = sorted(
                    rids, key=lambda r: -((outcomes[r].finish_t or 0.0)
                                          - (outcomes[r].submit_t
                                             or 0.0)))
                ranked.extend(r for r in by_wall if r not in seen)
            worst = [tl for tl in
                     (src.resolve(scope, r)
                      for r in ranked[:trace_worst_k]) if tl]
            _rt_tool.export(f"{trace_out}.trace.json", worst,
                            spans=_rt_tool.serving_spans(spans))
            with open(f"{trace_out}.reqtrace.json", "w") as f:
                import json as _json
                _json.dump({"format": "paddle_tpu.reqtrace/1",
                            "reason": "loadgen --trace-out",
                            "timelines": worst}, f)

    # router mode: per-replica routing/goodput breakdown rides the report
    router = engine.stats() if hasattr(engine, "stats") else None
    return {
        "offered_rps": float(offered_rps),
        "achieved_arrival_rps": round(n_requests / max(wall, 1e-9), 3),
        "n_requests": int(n_requests),
        "submitted": len(rids),
        "overloaded": int(overloaded),
        "outcomes": by_status,
        "shed": by_status.get("SHED", 0),
        "deadline_missed": by_status.get("DEADLINE_MISSED", 0),
        "failed": by_status.get("FAILED", 0),
        "cancelled": by_status.get("CANCELLED", 0),
        "finished": finished,
        "goodput_tokens_per_sec": round(good_tokens / max(wall, 1e-9), 2),
        "goodput_requests_per_sec": round(finished / max(wall, 1e-9), 3),
        "p50_ttft_s": _percentile(ttfts, 50),
        "p99_ttft_s": _percentile(ttfts, 99),
        "p50_itl_s": _percentile(itls, 50),
        "p99_itl_s": _percentile(itls, 99),
        "wall_s": round(wall, 3),
        "device_attribution": device,
        "p99_ttft_exemplar": p99_exemplar,
        "router": router,
    }


_MODEL_CACHE: dict = {}


def _tiny_model(seed=7):
    """One shared CPU-sized Llama per seed: replicas over the same model
    share compiled tick programs (serving._PAGED_JIT_CACHE), so an
    R-replica router costs one compile set, not R."""
    if seed not in _MODEL_CACHE:
        import paddle_tpu as paddle
        from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

        paddle.seed(seed)
        cfg = LlamaConfig(vocab_size=97, hidden_size=64,
                          intermediate_size=128, num_layers=2, num_heads=4,
                          max_seq_len=256, use_flash_attention=False)
        _MODEL_CACHE[seed] = LlamaForCausalLM(cfg)
    return _MODEL_CACHE[seed]


def _tiny_engine(max_batch=4, max_queue=32, high_water=None, seed=7,
                 kv_dtype=None, speculate=None, prefill_budget=None):
    """CPU-sized Llama replica for CLI runs and drills (per-request
    deadlines are passed through run_load, not the engine defaults)."""
    from paddle_tpu.inference import PagedEngine, ResilienceConfig
    from paddle_tpu.serving import SchedulerConfig

    rcfg = ResilienceConfig(max_queue=max_queue,
                            queue_high_water=high_water)
    sched = (SchedulerConfig(prefill_token_budget=prefill_budget)
             if prefill_budget else None)
    return PagedEngine(_tiny_model(seed), max_batch=max_batch,
                       block_size=8, num_blocks=128, max_blocks_per_seq=16,
                       kv_dtype=kv_dtype, speculate=speculate,
                       scheduler=sched, resilience=rcfg)


def _tiny_tier(replicas, **engine_kw):
    """R replicas behind a Router. Shedding policy lives AT THE ROUTER:
    replicas keep their bounded queues (Overloaded bounces the router to
    the next candidate) but run without an internal high-water mark —
    overload becomes router-level SHED outcomes, never replica-side
    drops (the acceptance shape the ISSUE/ROADMAP name)."""
    from paddle_tpu.serving import Router

    engine_kw.pop("high_water", None)
    reps = [_tiny_engine(high_water=None, **engine_kw)
            for _ in range(replicas)]
    return Router(reps).warmup()


def main(argv=None):
    import argparse
    import json

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--rates", default="4,16,64",
                    help="comma-separated offered loads (requests/s)")
    ap.add_argument("--requests", type=int, default=32)
    ap.add_argument("--max-new-tokens", type=int, default=8)
    ap.add_argument("--max-batch", type=int, default=4)
    ap.add_argument("--max-queue", type=int, default=32)
    ap.add_argument("--high-water", type=int, default=None)
    ap.add_argument("--ttft-deadline-s", type=float, default=None)
    ap.add_argument("--deadline-s", type=float, default=None)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--replicas", type=int, default=1,
                    help="router mode: front R replicas with the serving "
                         "router (shed at the router, per-replica "
                         "goodput breakdown in the report)")
    ap.add_argument("--kv-dtype", default=None,
                    help='e.g. "int8" for the quantized KV page pool')
    ap.add_argument("--speculate", default=None,
                    help='"ngram" enables speculative decoding')
    ap.add_argument("--prefill-budget", type=int, default=None,
                    help="phase-split scheduler: prefill tokens per tick")
    ap.add_argument("--trace-out", default=None, metavar="DIR",
                    help="export the worst-k request timelines per "
                         "curve point (chrome trace merged with device "
                         "spans + raw timelines) under DIR; the summary "
                         "line always carries the p99 TTFT exemplar's "
                         "segment decomposition")
    ap.add_argument("--trace-worst-k", type=int, default=4)
    args = ap.parse_args(argv)

    engine_kw = dict(max_batch=args.max_batch, max_queue=args.max_queue,
                     kv_dtype=args.kv_dtype, speculate=args.speculate,
                     prefill_budget=args.prefill_budget)
    for rate in [float(r) for r in args.rates.split(",") if r]:
        if args.replicas > 1:
            eng = _tiny_tier(args.replicas, **engine_kw)
        else:
            eng = _tiny_engine(high_water=args.high_water, **engine_kw)
            eng.warmup()
        trace_out = None
        if args.trace_out:
            import os
            trace_out = os.path.join(args.trace_out, f"rate_{rate:g}")
        report = run_load(
            eng, offered_rps=rate, n_requests=args.requests,
            max_new_tokens=args.max_new_tokens,
            ttft_deadline_s=args.ttft_deadline_s,
            deadline_s=args.deadline_s, seed=args.seed,
            trace_out=trace_out, trace_worst_k=args.trace_worst_k)
        report["replicas"] = args.replicas
        eng.drain()
        print(json.dumps(report))


if __name__ == "__main__":
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    main()
