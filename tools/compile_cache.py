"""Operator tool for the persistent compilation cache.

    python tools/compile_cache.py inspect
    python tools/compile_cache.py prune [--max-mb N]
    python tools/compile_cache.py clear
    python tools/compile_cache.py warm <manifest.jsonl>

Thin wrapper over ``python -m paddle_tpu.compile`` so fleet tooling has
one stable entry point next to the other tools/ scripts.
"""
import sys

if __name__ == "__main__":
    from paddle_tpu.compile.__main__ import main
    sys.exit(main(sys.argv[1:]))
