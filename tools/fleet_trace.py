"""Merge per-rank chrome traces onto one clock-aligned fleet timeline.

Every rank of a distributed run exports its own host chrome trace
(``profiler.export_chrome_tracing`` → ``worker_rN_host_ops.json``); each
file's timestamps are that process's ``perf_counter`` — a per-process
arbitrary epoch, so the raw files cannot be compared. This tool folds
them into ONE chrome trace with one **pid lane per rank**, shifting each
rank's timestamps by its perf_counter offset vs rank 0:

    python tools/fleet_trace.py /tmp/trace/worker_r*_host_ops.json \
        --out /tmp/trace/fleet.json

Offsets come from (in priority order):

1. ``--offsets offsets.json`` — ``{"0": 0.0, "1": -3.2e-4, ...}``
   seconds, e.g. extracted from a ``fleet.dump`` snapshot;
2. the ``clock_sync`` metadata event each trace embeds when
   ``paddle_tpu.observability.fleet.clock_sync()`` ran before export
   (the self-describing path — no side file needed);
3. zero, with a loud warning (lanes render but are NOT aligned).

Rank per file comes from the embedded ``clock_sync`` metadata, else a
``_r<N>_`` filename pattern, else positional order. Alignment accuracy is
the handshake's barrier exit skew (``skew_bound_s`` in the metadata):
µs-level on ICI, ~ms on the CPU gloo transport — see README "Fleet
observability".
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

__all__ = ["load_trace", "merge_traces", "transfer_compute_overlap",
           "main"]


def _merge_intervals(iv):
    iv = sorted(iv)
    out = []
    for a, b in iv:
        if out and a <= out[-1][1]:
            out[-1][1] = max(out[-1][1], b)
        else:
            out.append([a, b])
    return out


def _overlap_seconds(a, b):
    a, b = _merge_intervals(a), _merge_intervals(b)
    i = j = 0
    s = 0.0
    while i < len(a) and j < len(b):
        lo = max(a[i][0], b[j][0])
        hi = min(a[i][1], b[j][1])
        if hi > lo:
            s += hi - lo
        if a[i][1] <= b[j][1]:
            i += 1
        else:
            j += 1
    return s


def transfer_compute_overlap(trace: dict) -> dict:
    """Per-lane transfer/compute overlap of a (merged) chrome trace:
    seconds where an ``io``-category span (the DevicePrefetcher's
    ``io.prefetch`` transfer work) runs concurrently with a ``device``
    span (compute in flight). This is the async runtime's visible
    evidence — a synchronous pipeline shows ~0 overlap because the
    transfer finishes before the step's device window opens.

    Returns ``{lane_pid: {"io_s", "device_s", "overlap_s",
    "overlap_frac_of_io"}}``.
    """
    events = trace["traceEvents"] if isinstance(trace, dict) else trace
    lanes: Dict[int, Dict[str, list]] = {}
    for ev in events:
        if ev.get("ph") != "X" or "ts" not in ev:
            continue
        cat = str(ev.get("cat", ""))
        if cat not in ("io", "device"):
            continue
        t0 = float(ev["ts"]) / 1e6
        t1 = t0 + float(ev.get("dur", 0)) / 1e6
        lane = lanes.setdefault(int(ev.get("pid", 0)),
                                {"io": [], "device": []})
        lane[cat].append([t0, t1])
    out = {}
    for pid, lane in sorted(lanes.items()):
        io_s = sum(b - a for a, b in _merge_intervals(lane["io"]))
        dev_s = sum(b - a for a, b in _merge_intervals(lane["device"]))
        ov = _overlap_seconds(lane["io"], lane["device"])
        out[pid] = {"io_s": io_s, "device_s": dev_s, "overlap_s": ov,
                    "overlap_frac_of_io": ov / io_s if io_s else 0.0}
    return out


def load_trace(path: str) -> Tuple[List[dict], Optional[int],
                                   Optional[float]]:
    """(events, rank, offset_s) of one per-rank chrome trace file."""
    with open(path) as f:
        blob = json.load(f)
    events = blob["traceEvents"] if isinstance(blob, dict) else blob
    if not isinstance(events, list):
        raise ValueError(f"{path}: not a chrome trace "
                         f"(no traceEvents list)")
    rank = offset = None
    for ev in events:
        if ev.get("ph") == "M" and ev.get("name") == "clock_sync":
            args = ev.get("args", {})
            if args.get("rank") is not None:
                rank = int(args["rank"])
            if args.get("offset_vs_rank0_s") is not None:
                offset = float(args["offset_vs_rank0_s"])
            break
    if rank is None:
        m = re.search(r"_r(\d+)_", os.path.basename(path))
        if m:
            rank = int(m.group(1))
    return events, rank, offset


def merge_traces(paths: List[str],
                 offsets: Optional[Dict[int, float]] = None) -> dict:
    """One chrome trace dict: rank r's events land on pid r, timestamps
    shifted onto rank 0's clock. Returns
    ``{"traceEvents": [...], "metadata": {...}}``."""
    merged: List[dict] = []
    lanes = []
    unaligned = []
    used_ranks = set()
    for i, path in enumerate(sorted(paths)):
        events, rank, embedded = load_trace(path)
        if rank is None or rank in used_ranks:
            rank = i if i not in used_ranks else max(used_ranks) + 1
        used_ranks.add(rank)
        off = None
        if offsets is not None and rank in offsets:
            off = float(offsets[rank])
        elif embedded is not None:
            off = embedded
        if off is None:
            off = 0.0
            if rank != 0:
                unaligned.append(rank)
        shift_us = -off * 1e6
        lane_events = []
        for ev in events:
            ev = dict(ev)
            if ev.get("ph") == "M" and ev.get("name") in (
                    "process_name", "clock_sync"):
                continue            # re-emitted per lane below
            ev["pid"] = rank
            if "ts" in ev:
                ev["ts"] = int(ev["ts"] + shift_us)
            lane_events.append(ev)
        merged.extend(lane_events)
        lanes.append({"rank": rank, "file": os.path.basename(path),
                      "events": len(lane_events),
                      "offset_vs_rank0_s": off})
        merged.append({"name": "process_name", "ph": "M", "pid": rank,
                       "args": {"name": f"rank {rank}"}})
        merged.append({"name": "process_sort_index", "ph": "M",
                       "pid": rank, "args": {"sort_index": rank}})
    merged.sort(key=lambda e: (e.get("ts", -1), e.get("pid", 0)))
    return {"traceEvents": merged,
            "metadata": {"tool": "paddle_tpu tools/fleet_trace.py",
                         "lanes": lanes,
                         "unaligned_ranks": unaligned}}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("traces", nargs="+",
                    help="per-rank chrome trace files (globs ok)")
    ap.add_argument("--out", required=True, help="merged trace path")
    ap.add_argument("--offsets",
                    help="JSON file {rank: offset_seconds_vs_rank0} "
                    "overriding the embedded clock_sync metadata")
    args = ap.parse_args(argv)

    paths: List[str] = []
    for pat in args.traces:
        hits = sorted(glob.glob(pat))
        paths.extend(hits if hits else [pat])
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print(f"missing trace file(s): {missing}", file=sys.stderr)
        return 1

    offsets = None
    if args.offsets:
        with open(args.offsets) as f:
            raw = json.load(f)
        # accept a bare offsets map or a fleet.dump snapshot
        if "clock" in raw and isinstance(raw.get("clock"), dict):
            raw = raw["clock"].get("offsets", {})
        elif "offsets" in raw:
            raw = raw["offsets"]
        offsets = {int(k): float(v) for k, v in raw.items()}

    out = merge_traces(paths, offsets=offsets)
    with open(args.out, "w") as f:
        json.dump(out, f)
    lanes = out["metadata"]["lanes"]
    print(f"merged {len(lanes)} rank lane(s), "
          f"{len(out['traceEvents'])} events -> {args.out}")
    overlap = transfer_compute_overlap(out)
    for pid, o in overlap.items():
        if o["io_s"] or o["device_s"]:
            print(f"  rank {pid}: transfer {o['io_s'] * 1e3:.1f} ms / "
                  f"compute {o['device_s'] * 1e3:.1f} ms — "
                  f"{o['overlap_s'] * 1e3:.1f} ms overlapped "
                  f"({o['overlap_frac_of_io'] * 100:.0f}% of transfer "
                  f"hidden)")
    for lane in lanes:
        print(f"  rank {lane['rank']}: {lane['events']} events, "
              f"offset {lane['offset_vs_rank0_s'] * 1e3:+.3f} ms "
              f"({lane['file']})")
    if out["metadata"]["unaligned_ranks"]:
        print(f"WARNING: no clock offset for ranks "
              f"{out['metadata']['unaligned_ranks']} — their lanes are "
              f"NOT aligned (run fleet.clock_sync before export, or "
              f"pass --offsets)", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
