"""Render serving request timelines: waterfalls + chrome-trace lanes.

The request flight recorder (``paddle_tpu/observability/reqtrace.py``)
answers *why request 4711 took 900 ms*; this tool renders the answer two
ways:

* **terminal waterfall** — one request's lifecycle events with relative
  timestamps, inter-event deltas and cause metadata, followed by its
  exact ``queue / prefill / decode / preempted / rerouted`` wall-segment
  decomposition (the per-request analogue of ``tools/perf_report.py``'s
  step attribution);
* **chrome trace** — one lane per request whose bars ARE the segment
  intervals (plus instant marks for every raw event), merged on one
  clock with the engine's device spans (``serving.tick`` host spans and
  the blocking-read-bracketed ``serving.{prefill,decode}`` device
  spans) so "my request sat in queue" lines up against "the chip was
  busy prefilling someone else's prompt". Both reqtrace timestamps and
  span timestamps are monotonic-clock seconds (one epoch on Linux), so
  the merge needs no offset arithmetic.

Inputs: a reqtrace dump (``PADDLE_TPU_REQTRACE=/path`` → ``/path.r0``;
the watchdog writes one from the hang path too), or the live process
recorder when used as a library (``tools/loadgen.py --trace-out`` rides
this module per curve point). Router-scope timelines are stitched with
their replica legs through the ``routed`` events before rendering.

CLI::

    # worst-k TTFT exemplars from a dump, waterfalls + merged trace
    python tools/request_trace.py --dump /tmp/reqtrace.json.r0 \
        --worst 3 --out merged_trace.json

    # one specific request, merging the profiler's chrome trace
    python tools/request_trace.py --dump /tmp/reqtrace.json.r0 \
        --scope router0 --rid 17 --merge-trace worker_r0_host_ops.json
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence, Tuple


def _reqtrace():
    from paddle_tpu.observability import reqtrace
    return reqtrace


# ---------------------------------------------------------------------------
# Timeline selection (live recorder or dump payload)
# ---------------------------------------------------------------------------
class TimelineSource:
    """Uniform lookup over a dump payload or the live process recorder."""

    def __init__(self, payload: Optional[dict] = None):
        self._payload = payload
        self._index: Dict[Tuple[str, int], dict] = {}
        if payload is not None:
            for tl in payload.get("timelines", ()):
                self._index[(tl["scope"], int(tl["rid"]))] = tl

    def lookup(self, scope: str, rid: int) -> Optional[dict]:
        if self._payload is not None:
            return self._index.get((str(scope), int(rid)))
        return _reqtrace().RECORDER.timeline(scope, rid)

    def timelines(self) -> List[dict]:
        if self._payload is not None:
            return list(self._payload.get("timelines", ()))
        rt = _reqtrace()
        return rt.RECORDER.tail() + rt.RECORDER.live_timelines()

    def exemplars(self, kind: str = "ttft") -> List[dict]:
        if self._payload is not None:
            return list(
                (self._payload.get("exemplars") or {}).get(kind, ()))
        return _reqtrace().EXEMPLARS.worst(kind)

    def resolve(self, scope: str, rid: int) -> Optional[dict]:
        """Timeline for (scope, rid), stitched with replica legs when it
        is a router-scope timeline (detected by ``routed`` events)."""
        tl = self.lookup(scope, rid)
        if tl is None:
            return None
        if any(e["event"] == "routed" for e in tl.get("events", ())):
            tl = _reqtrace().stitch(tl, lookup=self.lookup)
        return tl

    def worst(self, k: int = 4, kind: str = "ttft") -> List[dict]:
        """Stitched timelines of the worst-k ``kind`` exemplars (falls
        back to the slowest total-wall timelines when no exemplars were
        recorded, e.g. an all-shed storm)."""
        out, seen = [], set()
        for ex in self.exemplars(kind):
            key = (ex["scope"], ex["rid"])
            if key in seen:          # ITL exemplars repeat request ids
                continue
            tl = self.resolve(*key)
            if tl is not None:
                out.append(tl)
                seen.add(key)
            if len(out) >= k:
                return out
        if not out:
            ranked = sorted(
                self.timelines(),
                key=lambda t: -_reqtrace().segments(t)["total"])
            out = [self.resolve(t["scope"], t["rid"]) or t
                   for t in ranked[:k]]
        return out[:k]


# ---------------------------------------------------------------------------
# Terminal waterfall
# ---------------------------------------------------------------------------
def _fmt_meta(meta: Optional[dict]) -> str:
    if not meta:
        return ""
    parts = []
    for k, v in meta.items():
        if isinstance(v, float):
            v = round(v, 6)
        if isinstance(v, str) and len(v) > 48:
            v = v[:45] + "..."
        parts.append(f"{k}={v}")
    return "  " + " ".join(parts)


def waterfall(timeline: dict) -> str:
    """One request's timeline as indented text: relative time, delta
    from the previous event, event name + metadata, then the segment
    decomposition line."""
    rt = _reqtrace()
    evs = timeline.get("events", ())
    lines = []
    seg = rt.segments(timeline)
    outcome = next((
        (e.get("meta") or {}).get("outcome")
        for e in reversed(evs) if e["event"] == "terminal"), "<live>")
    head = (f"request {timeline.get('scope')}/rid={timeline.get('rid')}"
            f"  outcome={outcome}  total={seg['total'] * 1e3:.2f}ms")
    if timeline.get("stitched"):
        head += "  (stitched across replicas)"
    lines.append(head)
    t0 = evs[0]["t"] if evs else 0.0
    prev = t0
    for e in evs:
        rel = (e["t"] - t0) * 1e3
        delta = (e["t"] - prev) * 1e3
        prev = e["t"]
        scope = f" [{e['scope']}]" if "scope" in e else ""
        lines.append(f"  {rel:10.3f}ms  (+{delta:8.3f}ms)  "
                     f"{e['event']:<16}{scope}{_fmt_meta(e.get('meta'))}")
    parts = []
    for b in rt.SEGMENT_BUCKETS:
        if seg[b] > 0:
            share = seg[b] / seg["total"] * 100 if seg["total"] else 0.0
            parts.append(f"{b} {seg[b] * 1e3:.2f}ms ({share:.0f}%)")
    lines.append("  segments: " + (" | ".join(parts) or "<empty>")
                 + ("" if seg["complete"] else "  [INCOMPLETE]"))
    problems = rt.validate(timeline)
    for p in problems:
        lines.append(f"  WARNING: {p}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Chrome trace
# ---------------------------------------------------------------------------
#: pid lanes in the merged trace
_PID_DEVICE = 0
_PID_REQUESTS = 1


def chrome_trace(timelines: Sequence[dict],
                 spans: Optional[Sequence] = None,
                 merge_events: Optional[Sequence[dict]] = None) -> dict:
    """One chrome trace: request lanes (segment bars + event marks) on
    a ``requests`` pid, optional engine spans (``trace.drain()``-style
    ``(name, cat, t0, t1, tid, args)`` tuples) on a ``device`` pid, and
    optional pre-rendered chrome events merged verbatim (a profiler
    export — same perf_counter*1e6 timebase)."""
    rt = _reqtrace()
    events: List[dict] = [
        {"name": "process_name", "ph": "M", "pid": _PID_REQUESTS,
         "args": {"name": "requests"}},
        {"name": "process_sort_index", "ph": "M", "pid": _PID_REQUESTS,
         "args": {"sort_index": 1}},
    ]
    for lane, tl in enumerate(timelines):
        # lane index, not the raw rid: two scopes may reuse a rid, and
        # a shared tid would merge their lanes in the viewer
        tid = lane
        label = f"{tl.get('scope')}/rid={tl['rid']}"
        events.append({"name": "thread_name", "ph": "M",
                       "pid": _PID_REQUESTS, "tid": tid,
                       "args": {"name": label}})
        intervals, _complete = rt.segment_intervals(tl)
        for state, t0, t1 in intervals:
            events.append({
                "name": state, "cat": "request", "ph": "X",
                "pid": _PID_REQUESTS, "tid": tid,
                "ts": int(t0 * 1e6),
                "dur": max(int((t1 - t0) * 1e6), 1)})
        for e in tl.get("events", ()):
            args = {"scope": e.get("scope", tl.get("scope"))}
            if e.get("meta"):
                args.update(e["meta"])
            events.append({
                "name": e["event"], "cat": "request_event", "ph": "i",
                "s": "t", "pid": _PID_REQUESTS, "tid": tid,
                "ts": int(e["t"] * 1e6), "args": args})
    if spans:
        events.append({"name": "process_name", "ph": "M",
                       "pid": _PID_DEVICE, "args": {"name": "device"}})
        events.append({"name": "process_sort_index", "ph": "M",
                       "pid": _PID_DEVICE, "args": {"sort_index": 0}})
        for name, cat, t0, t1, tid, args in spans:
            events.append({
                "name": name, "cat": cat, "ph": "X",
                "pid": _PID_DEVICE, "tid": int(tid),
                "ts": int(t0 * 1e6),
                "dur": max(int((t1 - t0) * 1e6), 0),
                "args": args or {}})
    if merge_events:
        events.extend(merge_events)
    events.sort(key=lambda e: (e.get("ts", -1), e.get("pid", 0)))
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def export(path: str, timelines: Sequence[dict],
           spans: Optional[Sequence] = None,
           merge_events: Optional[Sequence[dict]] = None) -> str:
    """Write the merged chrome trace; returns ``path``."""
    with open(path, "w") as f:
        json.dump(chrome_trace(timelines, spans=spans,
                               merge_events=merge_events), f)
    return path


def serving_spans(spans: Sequence) -> List:
    """Filter ``trace.drain()`` output down to the serving timeline:
    per-tick host spans and the prefill/decode device spans."""
    return [s for s in spans
            if s[1] in ("serving", "device")
            and (s[0].startswith("serving") or s[1] == "serving")]


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------
def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--dump", help="reqtrace dump file "
                    "(PADDLE_TPU_REQTRACE path + .r<rank>)")
    ap.add_argument("--scope", help="timeline scope (replica/router "
                    "name); with --rid selects one request")
    ap.add_argument("--rid", type=int, help="request id within --scope")
    ap.add_argument("--worst", type=int, default=0, metavar="K",
                    help="render the K worst-TTFT exemplar timelines")
    ap.add_argument("--kind", default="ttft", choices=("ttft", "itl"),
                    help="exemplar metric for --worst")
    ap.add_argument("--out", help="write a merged chrome trace here")
    ap.add_argument("--merge-trace", metavar="CHROME_JSON",
                    help="profiler chrome trace whose events (device "
                    "spans) are merged into --out on the same clock")
    ap.add_argument("--list", action="store_true",
                    help="list the dump's timelines and exit")
    args = ap.parse_args(argv)

    if not args.dump:
        ap.error("--dump is required (library callers use "
                 "TimelineSource directly)")
    rt = _reqtrace()
    src = TimelineSource(rt.load_dump(args.dump))

    if args.list:
        for tl in src.timelines():
            seg = rt.segments(tl)
            outcome = next((
                (e.get("meta") or {}).get("outcome")
                for e in reversed(tl.get("events", ()))
                if e["event"] == "terminal"), "<live>")
            print(f"{tl['scope']}/rid={tl['rid']}  {outcome}  "
                  f"total={seg['total'] * 1e3:.2f}ms  "
                  f"events={len(tl.get('events', ()))}")
        return 0

    if args.rid is not None:
        if not args.scope:
            ap.error("--rid needs --scope")
        tl = src.resolve(args.scope, args.rid)
        if tl is None:
            print(f"no timeline for {args.scope}/rid={args.rid} "
                  f"(evicted, or recorded under another scope)")
            return 1
        picked = [tl]
    else:
        picked = src.worst(args.worst or 3, kind=args.kind)
        if not picked:
            print("dump holds no timelines")
            return 1

    for tl in picked:
        print(waterfall(tl))
        print()

    if args.out:
        merge = None
        if args.merge_trace:
            with open(args.merge_trace) as f:
                merge = json.load(f).get("traceEvents", [])
        export(args.out, picked, merge_events=merge)
        print(f"chrome trace written: {args.out} "
              f"({len(picked)} request lane(s)"
              + (f" + {len(merge)} merged device events" if merge
                 else "") + ")")
    return 0


if __name__ == "__main__":
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    raise SystemExit(main())
