"""Print the paddle_tpu metrics snapshot (Prometheus text or JSON).

Thin wrapper over ``python -m paddle_tpu.observability``:

    python tools/metrics_dump.py                       # live registry
    python tools/metrics_dump.py --format json
    python tools/metrics_dump.py --input /tmp/metrics.json
    python tools/metrics_dump.py --merge /tmp/metrics.json

Pair with ``FLAGS_enable_metrics=1 PADDLE_TPU_METRICS_DUMP=/tmp/metrics.json``
on any training/serving run to capture a snapshot at exit, then render it
here offline. Multi-process runs write one file per process
(``.rankN`` for distributed ranks, ``.pidN`` for worker children);
``--merge`` folds the whole set into one aggregate with a leading
``rank`` label per series — see README "Fleet observability".
"""
import sys

from paddle_tpu.observability.__main__ import main

if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
