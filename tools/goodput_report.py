"""Render the training goodput ledger + sentinel incident timeline.

The goodput ledger (``paddle_tpu/observability/goodput.py``) partitions a
run's wall clock into badput buckets; the sentinel ring-buffers typed
anomaly incidents. This tool renders both as a markdown table + incident
timeline (or JSON) from any of:

* the **live process** (library use / REPL) — ledger + sentinel
  singletons;
* one or more **rank dumps** — ``PADDLE_TPU_GOODPUT=/path`` makes every
  rank write ``/path.r<rank>`` at exit (the watchdog hang path writes
  one too); ``--dump /path`` merges the whole set and reports the
  job-level goodput as the **min over ranks** (a pod is as good as its
  worst rank);
* a saved **fleet snapshot** (``fleet.snapshot()`` JSON, which carries a
  ``goodput`` + ``sentinel`` entry per rank).

CLI::

    python tools/goodput_report.py --dump /tmp/goodput.json
    python tools/goodput_report.py --dump /tmp/goodput.json --json
    python tools/goodput_report.py --snapshot /tmp/fleet_snap.json
"""
from __future__ import annotations

import argparse
import json
from typing import List, Optional


def _goodput():
    from paddle_tpu.observability import goodput
    return goodput


# ---------------------------------------------------------------------------
# Collection: rank records from dumps / fleet snapshot / live process
# ---------------------------------------------------------------------------
def collect(dump_base: Optional[str] = None,
            snapshot_path: Optional[str] = None) -> List[dict]:
    """Uniform per-rank records: ``{"rank", "goodput", "sentinel"}``."""
    if dump_base is not None:
        payloads = _goodput().merge_dumps(dump_base)
        if not payloads:
            raise SystemExit(f"no goodput dumps match {dump_base}.r*")
        return [{"rank": p.get("rank", 0), "goodput": p["goodput"],
                 "sentinel": p.get("sentinel") or {}} for p in payloads]
    if snapshot_path is not None:
        with open(snapshot_path) as f:
            snap = json.load(f)
        ranks = snap.get("ranks") or [snap]   # fleet.snapshot() or local
        out = []
        for r in ranks:
            if r.get("goodput") is None:
                continue
            out.append({"rank": r.get("rank", 0), "goodput": r["goodput"],
                        "sentinel": r.get("sentinel") or {}})
        if not out:
            raise SystemExit(f"{snapshot_path}: no goodput entries")
        return out
    from paddle_tpu.observability import sentinel
    return [{"rank": 0, "goodput": _goodput().ledger().snapshot(),
             "sentinel": sentinel.get().snapshot()}]


def job_report(records: List[dict]) -> dict:
    """Per-rank accounts + the job-level (min-over-ranks) goodput."""
    per_rank = []
    for rec in records:
        g = rec["goodput"]
        per_rank.append({
            "rank": rec["rank"],
            "wall_s": g.get("wall_s", 0.0),
            "goodput_fraction": g.get("goodput_fraction", 0.0),
            "buckets": g.get("buckets", {}),
            "steps": g.get("steps", 0),
            "rewind_steps": g.get("rewind_steps", 0),
            "incidents": (rec.get("sentinel") or {}).get("incidents", []),
        })
    worst = min(per_rank, key=lambda r: r["goodput_fraction"],
                default=None)
    return {
        "ranks": per_rank,
        "job_goodput_fraction": (worst["goodput_fraction"]
                                 if worst else 0.0),
        "worst_rank": worst["rank"] if worst else None,
    }


# ---------------------------------------------------------------------------
# Rendering
# ---------------------------------------------------------------------------
def render_markdown(report: dict) -> str:
    gp = _goodput()
    lines = ["# Goodput report", ""]
    lines.append(f"Job goodput (min over ranks): "
                 f"**{report['job_goodput_fraction']:.1%}** "
                 f"(worst rank: {report['worst_rank']})")
    lines.append("")
    header = "| rank | wall (s) | goodput | " + \
        " | ".join(gp.BUCKETS) + " | steps | rewound |"
    sep = "|" + "---|" * (len(gp.BUCKETS) + 5)
    lines += [header, sep]
    for r in report["ranks"]:
        b = r["buckets"]
        cells = [str(r["rank"]), f"{r['wall_s']:.1f}",
                 f"{r['goodput_fraction']:.1%}"]
        cells += [f"{b.get(k, 0.0):.2f}" for k in gp.BUCKETS]
        cells += [str(r["steps"]), str(r["rewind_steps"])]
        lines.append("| " + " | ".join(cells) + " |")
    lines.append("")
    lines.append("## Incident timeline")
    lines.append("")
    rows = []
    for r in report["ranks"]:
        for inc in r["incidents"]:
            rows.append((inc.get("step", 0), r["rank"], inc))
    if not rows:
        lines.append("(no incidents)")
    else:
        lines.append("| step | rank | kind | detail | dominant bucket |")
        lines.append("|---|---|---|---|---|")
        for step, rank, inc in sorted(rows, key=lambda x: (x[0], x[1])):
            dom = (inc.get("diff") or {}).get("dominant_bucket") or "-"
            lines.append(f"| {step} | {rank} | {inc.get('kind')} | "
                         f"{inc.get('detail')} | {dom} |")
    lines.append("")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Goodput ledger table + sentinel incident timeline")
    ap.add_argument("--dump", metavar="BASE",
                    help="PADDLE_TPU_GOODPUT base path; merges BASE.r*")
    ap.add_argument("--snapshot", metavar="FILE",
                    help="fleet.snapshot() JSON file")
    ap.add_argument("--json", action="store_true",
                    help="emit the report as JSON instead of markdown")
    ap.add_argument("--out", metavar="FILE",
                    help="write the report here instead of stdout")
    args = ap.parse_args(argv)

    records = collect(dump_base=args.dump, snapshot_path=args.snapshot)
    report = job_report(records)
    text = (json.dumps(report, indent=1, default=str) if args.json
            else render_markdown(report))
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
    else:
        print(text)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
