"""Op-parity audit against the reference op registry.

Diffs paddle_tpu's registered op surface (paddle_tpu/ops/registry.py — the
source of truth, auto-populated from every op module) against the reference
YAML op registry (reference: paddle/phi/ops/yaml/ops.yaml `- op : name`
entries, plus legacy/legacy_ops.yaml). Writes OP_PARITY.md at the repo root.

Run:  python tools/op_parity_audit.py [--ref /root/reference]
"""
from __future__ import annotations

import argparse
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

OP_RE = re.compile(r"^- *(?:backward_)?op *: *([a-zA-Z0-9_]+)")

# reference op -> our canonical name when they differ only by spelling
ALIASES = {
    "matmul": "matmul", "elementwise_add": "add", "elementwise_sub":
    "subtract", "elementwise_mul": "multiply", "elementwise_div": "divide",
    "reduce_sum": "sum", "reduce_mean": "mean", "reduce_max": "max",
    "reduce_min": "min", "reduce_prod": "prod", "fill_constant": "full",
    "top_k": "topk", "arg_max": "argmax", "arg_min": "argmin",
    "softmax_with_cross_entropy": "cross_entropy",
    "deformable_conv": "deform_conv2d", "multiclass_nms3": "multiclass_nms",
    "unpool": "max_unpool2d", "unpool3d": "max_unpool3d",
    "warprnnt": "rnnt_loss", "graph_sample_neighbors": "sample_neighbors",
    "graph_reindex": "reindex_graph",
    # in-graph control flow (static/nn/control_flow.py): the reference's
    # `while` op is our while_loop; conditional_block registers same-name
    "while": "while_loop",
}

# reference ops that are CUDA/infra-specific and have no TPU-user surface:
# fused kernels XLA produces itself, quant/ps infra, mobile ops.
# NOTE the fused-op class (round 15): reference fused kernels our
# compile/fusion rewrite targets cover are claimed by SUBSUMED below
# (checked BEFORE these prefixes) or by same-name registration
# (fused_bias_act registers under the reference's exact name) — the
# `fused_`/`fusion_` exclusion only absorbs the remainder (CUDA-only
# epilogue/attention variants XLA or flash_attention already covers).
EXCLUDE_PREFIXES = (
    "fused_", "fusion_", "c_", "distributed_", "partial_", "push_",
    "pull_", "onednn_", "xpu_", "dgc", "nop", "share_", "memcpy",
    "quantize", "dequantize", "fake_quantize", "fake_dequantize",
    "sparse_", "coalesce",
    # parameter-server / tree-based-recommender infra (L4 PS mode — the
    # TPU design replaces the PS path wholesale with SPMD sharding):
    "pyramid_hash", "tdm_", "rank_attention", "shuffle_batch_",
    # legacy LoD (variable-length static-graph) sequence kernels; varlen
    # here is flash_attn_unpadded / padding-mask based, not LoD tensors
    "sequence_conv", "sequence_pool",
    # channel-wise fake-quant observers (quantization.fake_quant covers
    # the capability; channel-wise handled inside PTQ/QAT observers)
    "fake_channel_wise_",
)

# reference ops whose capability lives at a different API level here —
# the TPU-native design deliberately covers these via the named surface
SUBSUMED = {
    # optimizer kernels -> paddle_tpu.optimizer classes (one jitted step)
    **{k: "optimizer" for k in (
        "sgd_", "momentum_", "adam_", "adamw_", "adamax_", "adagrad_",
        "adadelta_", "asgd_", "lamb_", "rmsprop_", "nadam_", "radam_",
        "rprop_", "merged_adam_", "merged_momentum_",
        "average_accumulates_", "decayed_adagrad")},
    # AMP loss-scaling kernels -> amp.GradScaler
    "check_finite_and_unscale_": "amp.GradScaler",
    "update_loss_scaling_": "amp.GradScaler",
    # FFT kernels -> paddle_tpu.fft
    "fft_c2c": "fft", "fft_c2r": "fft", "fft_r2c": "fft",
    # attention library kernels -> nn.functional.flash_attention (Pallas)
    "flash_attn": "nn.functional.flash_attention",
    "flash_attn_qkvpacked": "nn.functional.flash_attention",
    "memory_efficient_attention": "nn.functional.flash_attention",
    "masked_multihead_attention_": "nn.functional.flash_attention",
    # cudnn RNN kernels -> nn.LSTM/GRU/SimpleRNN (lax.scan stacks)
    "cudnn_lstm": "nn.LSTM", "lstm": "nn.LSTM", "gru": "nn.GRU",
    "gru_unit": "nn.GRUCell", "rnn": "nn.RNN",
    # metric kernels -> paddle_tpu.metric
    "accuracy": "metric.Accuracy", "auc": "metric.Auc",
    "accuracy_check": "metric.Accuracy",
    # distribution samplers -> paddle_tpu.distribution
    "dirichlet": "distribution", "binomial": "distribution",
    "standard_gamma": "distribution",
    "truncated_gaussian_random": "nn.initializer.TruncatedNormal",
    # signal kernels -> paddle_tpu.signal
    "stft": "signal.stft",
    # MoE routing kernels -> fleet.MoELayer dispatch/combine einsums
    "moe": "fleet.MoELayer", "number_count": "fleet.MoELayer",
    "assign_pos": "fleet.MoELayer", "limit_by_capacity": "fleet.MoELayer",
    "prune_gate_by_capacity": "fleet.MoELayer",
    "random_routing": "fleet.MoELayer",
    # control-flow program plumbing: branch-output merge ops have no
    # separate surface — the cond/switch_case op boundary IS the merge
    # (lax.cond/lax.switch return the selected branch's outputs)
    "select_input": "static.nn.cond (lax.cond output merge)",
    "select_output": "static.nn.cond (lax.cond output merge)",
    # program/IR plumbing ops with no eager surface
    "data": "jit/to_static", "full_int_array": "jit/to_static",
    "assign_out_": "jit/to_static", "increment": "ops.increment",
    "depend": "jit/to_static", "copy_to": "Tensor.to",
    "shape": "Tensor.shape", "is_empty": "Tensor.size",
    "view_dtype": "Tensor.astype", "view_shape": "Tensor.reshape",
    "trans_layout": "Tensor.transpose",
    "sync_batch_norm_": "nn.SyncBatchNorm",
    "spectral_norm": "nn.SpectralNorm",
    "warpctc": "nn.functional.ctc_loss",
    "sigmoid_cross_entropy_with_logits":
        "nn.functional.binary_cross_entropy_with_logits",
    "bce_loss": "nn.functional.binary_cross_entropy",
    "kldiv_loss": "nn.functional.kl_div",
    "cross_entropy_with_softmax": "nn.functional.cross_entropy",
    "margin_cross_entropy": "fleet.ParallelCrossEntropy",
    "mean_all": "ops.mean", "reverse": "ops.flip",
    "split_with_num": "ops.split", "fill": "ops.full_like",
    "full_": "ops.full", "full_with_tensor": "ops.full",
    "full_batch_size_like": "ops.full",
    "uniform_inplace": "ops.uniform",
    "uniform_random_batch_size_like": "ops.uniform",
    "gaussian_inplace": "ops.normal",
    "frobenius_norm": "linalg.norm", "l1_norm": "linalg.norm",
    "squared_l2_norm": "linalg.norm", "clip_by_norm": "nn.clip",
    "matrix_rank_tol": "linalg.matrix_rank",
    "max_pool2d_with_index": "nn.functional.max_pool2d",
    "max_pool3d_with_index": "nn.functional.max_pool3d",
    "pool2d": "nn.functional.avg_pool2d",
    "pool3d": "nn.functional.avg_pool3d",
    "linear_interp": "nn.functional.interpolate",
    "bilinear_interp": "nn.functional.interpolate",
    "bicubic_interp": "nn.functional.interpolate",
    "nearest_interp": "nn.functional.interpolate",
    "trilinear_interp": "nn.functional.interpolate",
    "depthwise_conv2d": "nn.functional.conv2d(groups=)",
    "depthwise_conv2d_transpose": "nn.functional.conv2d_transpose",
    "conv2d_transpose_bias": "nn.functional.conv2d_transpose",
    "identity_loss": "ops.mean", "huber_loss": "nn.functional.huber_loss",
    "tanh_shrink": "nn.functional.tanhshrink",
    "logsigmoid": "nn.functional.log_sigmoid",
    "repeat_interleave_with_tensor_index": "ops.repeat_interleave",
    "index_select_strided": "ops.index_select",
    "tensor_unfold": "ops.unfold", "as_strided": "ops.strided_slice",
    "set_value_with_tensor": "Tensor.set_value",
    "enable_check_model_nan_inf": "amp.debugging",
    "disable_check_model_nan_inf": "amp.debugging",
    "check_numerics": "amp.debugging",
    "npu_identity": "ops.assign",
    "assign_value_": "ops.assign",
    "viterbi_decode": "text.viterbi_decode",
    "crf_decoding": "text.viterbi_decode",
    "chunk_eval": "metric.chunk_eval",
    "detection_map": "metric.DetectionMAP",
    "edit_distance": "nn.functional.edit_distance",
    "ctc_align": "nn.functional.ctc_align",
    "flash_attn_unpadded": "nn.functional.flash_attn_unpadded",
    "flash_attn_varlen_qkvpacked": "nn.functional.flash_attn_unpadded",
    "flash_attn_with_sparse_mask": "nn.functional.flash_attention(mask)",
    "block_multihead_attention_": "nn.functional.flash_attention + KV cache",
    "segment_pool": "geometric.segment_sum/mean/min/max",
    "graph_khop_sampler": "geometric.sample_neighbors (per hop) + reindex",
    "weighted_sample_neighbors": "geometric.weighted_sample_neighbors",
    "reindex_graph": "geometric.reindex_graph",
    "send_u_recv": "geometric.send_u_recv",
    "send_ue_recv": "geometric.send_ue_recv",
    "send_uv": "geometric.send_uv",
    "merge_selected_rows": "framework.SelectedRows",
    "shuffle_channel": "nn.functional.channel_shuffle",
    "pad3d": "nn.functional.pad (NCDHW)",
    "yolo_box_head": "vision.ops.yolo_box",
    "yolo_box_post": "vision.ops.yolo_box + multiclass_nms",
    "weight_quantize": "quantization.weight_quantize",
    "weight_dequantize": "quantization.weight_dequantize",
    "weight_only_linear": "quantization.weight_only_linear",
    "llm_int8_linear": "quantization.llm_int8_linear",
    "apply_per_channel_scale": "quantization.apply_per_channel_scale",
    "hsigmoid_loss": "nn.functional.hsigmoid_loss",
    # fused multi-op kernels (reference paddle/phi/kernels/fusion/) ->
    # first-class fused OpDefs targeted by the compile/fusion pass
    "fused_layernorm": "ops.fused_residual_norm (residual in-pass)",
    "fused_bias_residual_layernorm":
        "ops.fused_residual_norm (residual in-pass)",
    "fused_rms_norm": "ops.fused_residual_norm (rms_norm kind)",
    "fused_rotary_position_embedding":
        "ops.fused_rope_proj (rope folded into the projection)",
    "fused_gemm_epilogue":
        "ops.fused_norm_linear (bias/act GEMM epilogue)",
    "fused_linear_param_grad_add":
        "ops.fused_norm_linear (grad via composite recompute)",
    "fc": "ops.fused_norm_linear (norm_type='')",
}

# registry categories audited as a CLASS: every op in these categories
# must carry doc/cost/spmd coverage — tools/fusion_audit.py enforces it
# and writes FUSION.md; here they are exempt from the 'extra ops with no
# yaml counterpart' noise list (they exist to REPLACE yaml fused ops)
CLASS_AUDITED_CATEGORIES = ("fusion",)


def reference_ops(ref_root: str):
    names = set()
    yaml_dir = os.path.join(ref_root, "paddle/phi/ops/yaml")
    for fname in ("ops.yaml", os.path.join("legacy", "ops.yaml"),
                  "legacy_ops.yaml"):
        path = os.path.join(yaml_dir, fname)
        if not os.path.exists(path):
            continue
        with open(path) as f:
            for line in f:
                m = OP_RE.match(line.strip())
                if m:
                    names.add(m.group(1))
    return names


def our_ops():
    # one definition of "the op surface": tpulint's registry loader (it is
    # also what the TPU3xx consistency pass audits)
    from tools.tpulint.registry_check import load_registry
    return dict(load_registry())


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ref", default="/root/reference")
    ap.add_argument("--out", default=os.path.join(REPO, "OP_PARITY.md"))
    args = ap.parse_args()

    ref = reference_ops(args.ref)
    ours = our_ops()
    our_names = set(ours)

    covered, missing, excluded, subsumed = [], [], [], []
    for op in sorted(ref):
        target = ALIASES.get(op, op)
        if target in our_names or op in our_names:
            covered.append(op)
        elif op in SUBSUMED:
            subsumed.append((op, SUBSUMED[op]))
        elif op.startswith(EXCLUDE_PREFIXES) or op.endswith(
                ("_grad", "_xpu", "_mkldnn")):
            excluded.append(op)
        else:
            missing.append(op)

    class_audited = sorted(
        n for n, d in ours.items()
        if getattr(d, "category", None) in CLASS_AUDITED_CATEGORIES)
    extra = sorted(our_names - ref
                   - {ALIASES.get(o, o) for o in ref}
                   - set(class_audited))
    n_cov = len(covered) + len(subsumed)
    pct = 100.0 * n_cov / max(n_cov + len(missing), 1)

    with open(args.out, "w") as f:
        f.write("# Op parity audit\n\n")
        f.write(f"Generated by `python tools/op_parity_audit.py` against "
                f"`{args.ref}` yaml registries.\n\n")
        f.write(f"| | count |\n|---|---|\n")
        f.write(f"| reference ops (yaml) | {len(ref)} |\n")
        f.write(f"| covered (same-name/alias op) | {len(covered)} |\n")
        f.write(f"| covered (subsumed by an API surface) | "
                f"{len(subsumed)} |\n")
        f.write(f"| missing (user-relevant) | {len(missing)} |\n")
        f.write(f"| excluded (CUDA/infra-only) | {len(excluded)} |\n")
        f.write(f"| paddle_tpu registered ops | {len(ours)} |\n")
        f.write(f"| coverage of user-relevant | {pct:.1f}% |\n\n")
        f.write("## Missing (user-relevant)\n\n")
        for op in missing:
            f.write(f"- `{op}`\n")
        f.write("""
## Caveats on subsumption claims

"Subsumed" means the *capability* exists behind a different API — NOT a
drop-in op. Users porting reference code should note in particular:

- `graph_khop_sampler` → composition: call `geometric.sample_neighbors`
  once per hop and `geometric.reindex_graph` yourself; there is no single
  fused k-hop call.
- `yolo_box_post` → composition of `vision.ops.yolo_box` +
  `vision.ops.multiclass_nms`; the fused post-process op does not exist.
- optimizer kernel ops (`adam_`, `sgd_`, ...) are subsumed by the
  `optimizer` package's jitted pytree step — there is no per-op
  functional form.
- `sequence_conv` / `sequence_pool` / `fake_channel_wise_*` are
  **excluded** (LoD-sequence and simulated-quant infrastructure), not
  re-expressed; code using them must be rewritten against padded-batch
  ops / the `quantization` package.

## Exact-parity limits (the reference has the same restriction)

- `signal.frame` / `signal.overlap_add`: axis in {0, -1} — the reference
  raises for other axes too (python/paddle/signal.py:104).
- `audio.backends.save`: PCM_16 only — the reference wave_backend
  supports only 16-bit PCM (python/paddle/audio/backends/wave_backend.py
  save docstring).

""")
        f.write("## Subsumed (capability at a different API level)\n\n")
        f.write("| reference op | covered by |\n|---|---|\n")
        for op, via in subsumed:
            f.write(f"| `{op}` | `{via}` |\n")
        f.write("\n## Fused-op class (category `fusion`)\n\n")
        f.write("Rewrite targets of the compile/fusion pass, standing in "
                "for the reference's fused_ops.yaml hot set. Coverage "
                "(docstring / cost model / spmd rule / kernel+composite "
                "pair) is audited per op by `python tools/fusion_audit.py`"
                " (fails loudly; writes FUSION.md).\n\n")
        f.write(", ".join(f"`{e}`" for e in class_audited) + "\n")
        f.write("\n## Ours with no yaml counterpart (composite/API-level)"
                "\n\n")
        f.write(", ".join(f"`{e}`" for e in extra) + "\n")
    print(f"coverage {pct:.1f}%  covered={len(covered)} "
          f"subsumed={len(subsumed)} missing={len(missing)} "
          f"excluded={len(excluded)} registered={len(ours)} -> {args.out}")


if __name__ == "__main__":
    main()
