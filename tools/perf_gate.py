"""Perf regression gate — bench run vs frozen baseline, with teeth.

Compares a ``bench.py`` JSON output against a frozen baseline
(``tools/perf_baseline.json``) with per-rung tolerances and exits
non-zero on regression — the CI gate every future perf PR is judged
against.

Inputs are tolerant of how bench output gets captured: a raw JSON-lines
stream (one ``{"metric": …}`` object per line), a driver wrapper dict
with the stream in a ``"tail"`` field (the BENCH_r*.json shape), or a
JSON list of rung dicts.

Workflows::

    # gate a candidate run (exit 1 on regression / malformed run)
    python tools/perf_gate.py candidate.json

    # freeze a new baseline after an INTENTIONAL perf change — run the
    # ladder on the target chip, eyeball the rungs, then:
    python tools/perf_gate.py --freeze candidate.json
    #   (writes tools/perf_baseline.json; commit it with the PR that
    #    changed performance, and say why in the PR body)

    # schema-only: structural validation without timing assertions (what
    # tier-1 runs on CPU — a CPU host must not judge TPU ratios)
    python tools/perf_gate.py --schema-only candidate.json

Per-rung tolerance lives in the baseline entry (``min_ratio``, default
0.90): a candidate regresses when value_ratio < min_ratio for
higher-is-better units, or 1/ratio < min_ratio for lower-is-better
units (``us/op``). Rungs that errored in the candidate always fail;
rungs missing from the candidate fail unless ``--allow-missing``.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional

DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "perf_baseline.json")
DEFAULT_MIN_RATIO = 0.90

#: units where a SMALLER value is better
_LOWER_IS_BETTER_UNITS = ("us/op", "us", "ms", "s", "seconds")

#: keys every bench rung must carry (the schema contract bench.py emits
#: and the driver archives)
_RUNG_KEYS = ("metric", "value", "unit", "vs_baseline")

__all__ = ["parse_bench_output", "validate_schema", "gate", "freeze",
           "main", "DEFAULT_BASELINE"]


def parse_bench_output(text: str) -> Dict[str, dict]:
    """{metric: rung dict} out of bench output in any captured shape."""
    text = text.strip()
    records: List[dict] = []
    if text.startswith("{") or text.startswith("["):
        try:
            blob = json.loads(text)
        except ValueError:
            blob = None
        if isinstance(blob, list):
            records = [r for r in blob if isinstance(r, dict)]
        elif isinstance(blob, dict) and "metric" in blob:
            records = [blob]
        elif isinstance(blob, dict) and isinstance(blob.get("tail"), str):
            return parse_bench_output(blob["tail"])
    if not records:
        for line in text.splitlines():
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                r = json.loads(line)
            except ValueError:
                continue
            if isinstance(r, dict) and "metric" in r:
                records.append(r)
    out = {}
    for r in records:
        out[str(r["metric"])] = r       # last wins (rung then summary)
    return out


def validate_schema(rungs: Dict[str, dict]) -> List[str]:
    """Structural problems of a parsed bench run (empty list = valid)."""
    problems = []
    if not rungs:
        return ["no bench rungs found in input"]
    for name, r in rungs.items():
        for k in _RUNG_KEYS:
            if k not in r:
                problems.append(f"{name}: missing key {k!r}")
        v = r.get("value")
        if not isinstance(v, (int, float)):
            problems.append(f"{name}: value is {type(v).__name__}, "
                            f"not a number")
        if r.get("unit") == "error":
            problems.append(
                f"{name}: errored rung "
                f"({r.get('extra', {}).get('error', '?')})")
    return problems


def _direction(unit: str) -> str:
    return ("lower" if str(unit).lower() in _LOWER_IS_BETTER_UNITS
            else "higher")


def gate(candidate: Dict[str, dict], baseline: dict,
         allow_missing: bool = False) -> dict:
    """Compare candidate rungs against the frozen baseline. Returns
    ``{"pass": bool, "checks": [...], "schema_problems": [...]}`` —
    check entries carry metric/base/candidate/ratio/min_ratio/status."""
    schema = validate_schema(candidate)
    checks = []
    ok = True
    for metric, base in baseline.get("rungs", {}).items():
        entry = {"metric": metric, "baseline": base.get("value"),
                 "min_ratio": float(base.get(
                     "min_ratio", baseline.get("default_min_ratio",
                                               DEFAULT_MIN_RATIO)))}
        cand = candidate.get(metric)
        if cand is None:
            entry.update(status="missing" if allow_missing else "fail",
                         reason="rung absent from candidate run")
            if not allow_missing:
                ok = False
            checks.append(entry)
            continue
        if cand.get("unit") == "error":
            entry.update(status="fail", reason="candidate rung errored")
            ok = False
            checks.append(entry)
            continue
        if not isinstance(cand.get("value"), (int, float)) or \
                not isinstance(base.get("value"), (int, float)):
            # malformed rung on either side (null value from a
            # partially-failed run or a hand-edited baseline): a clean
            # per-rung failure, not a gate traceback
            bad = ("candidate" if not isinstance(
                cand.get("value"), (int, float)) else "baseline")
            entry.update(status="fail",
                         reason=f"{bad} value is not a number")
            ok = False
            checks.append(entry)
            continue
        bval = float(base.get("value", 0.0))
        cval = float(cand.get("value", 0.0))
        direction = base.get("direction") or _direction(base.get("unit"))
        if bval <= 0:
            ratio = 1.0 if cval >= bval else 0.0
        elif direction == "lower":
            ratio = bval / cval if cval > 0 else 0.0
        else:
            ratio = cval / bval
        entry.update(candidate=cval, ratio=round(ratio, 4),
                     direction=direction)
        if ratio < entry["min_ratio"]:
            entry.update(status="fail",
                         reason=f"regressed: ratio {ratio:.4f} < "
                                f"min_ratio {entry['min_ratio']}")
            ok = False
        else:
            entry["status"] = "pass"
        checks.append(entry)
    if schema:
        ok = False
    return {"pass": ok, "checks": checks, "schema_problems": schema}


def freeze(candidate: Dict[str, dict],
           min_ratio: float = DEFAULT_MIN_RATIO,
           note: str = "") -> dict:
    """Baseline dict from a candidate run (the ``--freeze`` workflow).
    Errored rungs are left out — a baseline must not encode a broken
    rung as the bar."""
    rungs = {}
    device = None
    for metric, r in candidate.items():
        if r.get("unit") == "error":
            continue
        if not isinstance(r.get("value"), (int, float)):
            continue        # a null value must never become the bar
        rungs[metric] = {"value": r.get("value"), "unit": r.get("unit"),
                         "direction": _direction(r.get("unit")),
                         "min_ratio": min_ratio}
        device = device or r.get("extra", {}).get("device")
    return {"format": "paddle_tpu.perf_baseline/1",
            "device": device, "note": note,
            "default_min_ratio": min_ratio, "rungs": rungs}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("candidate", help="bench output (JSON lines, driver "
                    "wrapper, or list); '-' = stdin")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE)
    ap.add_argument("--freeze", action="store_true",
                    help="write the baseline from this candidate run "
                    "instead of gating")
    ap.add_argument("--min-ratio", type=float, default=DEFAULT_MIN_RATIO,
                    help="per-rung tolerance recorded at freeze time")
    ap.add_argument("--note", default="", help="why the baseline moved "
                    "(recorded in the frozen file)")
    ap.add_argument("--schema-only", action="store_true",
                    help="validate structure only, no ratio checks")
    ap.add_argument("--allow-missing", action="store_true",
                    help="baseline rungs absent from the candidate warn "
                    "instead of fail")
    args = ap.parse_args(argv)

    text = (sys.stdin.read() if args.candidate == "-"
            else open(args.candidate).read())
    candidate = parse_bench_output(text)

    if args.freeze:
        base = freeze(candidate, min_ratio=args.min_ratio, note=args.note)
        if not base["rungs"]:
            print("refusing to freeze: no healthy rungs in candidate",
                  file=sys.stderr)
            return 1
        with open(args.baseline, "w") as f:
            json.dump(base, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"froze {len(base['rungs'])} rung(s) -> {args.baseline}")
        return 0

    if args.schema_only:
        problems = validate_schema(candidate)
        print(json.dumps({"pass": not problems,
                          "schema_problems": problems}, indent=1))
        return 1 if problems else 0

    try:
        with open(args.baseline) as f:
            baseline = json.load(f)
    except (OSError, ValueError) as e:
        print(f"cannot read baseline {args.baseline!r}: {e} — freeze one "
              f"first (--freeze)", file=sys.stderr)
        return 1
    result = gate(candidate, baseline, allow_missing=args.allow_missing)
    print(json.dumps(result, indent=1))
    return 0 if result["pass"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
