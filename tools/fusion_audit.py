"""Fused-op coverage audit — the FUSION.md generator with teeth.

Every op registered under category ``fusion`` (the rewrite targets of
``paddle_tpu/compile/fusion/``) must carry the full first-class-op kit:

* a **docstring** (the registry doc surface),
* a **named cost model** (``observability.perf.costmodel.COST_MODELS``
  or a ``register(..., cost_fn=)`` site) so round-12 attribution sees
  through the rewrite,
* a **named spmd rule** (``distributed.spmd.rules.SPMD_RULES`` or a
  ``register(..., spmd_rule=)`` site — tier ``rule``, category fallback
  does NOT count) so round-13 propagation reports zero fallbacks on
  fused programs,
* a **Pallas kernel + XLA composite pair** (``ops/pallas/fused_ops`` +
  the lowering factory in ``nn/functional/fused.py``) so the autotuner
  has both legs to measure.

A fused op missing any of these FAILS the audit (exit 1) — and
``tests/test_fusion.py::test_fusion_audit_clean`` runs it in tier-1, so
registering a half-wired fused op breaks the build, not production.

Run::

    python tools/fusion_audit.py            # audit + rewrite FUSION.md
    python tools/fusion_audit.py --check    # audit only (no write)
"""
from __future__ import annotations

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

#: fusion pattern -> the fused op it rewrites onto (must stay in sync
#: with compile.fusion.PATTERNS — the audit asserts the sync)
PATTERN_TARGETS = {
    "norm_linear": "fused_norm_linear",
    "linear_act": "fused_norm_linear",
    "residual_norm": "fused_residual_norm",
    "bias_act": "fused_bias_act",
    "rope_proj": "fused_rope_proj",
}

#: fused op -> its Pallas kernel entry point (ops/pallas/fused_ops)
KERNELS = {
    "fused_bias_act": "fused_bias_act",
    "fused_residual_norm": "fused_residual_norm",
    "fused_norm_linear": "fused_matmul",
    "fused_rope_proj": "fused_matmul_rope",
}

#: fused op -> lowering factory in nn/functional/fused.py (the XLA
#: composite lives inside the factory as the numerics reference)
LOWERINGS = {
    "fused_bias_act": "bias_act_lowering",
    "fused_residual_norm": "residual_norm_lowering",
    "fused_norm_linear": "norm_linear_lowering",
    "fused_rope_proj": "rope_proj_lowering",
}

#: fused op -> autotune cache key family (fused.py _choose_impl kinds)
AUTOTUNE_KINDS = {
    "fused_bias_act": "fused_bias_act",
    "fused_residual_norm": "fused_residual_norm",
    "fused_norm_linear": "fused_norm_linear",
    "fused_rope_proj": "fused_rope_proj",
}


def audit() -> dict:
    from paddle_tpu.compile import fusion as fusion_pass
    from paddle_tpu.distributed.spmd import rules as spmd_rules
    from paddle_tpu.nn.functional import fused as fused_mod
    from paddle_tpu.observability.perf import costmodel
    from paddle_tpu.ops.pallas import fused_ops as FK
    from paddle_tpu.ops.registry import OPS

    problems = []
    fused_ops = sorted(n for n, d in OPS.items() if d.category == "fusion")
    if not fused_ops:
        problems.append("no ops registered under category 'fusion'")
    missing_decl = sorted(set(fused_mod.FUSED_OPS) - set(fused_ops))
    if missing_decl:
        problems.append(f"FUSED_OPS declared but not registered under "
                        f"category 'fusion': {missing_decl}")

    pat_set = set(fusion_pass.PATTERNS)
    if pat_set != set(PATTERN_TARGETS):
        problems.append(
            f"pattern inventory drifted: compile.fusion.PATTERNS="
            f"{sorted(pat_set)} vs audit map "
            f"{sorted(PATTERN_TARGETS)} — update PATTERN_TARGETS")

    rows = []
    for name in fused_ops:
        d = OPS[name]
        row = {"op": name,
               "patterns": sorted(p for p, t in PATTERN_TARGETS.items()
                                  if t == name)}
        if not (d.doc or "").strip():
            problems.append(f"{name}: registered without a docstring")
        row["doc"] = bool((d.doc or "").strip())

        cost = costmodel.COST_MODELS.get(name) or d.cost_fn
        if cost is None:
            problems.append(f"{name}: no NAMED cost model "
                            f"(costmodel.COST_MODELS / cost_fn=) — "
                            f"attribution would fall back to a generic "
                            f"category estimate")
        row["cost_model"] = getattr(cost, "__name__", None) if cost \
            else None

        rule = spmd_rules.SPMD_RULES.get(name) or d.spmd_rule
        if rule is None:
            problems.append(f"{name}: no NAMED spmd rule "
                            f"(rules.SPMD_RULES / spmd_rule=) — fused "
                            f"programs would replicate-fallback")
        row["spmd_rule"] = getattr(rule, "__name__", None) if rule \
            else None

        kern = KERNELS.get(name)
        if kern is None or not callable(getattr(FK, kern, None)):
            problems.append(f"{name}: no Pallas kernel mapped in "
                            f"ops/pallas/fused_ops (KERNELS table)")
            kern = None
        row["kernel"] = kern

        low = LOWERINGS.get(name)
        if low is None or not callable(getattr(fused_mod, low, None)):
            problems.append(f"{name}: no lowering factory (XLA "
                            f"composite) in nn/functional/fused.py")
            low = None
        row["lowering"] = low
        row["autotune_kind"] = AUTOTUNE_KINDS.get(name)
        rows.append(row)

    return {"ops": rows, "patterns": sorted(pat_set),
            "version": fusion_pass.FUSION_VERSION, "problems": problems}


def render_markdown(rep: dict) -> str:
    lines = [
        "# FUSION.md — fused-op coverage",
        "",
        "Generated by `python tools/fusion_audit.py`; regenerate after "
        "adding a pattern or a fused op. The audit FAILS (exit 1) on a "
        "fused op missing its docstring, named cost model, named spmd "
        "rule, or kernel/composite pair — "
        "`tests/test_fusion.py::test_fusion_audit_clean` runs it in "
        "tier-1.",
        "",
        f"- fusion pass version: **v{rep['version']}** "
        "(`compile.fusion.FUSION_VERSION`, folded into every compile-"
        "cache key)",
        "- patterns: " + ", ".join(f"`{p}`" for p in rep["patterns"]),
        "",
        "| fused op | rewritten from | Pallas kernel | XLA composite "
        "(lowering) | cost model | spmd rule | autotune key |",
        "|---|---|---|---|---|---|---|",
    ]
    for r in rep["ops"]:
        pats = ", ".join(f"`{p}`" for p in r["patterns"]) or "—"
        lines.append(
            f"| `{r['op']}` | {pats} "
            f"| `{r['kernel']}` | `{r['lowering']}` "
            f"| `{r['cost_model']}` | `{r['spmd_rule']}` "
            f"| `{r['autotune_kind']}` |")
    lines += [
        "",
        "Selection is a measured per-shape-class decision through the "
        "round-5 autotuner: the candidate grid is `[\"xla\", "
        "(\"pallas\", tile…)…]`, so one cached winner encodes both the "
        "implementation and its tiles. Off-TPU (or with "
        "`FLAGS_use_autotune=0`) the XLA composite is the default; the "
        "composite is always the numerics reference the Pallas "
        "backward recomputes through.",
        "",
        "Metrics: `paddle_tpu_fusion_matched_total{pattern=}`, "
        "`paddle_tpu_fusion_rewritten_total{pattern=}`, "
        "`paddle_tpu_fusion_rejected_total{pattern=}` (rejected = an "
        "interior value of the candidate chain is externally visible, "
        "or an input isn't available at the fusion site).",
        "",
    ]
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--check", action="store_true",
                    help="audit only; do not rewrite FUSION.md")
    ap.add_argument("--out", default=os.path.join(REPO, "FUSION.md"))
    args = ap.parse_args(argv)
    rep = audit()
    if not args.check:
        with open(args.out, "w") as f:
            f.write(render_markdown(rep))
        print(f"wrote {args.out}")
    print(f"fused ops={len(rep['ops'])} patterns={len(rep['patterns'])} "
          f"problems={len(rep['problems'])}")
    if rep["problems"]:
        for p in rep["problems"]:
            print(f"ERROR: {p}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
