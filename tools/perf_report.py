"""Per-op roofline + step-time attribution report.

Renders the performance-attribution layer's two core artifacts as
markdown (and JSON):

* a **per-op roofline table** — for every dispatched op: calls, host
  time, modeled FLOPs/bytes (``observability.perf.costmodel``), achieved
  FLOP/s and bytes/s, arithmetic intensity, the attainable roofline at
  that intensity (min(peak FLOPs, peak BW · AI)), % of attainable, and
  whether the op is compute- or bandwidth-bound on this chip;
* a **step-time attribution** — each step decomposed into compute /
  collective / host / idle (sums to measured step time; see PERF.md),
  plus whole-step modeled MFU and the attributed HBM census.

Modes::

    python tools/perf_report.py                      # run the demo loop
    python tools/perf_report.py --steps 8 --hidden 128
    python tools/perf_report.py --metrics snap.json  # render a saved
        # snapshot (written by PADDLE_TPU_METRICS_DUMP with
        # FLAGS_perf_op_cost=1) instead of running anything
    python tools/perf_report.py --json report.json --markdown report.md

The demo loop runs a tiny two-layer-attention model trained eagerly with
``FLAGS_benchmark=1`` (per-op device sync) so the dispatch latency
histogram approximates per-op execution time; on real ladder models the
same columns ride in ``bench.py`` extras and the metrics snapshot of any
instrumented run.
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional

__all__ = ["build_report", "build_report_from_snapshot",
           "render_markdown", "run_demo", "main"]


# --------------------------------------------------------------------------
# Report assembly
# --------------------------------------------------------------------------
def _op_rows(op_time: Dict[str, dict], op_cost: Dict[str, dict],
             peak_flops: float, peak_bw: float) -> List[dict]:
    """Join measured per-op host time with modeled cost into roofline
    rows. ``op_time[op] = {"calls", "total_s"}``; ``op_cost[op] =
    {"flops", "bytes"}`` (totals across the same window)."""
    rows = []
    ridge = peak_flops / peak_bw if peak_bw else float("inf")
    for op, t in op_time.items():
        c = op_cost.get(op, {})
        flops = float(c.get("flops", 0.0))
        nbytes = float(c.get("bytes", 0.0))
        total_s = float(t.get("total_s", 0.0))
        ai = flops / nbytes if nbytes else 0.0
        # zero-FLOP ops (gathers, reshapes) have no FLOP ceiling — an
        # attainable-GFLOP/s column must show 0, not the BW number
        attain = min(peak_flops, peak_bw * ai) if ai > 0 else 0.0
        ach_f = flops / total_s if total_s > 0 else 0.0
        ach_b = nbytes / total_s if total_s > 0 else 0.0
        rows.append({
            "op": op,
            "calls": int(t.get("calls", 0)),
            "host_s": round(total_s, 6),
            "model_gflops": round(flops / 1e9, 4),
            "model_gbytes": round(nbytes / 1e9, 6),
            "achieved_gflops_per_s": round(ach_f / 1e9, 3),
            "achieved_gbytes_per_s": round(ach_b / 1e9, 4),
            "arithmetic_intensity": round(ai, 3),
            "attainable_gflops_per_s": round(attain / 1e9, 3),
            "pct_of_roofline": round(100.0 * ach_f / attain, 2)
            if attain else 0.0,
            "bound": "compute" if ai >= ridge else "bandwidth",
            "op_mfu": round(ach_f / peak_flops, 4) if peak_flops else 0.0,
        })
    rows.sort(key=lambda r: -r["host_s"])
    return rows


def build_report(op_time: Dict[str, dict], op_cost: Dict[str, dict],
                 attribution: Optional[dict] = None,
                 hbm: Optional[dict] = None,
                 compiled: Optional[list] = None,
                 device_info: Optional[dict] = None,
                 cost_window_steps: Optional[int] = None) -> dict:
    """Assemble the report dict from its measured pieces (the demo run,
    bench extras, and tests all come through here)."""
    from paddle_tpu.observability import perf

    if device_info is None:
        try:
            import jax

            d = jax.devices()[0]
            device_info = {"device_kind": getattr(d, "device_kind",
                                                  d.platform),
                           "platform": d.platform}
        except Exception:
            device_info = {"device_kind": "unknown", "platform": "cpu"}
    peak_flops = perf.chip_peak_flops()
    peak_bw = perf.chip_peak_bw()
    device_info.update({
        "peak_gflops_per_s": round(peak_flops / 1e9, 1),
        "peak_hbm_gbytes_per_s": round(peak_bw / 1e9, 1),
        "ridge_intensity_flops_per_byte": round(peak_flops / peak_bw, 2),
    })
    report = {
        "device": device_info,
        "ops": _op_rows(op_time, op_cost, peak_flops, peak_bw),
    }
    total_flops = sum(float(c.get("flops", 0.0)) for c in op_cost.values())
    if attribution:
        tot = attribution.get("total", attribution)
        report["step_attribution"] = attribution
        n = max(int(tot.get("n_steps", 1)), 1)
        step_s = tot.get("step_s", 0.0) / n
        # the op counters and the attribution pass may cover DIFFERENT
        # numbers of steps (the demo accumulates cost over `steps` eager
        # steps but attributes 2 synced ones) — normalize each by its own
        # window or the MFU inflates by their ratio
        cost_n = max(int(cost_window_steps or n), 1)
        flops_per_step = total_flops / cost_n
        report["whole_step"] = {
            "step_s": round(step_s, 6),
            "modeled_flops_per_step": flops_per_step,
            "mfu": round(flops_per_step / (step_s * peak_flops), 4)
            if step_s > 0 else 0.0,
        }
    if hbm:
        report["hbm"] = {k: int(v) for k, v in hbm.items()}
    if compiled:
        report["compiled_programs"] = compiled
    return report


def _series_tables(snap: dict):
    """(op_time, op_cost, hbm) tables out of a metrics snapshot."""
    def series_of(name):
        m = snap.get(name)
        if not m:
            return {}
        out = {}
        for s in m["series"]:
            key = s["labels"][0] if s["labels"] else ""
            out[key] = s["value"]
        return out

    lat = series_of("paddle_tpu_dispatch_op_latency_seconds")
    flops = series_of("paddle_tpu_perf_op_flops_total")
    nbytes = series_of("paddle_tpu_perf_op_bytes_total")
    op_time = {op: {"calls": v["count"], "total_s": v["sum"]}
               for op, v in lat.items() if isinstance(v, dict)}
    op_cost = {op: {"flops": flops.get(op, 0.0),
                    "bytes": nbytes.get(op, 0.0)}
               for op in set(flops) | set(nbytes)}
    hbm = series_of("paddle_tpu_hbm_live_bytes")
    return op_time, op_cost, hbm


def build_report_from_snapshot(snap: dict) -> dict:
    """Roofline rows from a saved metrics snapshot (needs the
    ``paddle_tpu_dispatch_op_latency_seconds`` histogram and the
    ``paddle_tpu_perf_op_{flops,bytes}_total`` counters — i.e. a run
    with FLAGS_enable_metrics=1 FLAGS_perf_op_cost=1)."""
    op_time, op_cost, hbm = _series_tables(snap)
    return build_report(op_time, op_cost, hbm=hbm or None)


# --------------------------------------------------------------------------
# Markdown rendering
# --------------------------------------------------------------------------
def _fmt_row(cells, widths):
    return "| " + " | ".join(str(c).ljust(w)
                             for c, w in zip(cells, widths)) + " |"


def render_markdown(report: dict, top_n: int = 25) -> str:
    d = report["device"]
    lines = ["# paddle_tpu performance attribution", ""]
    lines.append(
        f"device: **{d.get('device_kind')}** — peak "
        f"{d.get('peak_gflops_per_s')} GFLOP/s, "
        f"{d.get('peak_hbm_gbytes_per_s')} GB/s HBM "
        f"(ridge {d.get('ridge_intensity_flops_per_byte')} FLOP/B)")
    lines.append("")
    if "whole_step" in report:
        w = report["whole_step"]
        lines.append(
            f"whole step: {w['step_s'] * 1e3:.3f} ms, modeled "
            f"{w['modeled_flops_per_step'] / 1e9:.2f} GFLOPs → "
            f"**MFU {w['mfu']:.3f}**")
        lines.append("")
    if "step_attribution" in report:
        tot = report["step_attribution"]["total"]
        lines.append("## Step-time attribution")
        lines.append("")
        hdr = ["component", "seconds", "fraction"]
        widths = [12, 10, 8]
        lines.append(_fmt_row(hdr, widths))
        lines.append(_fmt_row(["---"] * 3, widths))
        for k in ("compute", "collective", "host", "idle"):
            lines.append(_fmt_row(
                [k, f"{tot[k + '_s']:.4f}", f"{tot[k + '_frac']:.3f}"],
                widths))
        lines.append(_fmt_row(["step total", f"{tot['step_s']:.4f}",
                               "1.000"], widths))
        lines.append("")
    ops = report.get("ops", [])
    if ops:
        lines.append("## Per-op roofline (by host time)")
        lines.append("")
        hdr = ["op", "calls", "host ms", "GFLOPs", "GFLOP/s", "GB/s",
               "AI", "attainable", "% roof", "bound"]
        widths = [24, 6, 9, 9, 9, 8, 7, 10, 7, 9]
        lines.append(_fmt_row(hdr, widths))
        lines.append(_fmt_row(["---"] * len(hdr), widths))
        for r in ops[:top_n]:
            lines.append(_fmt_row(
                [r["op"], r["calls"], f"{r['host_s'] * 1e3:.2f}",
                 f"{r['model_gflops']:.2f}",
                 f"{r['achieved_gflops_per_s']:.1f}",
                 f"{r['achieved_gbytes_per_s']:.2f}",
                 f"{r['arithmetic_intensity']:.1f}",
                 f"{r['attainable_gflops_per_s']:.1f}",
                 f"{r['pct_of_roofline']:.1f}", r["bound"]], widths))
        lines.append("")
    if "hbm" in report:
        lines.append("## HBM census (attributed live bytes)")
        lines.append("")
        widths = [16, 14]
        lines.append(_fmt_row(["tag", "bytes"], widths))
        lines.append(_fmt_row(["---"] * 2, widths))
        for tag, v in sorted(report["hbm"].items()):
            lines.append(_fmt_row([tag, f"{int(v):,}"], widths))
        lines.append("")
    if report.get("compiled_programs"):
        lines.append("## Compiled programs (XLA analysis)")
        lines.append("")
        widths = [10, 28, 12, 14, 12]
        lines.append(_fmt_row(["site", "label", "GFLOPs", "bytes acc.",
                               "peak bytes"], widths))
        lines.append(_fmt_row(["---"] * 5, widths))
        for p in report["compiled_programs"][:top_n]:
            lines.append(_fmt_row(
                [p["site"], p["label"][:28],
                 f"{p.get('flops', 0.0) / 1e9:.3f}",
                 f"{int(p.get('bytes_accessed', 0)):,}",
                 f"{int(p.get('peak_bytes', 0)):,}"], widths))
        lines.append("")
    return "\n".join(lines)


# --------------------------------------------------------------------------
# Demo workload
# --------------------------------------------------------------------------
def run_demo(steps: int = 4, hidden: int = 64, batch: int = 4,
             seq: int = 32) -> dict:
    """Train a tiny attention model eagerly for ``steps`` steps with the
    full attribution stack armed, and build the report."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.observability import REGISTRY, perf

    paddle.set_flags({"FLAGS_enable_metrics": True,
                      "FLAGS_perf_op_cost": True,
                      "FLAGS_benchmark": True})
    perf.attach_cost_models()
    REGISTRY.reset()
    perf.memory.reset_high_water()
    paddle.seed(0)

    class _Tiny(nn.Layer):
        def __init__(self):
            super().__init__()
            self.emb = nn.Embedding(97, hidden)
            self.q = nn.Linear(hidden, hidden)
            self.k = nn.Linear(hidden, hidden)
            self.v = nn.Linear(hidden, hidden)
            self.ln = nn.LayerNorm(hidden)
            self.head = nn.Linear(hidden, 97)

        def forward(self, ids):
            import paddle_tpu.nn.functional as F

            x = self.emb(ids)
            b, s, h = x.shape
            def split(t):
                return t.reshape([b, s, 4, h // 4])
            a, _ = F.flash_attention(split(self.q(x)), split(self.k(x)),
                                     split(self.v(x)))
            x = self.ln(x + a.reshape([b, s, h]))
            return self.head(x)

    model = _Tiny()
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(
        rng.randint(0, 97, (batch, seq)).astype(np.int64))

    def one_step():
        import paddle_tpu.nn.functional as F

        logits = model(ids)
        loss = F.cross_entropy(logits.reshape([-1, 97]),
                               ids.reshape([-1]))
        loss.backward()
        opt.step()
        opt.clear_grad()
        perf.update_high_water("train_step")
        return loss

    # per-op pass: eager with per-op sync (FLAGS_benchmark) so the
    # dispatch latency histogram approximates per-op execution time —
    # the roofline table's denominator
    for _ in range(max(steps, 1)):
        one_step()
    op_time, op_cost, _ = _series_tables(REGISTRY.snapshot())

    # attribution pass: per-op sync off, so dispatch enqueues async and
    # the step's device execution drains inside the timed_section block
    # wait (the compute component), host spans stay host
    paddle.set_flags({"FLAGS_benchmark": False})
    attribution = perf.step_attribution(one_step, iters=2, warmup=0,
                                        name="train_step")

    hbm = perf.census()
    paddle.set_flags({"FLAGS_enable_metrics": False,
                      "FLAGS_perf_op_cost": False})
    return build_report(op_time, op_cost, attribution=attribution,
                        hbm=hbm, compiled=perf.compiled_programs(),
                        cost_window_steps=max(steps, 1))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--metrics", help="render a saved metrics snapshot "
                    "instead of running the demo loop")
    ap.add_argument("--json", help="write the report dict here")
    ap.add_argument("--markdown", help="write markdown here "
                    "(default: stdout)")
    ap.add_argument("--steps", type=int, default=4)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--top", type=int, default=25)
    args = ap.parse_args(argv)

    if args.metrics:
        try:
            with open(args.metrics) as f:
                snap = json.load(f)
        except (OSError, ValueError) as e:
            print(f"cannot read snapshot {args.metrics!r}: {e}",
                  file=sys.stderr)
            return 1
        report = build_report_from_snapshot(snap)
    else:
        report = run_demo(steps=args.steps, hidden=args.hidden)

    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=1, sort_keys=True)
    md = render_markdown(report, top_n=args.top)
    if args.markdown:
        with open(args.markdown, "w") as f:
            f.write(md)
    else:
        print(md)
    return 0


if __name__ == "__main__":
    import os
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    raise SystemExit(main())
