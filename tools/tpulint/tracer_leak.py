"""Pass 2 — tracer-leak (TPU2xx).

Tensor/tracer values escaping the trace's lifetime: stores into module-level
globals or containers (TPU201), mutable default arguments (TPU202), and
caches keyed on tensor values (TPU203). A leaked tracer keeps an entire
traced computation alive and explodes the next trace with
``UnexpectedTracerError`` far from the leak site — flagging the store site
is the whole point of doing this statically.
"""
from __future__ import annotations

from .core import SourceFile
from .taint import analyze_file

CODES = {"TPU201", "TPU202", "TPU203"}


def run(sf: SourceFile):
    analyze_file(sf, CODES)
