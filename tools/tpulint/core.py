"""tpulint core: findings, suppression comments, and the CI baseline.

The baseline keys findings on (path, code, normalized source line) rather
than line numbers, so unrelated edits above a frozen finding do not unfreeze
it. ``--update-baseline`` regenerates the file; the gate fails only on
findings NOT covered by the checked-in counts (new debt), never on fixed
ones (the update workflow shrinks the file).
"""
from __future__ import annotations

import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

BASELINE_VERSION = 1

#: every code the analyzer can emit, with one-line meaning (also --list-codes)
CODES = {
    "TPU100": "file does not parse (syntax error)",
    "TPU101": ".numpy() on a tensor — host materialization",
    "TPU102": ".item()/.tolist() on a tensor — host materialization",
    "TPU103": "float()/int()/bool() applied to a tensor-derived value",
    "TPU104": "np.* call on a tensor-derived value (use jnp)",
    "TPU105": "`if` predicated on a tensor value (use static.nn.cond)",
    "TPU106": "`while` predicated on a tensor value (use static.nn.while_loop)",
    "TPU201": "tensor value stored into a module-level global/container",
    "TPU202": "mutable default argument (tracer-retention vector)",
    "TPU203": "container subscripted/keyed by a tensor value",
    "TPU301": "OpDef has an empty doc",
    "TPU302": "OpDef category not in registry.KNOWN_CATEGORIES",
    "TPU303": "inplace_variant names an unregistered op",
    "TPU304": "register_module bulk registration shadowed by an earlier one",
    "TPU305": "ops/__init__ public export neither registered nor allowlisted",
    "TPU306": "op_parity_audit alias target is not a registered op",
}


@dataclass
class Finding:
    path: str          # repo-relative, forward slashes
    line: int          # 1-based; 0 for registry-level findings
    col: int
    code: str
    message: str
    fixit: str = ""
    #: normalized source-line text (or a synthetic ``op:<name>`` key for
    #: registry findings) — the line-drift-stable part of the baseline key
    line_text: str = ""

    def key(self) -> str:
        return f"{self.path}|{self.code}|{self.line_text}"

    def render(self) -> str:
        loc = f"{self.path}:{self.line}:{self.col}"
        out = f"{loc}: {self.code} {self.message}"
        if self.fixit:
            out += f"\n    fix: {self.fixit}"
        return out


# ---------------------------------------------------------------------------
# Suppression comments:  # tpulint: disable=TPU101,TPU2xx
#   inline  -> suppresses that line; on a line of its own -> suppresses the
#   NEXT line.             # tpulint: skip-file  (whole module, first 5 lines)
# A trailing justification after the codes is encouraged and ignored.
# ---------------------------------------------------------------------------
_DISABLE_RE = re.compile(
    r"#\s*tpulint:\s*disable=((?:TPU\w+|all)(?:\s*,\s*(?:TPU\w+|all))*)")
_SKIP_FILE_RE = re.compile(r"#\s*tpulint:\s*skip-file")


def _norm_line(text: str) -> str:
    """Whitespace-collapsed line text used in baseline keys."""
    return " ".join(text.split())


class SourceFile:
    """One analyzed file: source, per-line suppressions, finding sink."""

    def __init__(self, path: str, rel: str, text: Optional[str] = None):
        self.path = path
        self.rel = rel.replace(os.sep, "/")
        if text is None:
            with open(path, encoding="utf-8") as f:
                text = f.read()
        self.text = text
        self.lines = text.splitlines()
        self.skip = any(_SKIP_FILE_RE.search(l) for l in self.lines[:5])
        self._disabled: Dict[int, set] = {}
        for i, l in enumerate(self.lines, 1):
            m = _DISABLE_RE.search(l)
            if m:
                codes = {c.strip() for c in m.group(1).split(",") if c.strip()}
                target = i + 1 if l.lstrip().startswith("#") else i
                self._disabled.setdefault(target, set()).update(codes)
        self.findings: List[Finding] = []

    def suppressed(self, line: int, code: str) -> bool:
        codes = self._disabled.get(line)
        if not codes:
            return False
        # TPU1xx-style wildcards match a whole pass family
        fam = code[:4] + "xx"
        return "all" in codes or code in codes or fam in codes

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return _norm_line(self.lines[line - 1])
        return ""

    def add(self, line: int, col: int, code: str, message: str,
            fixit: str = "", line_text: Optional[str] = None):
        if self.skip or self.suppressed(line, code):
            return
        self.findings.append(Finding(
            self.rel, line, col, code, message, fixit,
            line_text if line_text is not None else self.line_text(line)))


def iter_python_files(paths: List[str], repo_root: str) -> List[Tuple[str, str]]:
    """Expand files/dirs into (abs_path, repo_relative) python sources."""
    out = []
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isfile(p) and p.endswith(".py"):
            out.append(p)
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", ".git"))
                for f in sorted(files):
                    if f.endswith(".py"):
                        out.append(os.path.join(root, f))
    seen, uniq = set(), []
    for p in out:
        if p not in seen:
            seen.add(p)
            rel = os.path.relpath(p, repo_root)
            uniq.append((p, rel.replace(os.sep, "/")))
    return uniq


# ---------------------------------------------------------------------------
# Baseline
# ---------------------------------------------------------------------------
def baseline_counts(findings: List[Finding]) -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for f in findings:
        counts[f.key()] = counts.get(f.key(), 0) + 1
    return counts


def save_baseline(path: str, findings: List[Finding]):
    data = {"version": BASELINE_VERSION,
            "total": len(findings),
            "findings": dict(sorted(baseline_counts(findings).items()))}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=1, sort_keys=True)
        f.write("\n")


def load_baseline(path: str) -> Dict[str, int]:
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(f"unsupported baseline version in {path}")
    return dict(data["findings"])


def diff_against_baseline(findings: List[Finding],
                          baseline: Dict[str, int]) -> List[Finding]:
    """Findings not covered by the baseline counts (the CI failures)."""
    budget = dict(baseline)
    new = []
    for f in findings:
        k = f.key()
        if budget.get(k, 0) > 0:
            budget[k] -= 1
        else:
            new.append(f)
    return new
