"""tpulint — framework-aware static analysis for paddle_tpu.

Three passes, mirroring the bug classes a jax-graft tracing framework is
uniquely exposed to (see ISSUE 2 / README "tpulint"):

- TPU1xx  trace-safety: host syncs (``.numpy()``/``.item()``/``float()``/
  ``np.*`` on tensor-derived values, ``if``/``while`` on tensor predicates)
  that silently graph-break ``to_static``/SOT capture.
- TPU2xx  tracer-leak: tensor values escaping into module globals, mutable
  default arguments, or caches keyed on tensors — the classic leaked-tracer
  bug class.
- TPU3xx  registry consistency: every ``OpDef`` documented and categorised,
  ``inplace_variant`` targets registered, bulk ``register_module`` calls not
  shadowing decorator registrations, and the registry reconciling with
  ``ops/__init__`` exports and the parity-audit alias table.

Run:  python -m tools.tpulint [paths] --baseline tools/tpulint/baseline.json
"""
from .core import Finding, load_baseline, diff_against_baseline  # noqa: F401
from .registry_check import load_registry  # noqa: F401
