"""Pass 3 — op-registry consistency (TPU3xx).

The reference framework keeps its 150K-LoC op surface honest with a
declarative YAML schema plus generated checks (paddle/phi/ops/yaml/ops.yaml);
our ``OpDef`` registry is the same source of truth, so this pass IS the
generated check: it imports the real registry (no mocks) and verifies every
``OpDef`` is documented and categorised, ``inplace_variant`` targets exist,
bulk ``register_module`` calls did not silently shadow decorator
registrations, and the registry reconciles with the public ``ops`` exports
and the parity-audit alias table.

Findings key on the synthetic line text ``op:<name>`` so the baseline is
stable under unrelated source-line drift.
"""
from __future__ import annotations

import inspect
import os
from typing import List

from .core import Finding

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

#: public names in the ``paddle_tpu.ops`` namespace that are deliberately
#: NOT ops: constructors, dtype predicates and registry introspection
#: helpers (host-side API conveniences with no kernel/lowering identity)
EXPORT_ALLOWLIST = {
    "as_tensor", "to_tensor", "tolist", "convert_dtype", "broadcast_shape",
    "is_complex", "is_empty", "is_floating_point", "is_integer",
    "op_names", "ops_by_category", "register", "register_module",
}


def load_registry():
    """Import paddle_tpu and return its live OPS dict.

    THE registry loader — ``tools/op_parity_audit.py`` and the tpulint CLI
    both go through here so "what counts as the op surface" has one
    definition. Linting is a host-side activity: if no platform was chosen
    explicitly, force CPU so the import never grabs a TPU.
    """
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import sys
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    import paddle_tpu  # noqa: F401  (triggers registration)
    from paddle_tpu.ops.registry import OPS
    return OPS


def _op_location(opdef) -> tuple:
    fn = opdef.lowering
    try:
        path = inspect.getsourcefile(fn)
        line = inspect.getsourcelines(fn)[1]
        if path and path.startswith(REPO):
            return (os.path.relpath(path, REPO).replace(os.sep, "/"), line)
    except (TypeError, OSError):
        pass
    return ("paddle_tpu/ops/registry.py", 0)


def _finding(opdef, code: str, message: str, fixit: str = "") -> Finding:
    path, line = _op_location(opdef)
    return Finding(path, line, 0, code, message, fixit,
                   line_text=f"op:{opdef.name}")


def run() -> List[Finding]:
    OPS = load_registry()
    from paddle_tpu.ops import registry as reg
    findings: List[Finding] = []
    known_cats = getattr(reg, "KNOWN_CATEGORIES", None) or {
        d.category for d in OPS.values()}

    for name in sorted(OPS):
        d = OPS[name]
        if getattr(d.lowering, "__module__", "") == \
                "paddle_tpu.utils.custom_op":
            # runtime user ops (register_custom_op) join the live registry
            # but are not part of the SHIPPED op contract this pass audits
            # — in-process registrations (e.g. from earlier tests) must not
            # make the gate order-dependent
            continue
        if not (d.doc or "").strip():
            findings.append(_finding(
                d, "TPU301",
                f"op '{name}' has no doc — the registry is the op surface's "
                "documentation of record",
                "add a docstring to the lowering function (register_module "
                "propagates it) or pass doc= at registration"))
        if d.category not in known_cats:
            findings.append(_finding(
                d, "TPU302",
                f"op '{name}' category '{d.category}' is not in "
                "registry.KNOWN_CATEGORIES",
                "use an existing category or add the new one to "
                "KNOWN_CATEGORIES deliberately"))
        if d.inplace_variant and d.inplace_variant not in OPS:
            findings.append(_finding(
                d, "TPU303",
                f"op '{name}' declares inplace_variant "
                f"'{d.inplace_variant}' which is not registered"))

    # bulk register_module() calls record what they silently skipped when a
    # same-name op already existed with a DIFFERENT callable
    for mod_name, op_name in sorted(set(getattr(reg, "SHADOWED", ()))):
        d = OPS.get(op_name)
        if d is None:
            continue
        findings.append(_finding(
            d, "TPU304",
            f"register_module('{mod_name}') skipped '{op_name}': a different "
            "callable is already registered under that name",
            "rename one of the functions or pass skip=(name,) explicitly"))

    # exports <-> registry reconciliation
    import paddle_tpu.ops as ops_ns
    lowerings = {id(d.lowering) for d in OPS.values()}
    for name in sorted(vars(ops_ns)):
        if name.startswith("_") or name in EXPORT_ALLOWLIST:
            continue
        obj = getattr(ops_ns, name)
        if (not callable(obj) or inspect.isclass(obj)
                or inspect.ismodule(obj)):
            continue
        if not getattr(obj, "__module__", "").startswith("paddle_tpu"):
            continue
        if name in OPS or id(obj) in lowerings:
            continue  # registered, or an alias of a registered lowering
        findings.append(Finding(
            "paddle_tpu/ops/__init__.py", 0, 0, "TPU305",
            f"public ops export '{name}' is neither a registered op, an "
            "alias of one, nor allowlisted as a helper",
            "register it, or add it to tpulint's EXPORT_ALLOWLIST with a "
            "reason", line_text=f"export:{name}"))

    # parity-audit alias table must point at real registered ops
    try:
        from tools import op_parity_audit as audit
        for ref_name, target in sorted(audit.ALIASES.items()):
            if target not in OPS:
                findings.append(Finding(
                    "tools/op_parity_audit.py", 0, 0, "TPU306",
                    f"ALIASES['{ref_name}'] -> '{target}' is not a "
                    "registered op (audit would count parity it doesn't "
                    "have)", line_text=f"alias:{ref_name}"))
    except ImportError:
        pass
    return findings
