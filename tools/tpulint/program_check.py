"""Program-level verification: trace the framework's ladder-style
programs and run the static verifier over each recorded op-list IR.

``python -m tools.tpulint --programs`` (and the tier-1 gate in
``tests/test_program_verifier.py``) drives :func:`run`: every program
the bench ladder and the test suite already trace — a GPT block with
loss, a tiny llama forward, an SGD train step, in-graph control flow,
the fusion pass's rewritten plan, and a sharded program over a mesh —
must verify CLEAN. A finding here is new framework debt: fix the
program, or suppress it in the verifier call with a justification.
Round 21 adds the serving decode/verify tick programs (the paged
engine's jitted chunk replayed eagerly over live cache state) and the
pipeline stage slices + cross-stage send/recv contract (TPU8xx).

Kept import-light: heavy imports happen inside :func:`build_programs`
so ``python -m tools.tpulint`` without ``--programs`` stays AST-only.
"""
from __future__ import annotations

from typing import Callable, List, Tuple

__all__ = ["build_programs", "run"]


def _gpt_loss_program(batch=2):
    """Tiny GPT forward + loss recorded as a static.Program."""
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.ops as ops
    from paddle_tpu import static
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.nn import functional as F

    paddle.seed(7)
    model = GPTForCausalLM(GPTConfig(
        vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
        max_seq_len=16, use_flash_attention=False))
    prog = static.Program()
    with static.program_guard(prog):
        ids = static.data("ids", [batch, 8], "int64")
        logits = model(ids)
        if isinstance(logits, (tuple, list)):
            logits = logits[0]
        v = logits.shape[-1]
        loss = F.cross_entropy(
            ops.reshape(logits[:, :-1, :], [-1, v]),
            ops.reshape(ids[:, 1:], [-1]))
        loss = loss.mean()
    return prog, [id(loss)], model


def _programs_impl() -> List[Tuple[str, Callable[[], object]]]:
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import static
    from paddle_tpu.static import verifier

    def gpt_loss():
        prog, fetch, _m = _gpt_loss_program()
        return verifier.check(prog, fetch_ids=fetch, label="gpt_loss")

    def gpt_loss_sharded():
        import jax
        from jax.sharding import PartitionSpec as P
        from paddle_tpu.distributed import mesh as mesh_mod
        n = len(jax.devices())
        # batch == device count: the data axis divides it exactly
        prog, fetch, _m = _gpt_loss_program(batch=n)
        mesh = mesh_mod.build_mesh({"data": n})
        return verifier.check(prog, mesh=mesh,
                              in_specs={"ids": P("data", None)},
                              fetch_ids=fetch, label="gpt_loss_sharded")

    def llama_forward():
        from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
        paddle.seed(7)
        model = LlamaForCausalLM(LlamaConfig(
            vocab_size=64, hidden_size=32, intermediate_size=64,
            num_layers=2, num_heads=4, max_seq_len=32,
            use_flash_attention=False))
        prog = static.Program()
        with static.program_guard(prog):
            ids = static.data("ids", [2, 8], "int64")
            logits = model(ids)
            if isinstance(logits, (tuple, list)):
                logits = logits[0]
        return verifier.check(prog, fetch_ids=[id(logits)],
                              label="llama_forward")

    def sgd_train_step():
        import paddle_tpu.nn as nn
        import paddle_tpu.optimizer as opt
        paddle.seed(7)
        model = nn.Sequential(nn.Linear(8, 16), nn.GELU(),
                              nn.Linear(16, 4))
        sgd = opt.SGD(learning_rate=0.1,
                      parameters=model.parameters())
        x = paddle.to_tensor(np.random.rand(4, 8).astype("float32"))

        def step(inp):
            loss = model(inp).mean()
            loss.backward()
            sgd.step()
            sgd.clear_grad()
            return loss

        return verifier.audit_step(step, (x,), label="sgd_train_step")

    def control_flow():
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [4], "float32")
            y = static.nn.cond(paddle.to_tensor(True),
                               lambda: x * 2.0, lambda: x * 3.0)

            def c(i, v):
                return i < 4

            def b(i, v):
                return [i + 1, v + y]

            i0 = paddle.to_tensor(0)
            _i, out = static.nn.while_loop(c, b, [i0, x])
        return verifier.check(prog, fetch_ids=[id(out)],
                              label="control_flow")

    def fused_plan():
        # the fusion pass's rewritten plan must verify clean too: the
        # FusedSteps replay like _OpRecords and carry loc provenance
        from paddle_tpu.compile import fusion
        import paddle_tpu.nn as nn
        paddle.seed(7)
        lin = nn.Linear(16, 16)
        norm = nn.LayerNorm(16)
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [4, 16], "float32")
            h = nn.functional.gelu(lin(norm(x)))
        fetch = [id(h)]
        plan, _stats = fusion.fuse_program_ops(
            prog.global_block().ops, fetch)
        return verifier.check(plan, fetch_ids=fetch, label="fused_plan")

    def _paged_engine(speculate=False):
        """Tiny-GPT paged engine advanced one tick so the K/V caches,
        block tables, and slot state are live decode state."""
        from paddle_tpu.inference import serving as sv
        from paddle_tpu.models import GPTConfig, GPTForCausalLM
        paddle.seed(7)
        model = GPTForCausalLM(GPTConfig(
            vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
            max_seq_len=64, use_flash_attention=False))
        eng = sv.PagedEngine(model, max_batch=2, block_size=8,
                             num_blocks=32, max_blocks_per_seq=8,
                             speculate=speculate, speculate_k=2)
        eng.add_request([3, 5, 7, 9], max_new_tokens=8)
        eng.step()
        return sv, eng

    def _chunk_args(eng, tokens, seq):
        return eng._chunk_args(
            tokens, seq, eng.tables,
            np.zeros((eng.max_batch,), np.float32),
            np.ones((eng.max_batch,), np.float32),
            np.zeros((eng.max_batch,), np.int32),
            np.zeros((eng.max_batch,), np.int32))

    def serving_decode_tick():
        # the engine's decode tick is ONE jitted program
        # (inference/serving._paged_forward); replay it EAGERLY over
        # live engine state so the recorder sees the same op stream the
        # jit traces — a dispatched-but-unregistered op is TPU700 here
        sv, eng = _paged_engine()
        seq = eng.seq_lens.copy()
        if eng.slots[0] is not None:
            seq[0] = eng.slots[0].seq_len
        tokens = eng.last_token[:, None].astype(np.int32)
        return verifier.audit_step(
            sv._paged_forward,
            (eng.arch, tuple(eng._params))
            + tuple(_chunk_args(eng, tokens, seq)),
            label="serving_decode_tick")

    def serving_verify_tick():
        # the speculative sibling: one (B, k+1) verify program with the
        # in-graph accept-prefix — the fused decode path of round 18
        sv, eng = _paged_engine(speculate=True)
        k = eng._spec_k
        seq = eng.seq_lens.copy()
        if eng.slots[0] is not None:
            seq[0] = eng.slots[0].seq_len + k
        tokens = np.zeros((eng.max_batch, k + 1), np.int32)
        tokens[0, 0] = eng.last_token[0]
        return verifier.audit_step(
            sv._paged_verify,
            (eng.arch, tuple(eng._params))
            + tuple(_chunk_args(eng, tokens, seq))
            + (np.full((eng.max_batch,), k, np.int32),),
            label="serving_verify_tick")

    def moe_layer():
        # the GShard MoE block (distributed.fleet.moe): gate + stacked
        # experts dispatch as the registered moe_gate/moe_layer ops;
        # BOTH the output and the aux loss are fetched (the training
        # loop consumes l_aux — unfetched it would read as dead)
        from paddle_tpu.distributed.fleet.moe import MoELayer
        paddle.seed(7)
        layer = MoELayer(d_model=16, num_experts=4, top_k=2,
                         capacity_factor=2.0)
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [8, 16], "float32")
            y = layer(x)
            l_aux = layer.l_aux
        rep = verifier.check(prog, fetch_ids=[id(y), id(l_aux)],
                             label="moe_layer")
        # the liveness pass must be able to price it too: the peak
        # report is part of the op surface contract for ladder programs
        from paddle_tpu.static import liveness
        liveness.peak_report(prog, fetch_ids=[id(y), id(l_aux)])
        return rep

    def pipeline_stages():
        # every stage slice of a cost-partitioned program must verify
        # as a standalone op stream AND the cross-stage send/recv
        # contract must match (TPU801/802/803, verifier.check_stages)
        from paddle_tpu.distributed.pipeline import partition_program
        import paddle_tpu.nn as nn
        paddle.seed(7)
        blocks = []
        for _ in range(4):
            blocks += [nn.Linear(16, 16), nn.GELU()]
        model = nn.Sequential(*blocks)
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [4, 16], "float32")
            loss = (model(x) ** 2).mean()
        part = partition_program(prog, 2, fetch_ids=[id(loss)])
        return verifier.check_stages(part.stage_records(),
                                     label="pipeline_stages")

    return [("gpt_loss", gpt_loss),
            ("gpt_loss_sharded", gpt_loss_sharded),
            ("llama_forward", llama_forward),
            ("sgd_train_step", sgd_train_step),
            ("control_flow", control_flow),
            ("fused_plan", fused_plan),
            ("serving_decode_tick", serving_decode_tick),
            ("serving_verify_tick", serving_verify_tick),
            ("moe_layer", moe_layer),
            ("pipeline_stages", pipeline_stages)]


def build_programs():
    """(label, thunk) pairs; each thunk traces one framework program
    and returns its verifier Report."""
    return _programs_impl()


def run(quiet: bool = False) -> int:
    """Trace + verify every program; print findings; exit status 1 when
    any program is not verifier-clean."""
    failures = 0
    for label, thunk in build_programs():
        try:
            report = thunk()
        except Exception as e:      # a program that cannot trace IS debt
            failures += 1
            print(f"program {label}: TRACE FAILED — "
                  f"{type(e).__name__}: {e}")
            continue
        if report.findings:
            failures += 1
            print(report.render())
        elif not quiet:
            print(f"program {label}: clean "
                  f"({report.stats.get('ops', '?')} ops)")
    tail = "clean" if not failures else f"{failures} program(s) flagged"
    print(f"tpulint --programs: {tail}")
    return 1 if failures else 0
