import sys

from .cli import main

try:
    sys.exit(main())
except BrokenPipeError:
    # output piped into head/grep that exited early — not an error
    sys.exit(0)
