"""Intra-function taint analysis over the AST.

"Tainted" = the expression may hold (or derive from) a live tensor/tracer
value at runtime. Sources are framework idioms, not type inference:
``Tensor(...)``/``as_tensor(...)``/``_t(...)`` constructions, ``*._data``
payload reads, ``dispatch.call`` results, ``jnp.*``/``jax.*`` results, and
the parameters of lowering functions handed to ``dispatch.call`` (those run
under trace, so their arguments are tracers). Taint propagates through
arithmetic, indexing, methods, containers — and through ``np.*`` calls: the
``np`` call itself is the host-sync finding (TPU104), and its result is a
host copy of tensor data, so a later ``float()`` on it is still part of the
same graph break (how `loss.py edit_distance`'s ``float(dp[n])`` is found).

The walk runs twice per scope so names tainted on a loop back-edge are seen
by earlier lines; findings dedup on (line, col, code).
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from .core import SourceFile

TENSOR_FACTORIES = {"_t", "as_tensor", "to_tensor", "Tensor", "t"}
SYNC_METHODS = {"numpy": "TPU101", "item": "TPU102", "tolist": "TPU102"}
CAST_BUILTINS = {"float", "int", "bool", "complex"}
#: attributes that are static metadata even on a tensor (trace-safe)
SAFE_ATTRS = {"shape", "ndim", "dtype", "size", "name", "place",
              "stop_gradient", "grad_node", "output_index", "is_leaf"}
#: builtins whose results never carry tensor data
UNTAINTED_CALLS = {"len", "isinstance", "issubclass", "hasattr", "type",
                   "id", "print", "repr", "str", "format", "range",
                   "callable", "getattr", "dir", "vars"}
#: jax/jnp calls returning static metadata (dtypes, backend names) or
#: host-side callable wrappers (jit/eval_shape), not device values —
#: truthiness on these is trace-safe
METADATA_CALLS = {"issubdtype", "isdtype", "result_type", "can_cast",
                  "promote_types", "iinfo", "finfo", "dtype",
                  "default_backend", "device_count", "local_device_count",
                  "devices", "local_devices", "process_index",
                  "process_count", "jit", "eval_shape",
                  "ShapeDtypeStruct", "tree_structure"}

FIXITS = {
    "TPU101": "keep the computation in-graph (jnp ops / registered ops); "
              "materialize only at explicit host boundaries",
    "TPU102": "use jnp indexing/reductions instead of host scalars",
    "TPU103": "use jnp arithmetic; for data-dependent branching use "
              "static.nn.cond / static.nn.while_loop",
    "TPU104": "use the jnp.* equivalent so XLA keeps the op on device",
    "TPU105": "use static.nn.cond (compiles to lax.cond, one XLA program)",
    "TPU106": "use static.nn.while_loop (compiles to lax.while_loop)",
    "TPU201": "thread the tensor through function returns/pytrees; module "
              "state outlives the trace and leaks the tracer",
    "TPU202": "default to None and construct inside the function body",
    "TPU203": "key caches on static metadata (shape/dtype), never on "
              "tensor values — tracer hashes poison the cache",
}


def _call_name(node: ast.Call) -> str:
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return ""


def _dotted(node) -> str:
    """'a.b.c' for nested attributes rooted at a Name, else ''."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


class ModuleInfo:
    """Module-level facts the per-scope analysis consults."""

    def __init__(self, tree: ast.Module):
        self.np_aliases: Set[str] = set()
        self.jnp_aliases: Set[str] = set()
        self.module_mutables: Set[str] = set()
        self.lowering_fn_names: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    alias = a.asname or a.name.split(".")[0]
                    if a.name == "numpy":
                        self.np_aliases.add(alias)
                    elif a.name in ("jax.numpy", "jax"):
                        self.jnp_aliases.add(alias)
            elif isinstance(node, ast.ImportFrom):
                if node.module == "jax" and any(a.name == "numpy"
                                                for a in node.names):
                    for a in node.names:
                        if a.name == "numpy":
                            self.jnp_aliases.add(a.asname or "numpy")
            elif isinstance(node, ast.Call):
                # dispatch.call("op", f, ...): f's params are tracers
                if (_dotted(node.func).endswith("dispatch.call")
                        or _dotted(node.func) == "call") and len(node.args) >= 2:
                    if isinstance(node.args[1], ast.Name):
                        self.lowering_fn_names.add(node.args[1].id)
        for stmt in tree.body:
            if isinstance(stmt, ast.Assign) and self._is_mutable(stmt.value):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        self.module_mutables.add(t.id)

    @staticmethod
    def _is_mutable(v) -> bool:
        if isinstance(v, (ast.Dict, ast.List, ast.Set, ast.DictComp,
                          ast.ListComp, ast.SetComp)):
            return True
        if isinstance(v, ast.Call) and _call_name(v) in (
                "dict", "list", "set", "defaultdict", "OrderedDict",
                "WeakValueDictionary"):
            return True
        return False


class ScopeAnalyzer:
    """Runs the taint walk over one function (or the module body)."""

    def __init__(self, sf: SourceFile, info: ModuleInfo, enabled: Set[str],
                 seen: Set):
        self.sf = sf
        self.info = info
        self.enabled = enabled
        self.seen = seen          # (line, col, code) dedup, shared per file
        self.tainted: Set[str] = set()
        self.dict_names: Set[str] = set(info.module_mutables)
        self.globals_decl: Set[str] = set()
        self.vararg_names: Set[str] = set()
        self.emit_findings = False   # only on the final walk

    def flag(self, node, code: str, message: str):
        if not self.emit_findings or code not in self.enabled:
            return
        k = (node.lineno, node.col_offset, code)
        if k in self.seen:
            return
        self.seen.add(k)
        self.sf.add(node.lineno, node.col_offset, code, message,
                    FIXITS.get(code, ""))

    # -- expression taint (emits sync findings as a side effect) ----------
    def expr(self, node) -> bool:
        if node is None or isinstance(node, ast.Constant):
            return False
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Attribute):
            if node.attr == "_data":
                return True
            base = self.expr(node.value)
            if node.attr in SAFE_ATTRS:
                return False
            return base
        if isinstance(node, ast.Subscript):
            self.expr(node.slice)
            return self.expr(node.value)
        if isinstance(node, ast.BinOp):
            l, r = self.expr(node.left), self.expr(node.right)
            return l or r
        if isinstance(node, ast.UnaryOp):
            return self.expr(node.operand)
        if isinstance(node, ast.BoolOp):
            return any([self.expr(v) for v in node.values])
        if isinstance(node, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                for c in node.comparators:
                    self.expr(c)
                self.expr(node.left)
                return False      # identity checks are trace-safe
            if all(isinstance(op, (ast.In, ast.NotIn)) for op in node.ops):
                # membership depends on the KEY being tensor-derived; a
                # static-keyed container merely holding tensors is safe
                left = self.expr(node.left)
                for c in node.comparators:
                    self.expr(c)
                return left
            parts = [self.expr(node.left)] + [self.expr(c)
                                              for c in node.comparators]
            return any(parts)
        if isinstance(node, ast.IfExp):
            self.expr(node.test)
            b, o = self.expr(node.body), self.expr(node.orelse)
            return b or o
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any([self.expr(e) for e in node.elts])
        if isinstance(node, ast.Dict):
            ks = [self.expr(k) for k in node.keys if k is not None]
            vs = [self.expr(v) for v in node.values]
            return any(ks) or any(vs)
        if isinstance(node, ast.Starred):
            return self.expr(node.value)
        if isinstance(node, ast.Slice):
            for part in (node.lower, node.upper, node.step):
                self.expr(part)
            return False
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                             ast.DictComp)):
            return self._comprehension(node)
        if isinstance(node, ast.Lambda):
            return False
        if isinstance(node, ast.JoinedStr):
            for v in node.values:
                if isinstance(v, ast.FormattedValue):
                    self.expr(v.value)
            return False
        if isinstance(node, ast.Call):
            return self._call(node)
        if isinstance(node, (ast.Await, ast.Yield, ast.YieldFrom)):
            return self.expr(getattr(node, "value", None))
        if isinstance(node, ast.NamedExpr):
            t = self.expr(node.value)
            if isinstance(node.target, ast.Name):
                self._bind(node.target.id, t)
            return t
        return False

    def _comprehension(self, node) -> bool:
        saved = set(self.tainted)
        for gen in node.generators:
            it = self.expr(gen.iter)
            # bind the target either way: an UNTAINTED iterable must
            # CLEAR stale taint on a shadowing target name (the
            # two-pass back-edge union otherwise leaks a tensor-loop
            # variable's taint into a later metadata comprehension
            # reusing the name — augmented-assign/truthiness FPs)
            for n in ast.walk(gen.target):
                if isinstance(n, ast.Name):
                    self._bind(n.id, it)
            for cond in gen.ifs:
                if self.expr(cond):
                    self.flag(cond, "TPU105",
                              "comprehension filter predicated on a tensor "
                              "value forces a host sync per element")
        if isinstance(node, ast.DictComp):
            k, v = self.expr(node.key), self.expr(node.value)
            out = k or v
        else:
            out = self.expr(node.elt)
        self.tainted = saved
        return out

    def _call(self, node: ast.Call) -> bool:
        name = _call_name(node)
        dotted = _dotted(node.func)
        root = dotted.split(".")[0] if dotted else ""
        arg_taints = [self.expr(a) for a in node.args]
        arg_taints += [self.expr(k.value) for k in node.keywords]
        any_arg = any(arg_taints)

        # ---- sync points -------------------------------------------------
        if isinstance(node.func, ast.Attribute) and name in SYNC_METHODS:
            if self.expr(node.func.value):
                self.flag(node, SYNC_METHODS[name],
                          f"host sync: .{name}() materializes a tensor to "
                          "the host")
                return False      # result is a host scalar/ndarray copy
        if isinstance(node.func, ast.Name) and name in CAST_BUILTINS:
            if any_arg:
                self.flag(node, "TPU103",
                          f"host sync: {name}() forces a tensor-derived "
                          "value to a python scalar")
                return False
        if root in self.info.np_aliases and root != "":
            if any_arg:
                self.flag(node, "TPU104",
                          f"host sync: {dotted}() pulls tensor-derived data "
                          "through numpy on the host")
            return any_arg        # host COPY of tensor data stays tracked

        # ---- taint-producing calls ---------------------------------------
        if isinstance(node.func, ast.Name) and name in TENSOR_FACTORIES:
            return True
        if dotted.endswith("dispatch.call") or dotted in (
                "call", "Tensor", "as_tensor", "to_tensor", "paddle.to_tensor"):
            return True
        if root in self.info.jnp_aliases and root != "":
            return name not in METADATA_CALLS
        if name in UNTAINTED_CALLS and isinstance(node.func, ast.Name):
            return False
        if isinstance(node.func, ast.Attribute):
            # method on a tainted object keeps the data tensor-derived
            if self.expr(node.func.value):
                return True
        return any_arg

    def _predicate_taint(self, test) -> bool:
        """Taint of an if/while test. Truthiness of a bare ``*args`` name
        is an ARITY check (``if rest:`` for an optional input) — trace-safe
        even though the tuple's elements are tracers. Likewise the bare
        truthiness of a name KNOWN to be a python container (bound from a
        dict/list/set literal or comprehension) is an EMPTINESS check:
        the container may hold tensors, but ``bool()`` never touches its
        elements (``if not params:`` / ``if state_dict:``)."""
        safe_names = self.vararg_names | self.dict_names
        if isinstance(test, ast.Name) and test.id in safe_names:
            return False
        if (isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not)
                and isinstance(test.operand, ast.Name)
                and test.operand.id in safe_names):
            return False
        return self.expr(test)

    # -- statements -------------------------------------------------------
    def _bind(self, name: str, taint: bool):
        if taint:
            self.tainted.add(name)
        else:
            self.tainted.discard(name)

    def _assign_target(self, target, taint: bool, value=None):
        if isinstance(target, ast.Name):
            if target.id in self.globals_decl and taint:
                self.flag(target, "TPU201",
                          f"tensor value assigned to module global "
                          f"'{target.id}' — outlives the trace (leaked "
                          "tracer)")
            self._bind(target.id, taint)
            if value is not None and ModuleInfo._is_mutable(value):
                self.dict_names.add(target.id)
            elif value is not None:
                # re-bound to a non-container: the emptiness-check
                # exemption must not outlive the container binding
                self.dict_names.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            if (value is not None and isinstance(value, (ast.Tuple, ast.List))
                    and len(value.elts) == len(target.elts)):
                for t, v in zip(target.elts, value.elts):
                    self._assign_target(t, self.expr(v), v)
            else:
                for t in target.elts:
                    self._assign_target(t, taint)
        elif isinstance(target, ast.Starred):
            self._assign_target(target.value, taint)
        elif isinstance(target, ast.Subscript):
            key_taint = self.expr(target.slice)
            base = target.value
            if isinstance(base, ast.Name):
                if key_taint and base.id in self.dict_names:
                    self.flag(target, "TPU203",
                              f"container '{base.id}' keyed on a tensor "
                              "value")
                if base.id in self.info.module_mutables and taint:
                    self.flag(target, "TPU201",
                              f"tensor value stored into module-level "
                              f"container '{base.id}'")
                if taint:
                    # writing tensor-derived data into a slot taints the
                    # whole container (edit_distance: dp[c] = ... min(s1 != s2))
                    self.tainted.add(base.id)
        elif isinstance(target, ast.Attribute):
            self.expr(target.value)

    def stmt(self, node):
        if isinstance(node, ast.Assign):
            taint = self.expr(node.value)
            for t in node.targets:
                self._assign_target(t, taint, node.value)
        elif isinstance(node, ast.AnnAssign):
            taint = self.expr(node.value) if node.value else False
            ann = _dotted(node.annotation) if node.annotation else ""
            if ann.split(".")[-1] == "Tensor":
                taint = True
            if node.target is not None:
                self._assign_target(node.target, taint, node.value)
        elif isinstance(node, ast.AugAssign):
            taint = self.expr(node.value)
            if isinstance(node.target, ast.Name):
                if taint:
                    if node.target.id in self.globals_decl:
                        self.flag(node.target, "TPU201",
                                  f"tensor value accumulated into module "
                                  f"global '{node.target.id}'")
                    self.tainted.add(node.target.id)
            else:
                self._assign_target(node.target, taint)
        elif isinstance(node, ast.If):
            if self._predicate_taint(node.test):
                self.flag(node, "TPU105",
                          "`if` on a tensor value graph-breaks capture "
                          "(host sync per trace)")
            self.body(node.body)
            self.body(node.orelse)
        elif isinstance(node, ast.While):
            if self._predicate_taint(node.test):
                self.flag(node, "TPU106",
                          "`while` on a tensor value graph-breaks capture "
                          "(host sync per iteration)")
            self.body(node.body)
            self.body(node.orelse)
        elif isinstance(node, ast.For):
            it = self.expr(node.iter)
            # re-binding semantics: a loop over an UNTAINTED iterable
            # clears stale taint on its target names (e.g. ``for t in
            # range(3)`` after an earlier tensor loop reused ``t`` — the
            # back-edge union otherwise flags ``n += t`` / ``if t:``)
            for n in ast.walk(node.target):
                if isinstance(n, ast.Name):
                    self._bind(n.id, it)
            self.body(node.body)
            self.body(node.orelse)
        elif isinstance(node, ast.With):
            for item in node.items:
                self.expr(item.context_expr)
                if item.optional_vars is not None:
                    self._assign_target(item.optional_vars, False)
            self.body(node.body)
        elif isinstance(node, ast.Try):
            self.body(node.body)
            for h in node.handlers:
                self.body(h.body)
            self.body(node.orelse)
            self.body(node.finalbody)
        elif isinstance(node, ast.Global):
            self.globals_decl.update(node.names)
        elif isinstance(node, (ast.Return, ast.Expr, ast.Delete,
                               ast.Raise, ast.Assert)):
            for child in ast.iter_child_nodes(node):
                self.expr(child)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            pass  # nested scopes handled by the module driver
        elif isinstance(node, ast.ClassDef):
            self.body(node.body)

    def body(self, stmts):
        for s in stmts:
            self.stmt(s)

    def run(self, stmts, param_taints: Optional[Dict[str, bool]] = None):
        if param_taints:
            for n, t in param_taints.items():
                self._bind(n, t)
        base = set(self.tainted)
        # pass 1: silent, to reach names tainted on loop back-edges
        self.emit_findings = False
        self.body(stmts)
        looped = set(self.tainted)
        self.tainted = base | looped
        self.emit_findings = True
        self.body(stmts)


def _function_scopes(tree: ast.Module):
    """Yield (funcdef, enclosing-class-or-None) for every function."""
    out = []

    def walk(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append(child)
                walk(child)
            elif isinstance(child, ast.ClassDef):
                walk(child)
            elif isinstance(child, (ast.If, ast.Try, ast.With, ast.For,
                                    ast.While)):
                walk(child)
    walk(tree)
    return out


def analyze_file(sf: SourceFile, enabled: Set[str]):
    """Run the taint passes over one file, appending findings to ``sf``."""
    try:
        tree = ast.parse(sf.text, filename=sf.path)
    except SyntaxError as e:
        sf.add(e.lineno or 1, 0, "TPU100", f"syntax error: {e.msg}")
        return
    info = ModuleInfo(tree)
    seen: Set = set()

    # module body (imports/constants) — analyzed as its own scope
    top = ScopeAnalyzer(sf, info, enabled, seen)
    top.run([s for s in tree.body
             if not isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef))])
    module_taint = set(top.tainted)

    for fn in _function_scopes(tree):
        an = ScopeAnalyzer(sf, info, enabled, seen)
        an.tainted = set(module_taint)
        params: Dict[str, bool] = {}
        args = fn.args
        all_args = (args.posonlyargs + args.args + args.kwonlyargs
                    + ([args.vararg] if args.vararg else [])
                    + ([args.kwarg] if args.kwarg else []))
        is_lowering = fn.name in info.lowering_fn_names
        if args.vararg:
            an.vararg_names.add(args.vararg.arg)
        for a in all_args:
            ann = _dotted(a.annotation) if a.annotation else ""
            params[a.arg] = (is_lowering and a.arg != "self") or \
                ann.split(".")[-1] == "Tensor"
        # TPU202: mutable defaults retain whatever the trace puts in them
        if "TPU202" in enabled:
            for d in list(args.defaults) + [d for d in args.kw_defaults if d]:
                if ModuleInfo._is_mutable(d):
                    sf.add(d.lineno, d.col_offset, "TPU202",
                           f"mutable default argument in '{fn.name}' — "
                           "retains tensors/tracers across calls",
                           FIXITS["TPU202"])
        an.run(fn.body, params)
