"""tpulint command line.

    python -m tools.tpulint [paths...]
        --baseline tools/tpulint/baseline.json   gate against frozen debt
        --update-baseline                        refreeze current findings
        --no-registry                            skip the TPU3xx import pass
        --select TPU1xx,TPU203                   restrict emitted codes
        --list-codes                             print the code table
        --diff REV                               lint only files changed
                                                 since git rev REV
        --programs                               trace + verify the
                                                 framework's ladder
                                                 programs with the
                                                 static.verifier passes
                                                 (TPU4xx/5xx/6xx/7xx)
    --cross-rank BASE                        diff the rank-suffixed
                                                 program dumps
                                                 BASE.r<rank> that a
                                                 PADDLE_TPU_PROGRAM_RECORD
                                                 launch wrote (TPU45x)

Exit status: 0 clean (vs baseline if given), 1 new findings, 2 usage error.
"""
from __future__ import annotations

import argparse
import os
import sys
from typing import List

from . import registry_check, trace_safety, tracer_leak
from .core import (CODES, Finding, SourceFile, diff_against_baseline,
                   iter_python_files, load_baseline, save_baseline)

REPO = registry_check.REPO


def _match_select(code: str, select: List[str]) -> bool:
    return any(code == s or (s.endswith("xx") and code.startswith(s[:4]))
               for s in select)


def diff_paths(rev: str, paths: List[str]) -> List[str]:
    """Python files changed since ``rev`` (``git diff --name-only``),
    restricted to the requested paths — keeps lint wall time flat as
    the tree grows (CI lints the diff; the baseline gate still covers
    the whole tree in tier-1)."""
    import subprocess
    out = subprocess.run(
        ["git", "diff", "--name-only", rev, "--", "*.py"],
        cwd=REPO, capture_output=True, text=True, check=True).stdout
    roots = [os.path.abspath(p) for p in paths]
    changed = []
    for rel in out.splitlines():
        p = os.path.join(REPO, rel.strip())
        if not (rel.strip().endswith(".py") and os.path.isfile(p)):
            continue
        if any(os.path.commonpath([p, r]) == r for r in roots
               if os.path.isdir(r)) or p in roots:
            changed.append(p)
    return changed


def collect_findings(paths: List[str], with_registry: bool = True,
                     select: List[str] = ()) -> List[Finding]:
    findings: List[Finding] = []
    for abspath, rel in iter_python_files(paths, REPO):
        sf = SourceFile(abspath, rel)
        trace_safety.run(sf)
        tracer_leak.run(sf)
        findings.extend(sf.findings)
    if with_registry:
        findings.extend(registry_check.run())
    if select:
        findings = [f for f in findings if _match_select(f.code, select)]
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.code))
    return findings


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpulint", description="framework-aware static analysis "
        "(trace-safety / tracer-leak / op-registry consistency)")
    ap.add_argument("paths", nargs="*",
                    default=[os.path.join(REPO, "paddle_tpu")])
    ap.add_argument("--baseline", help="frozen-debt file; findings it "
                    "covers do not fail the run")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite --baseline from the current findings")
    ap.add_argument("--no-registry", action="store_true",
                    help="AST passes only (no paddle_tpu import)")
    ap.add_argument("--select", default="",
                    help="comma-separated codes/families, e.g. TPU1xx,TPU203")
    ap.add_argument("--list-codes", action="store_true")
    ap.add_argument("--diff", metavar="REV", default=None,
                    help="lint only python files changed since this "
                         "git revision (within the given paths)")
    ap.add_argument("--programs", action="store_true",
                    help="trace the framework's ladder + serving-tick "
                         "+ pipeline-stage programs and run the static "
                         "program verifier (static.verifier "
                         "TPU4xx/5xx/6xx/7xx/8xx) over each op-list IR")
    ap.add_argument("--cross-rank", metavar="BASE", default=None,
                    help="statically diff the per-rank program dumps "
                         "BASE.r<rank> written by a launch with "
                         "PADDLE_TPU_PROGRAM_RECORD=BASE — mismatched "
                         "collective sequences / content / order and "
                         "divergent op streams are flagged with the "
                         "rank and first divergent seq (TPU45x) before "
                         "anything has to hang")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="summary line only")
    args = ap.parse_args(argv)

    if args.list_codes:
        for code, meaning in sorted(CODES.items()):
            print(f"{code}  {meaning}")
        try:
            from paddle_tpu.static.verifier import CODES as VCODES
            for code, (sev, meaning) in sorted(VCODES.items()):
                print(f"{code}  [{sev}] {meaning}  (verifier)")
        except Exception:
            pass                     # AST-only environment: skip
        return 0
    if args.programs:
        from . import program_check
        return program_check.run(quiet=args.quiet)
    if args.cross_rank:
        from paddle_tpu.static import crossrank
        return 1 if crossrank.run(args.cross_rank,
                                  quiet=args.quiet) else 0
    if args.update_baseline and not args.baseline:
        ap.error("--update-baseline requires --baseline")
    if args.update_baseline and args.diff is not None:
        # a partial (changed-files-only) run must never REPLACE the
        # whole-tree baseline: frozen debt in unchanged files would be
        # dropped and resurface as NEW findings on the next full run
        ap.error("--update-baseline requires a full-tree run "
                 "(drop --diff)")

    select = [s.strip() for s in args.select.split(",") if s.strip()]
    paths = args.paths
    if args.diff is not None:
        import subprocess
        try:
            paths = diff_paths(args.diff, paths)
        except subprocess.CalledProcessError as e:
            # a typo'd revision is a USAGE error (exit 2), never "new
            # lint findings" (exit 1) — CI wrappers key on the status
            ap.error(f"--diff {args.diff!r}: git diff failed — "
                     f"{(e.stderr or '').strip() or e}")
        if not paths:
            print("tpulint: no changed python files under the given "
                  "paths — clean")
            return 0
    findings = collect_findings(paths,
                                with_registry=not args.no_registry,
                                select=select)

    if args.update_baseline:
        save_baseline(args.baseline, findings)
        print(f"baseline: froze {len(findings)} finding(s) -> "
              f"{args.baseline}")
        return 0

    new = findings
    frozen = 0
    if args.baseline:
        baseline = load_baseline(args.baseline)
        new = diff_against_baseline(findings, baseline)
        frozen = len(findings) - len(new)

    if not args.quiet:
        for f in new:
            print(f.render())
    tail = f" ({frozen} frozen in baseline)" if args.baseline else ""
    print(f"tpulint: {len(new)} new finding(s), {len(findings)} total{tail}")
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
