"""tpulint command line.

    python -m tools.tpulint [paths...]
        --baseline tools/tpulint/baseline.json   gate against frozen debt
        --update-baseline                        refreeze current findings
        --no-registry                            skip the TPU3xx import pass
        --select TPU1xx,TPU203                   restrict emitted codes
        --list-codes                             print the code table

Exit status: 0 clean (vs baseline if given), 1 new findings, 2 usage error.
"""
from __future__ import annotations

import argparse
import os
import sys
from typing import List

from . import registry_check, trace_safety, tracer_leak
from .core import (CODES, Finding, SourceFile, diff_against_baseline,
                   iter_python_files, load_baseline, save_baseline)

REPO = registry_check.REPO


def _match_select(code: str, select: List[str]) -> bool:
    return any(code == s or (s.endswith("xx") and code.startswith(s[:4]))
               for s in select)


def collect_findings(paths: List[str], with_registry: bool = True,
                     select: List[str] = ()) -> List[Finding]:
    findings: List[Finding] = []
    for abspath, rel in iter_python_files(paths, REPO):
        sf = SourceFile(abspath, rel)
        trace_safety.run(sf)
        tracer_leak.run(sf)
        findings.extend(sf.findings)
    if with_registry:
        findings.extend(registry_check.run())
    if select:
        findings = [f for f in findings if _match_select(f.code, select)]
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.code))
    return findings


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpulint", description="framework-aware static analysis "
        "(trace-safety / tracer-leak / op-registry consistency)")
    ap.add_argument("paths", nargs="*",
                    default=[os.path.join(REPO, "paddle_tpu")])
    ap.add_argument("--baseline", help="frozen-debt file; findings it "
                    "covers do not fail the run")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite --baseline from the current findings")
    ap.add_argument("--no-registry", action="store_true",
                    help="AST passes only (no paddle_tpu import)")
    ap.add_argument("--select", default="",
                    help="comma-separated codes/families, e.g. TPU1xx,TPU203")
    ap.add_argument("--list-codes", action="store_true")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="summary line only")
    args = ap.parse_args(argv)

    if args.list_codes:
        for code, meaning in sorted(CODES.items()):
            print(f"{code}  {meaning}")
        return 0
    if args.update_baseline and not args.baseline:
        ap.error("--update-baseline requires --baseline")

    select = [s.strip() for s in args.select.split(",") if s.strip()]
    findings = collect_findings(args.paths,
                                with_registry=not args.no_registry,
                                select=select)

    if args.update_baseline:
        save_baseline(args.baseline, findings)
        print(f"baseline: froze {len(findings)} finding(s) -> "
              f"{args.baseline}")
        return 0

    new = findings
    frozen = 0
    if args.baseline:
        baseline = load_baseline(args.baseline)
        new = diff_against_baseline(findings, baseline)
        frozen = len(findings) - len(new)

    if not args.quiet:
        for f in new:
            print(f.render())
    tail = f" ({frozen} frozen in baseline)" if args.baseline else ""
    print(f"tpulint: {len(new)} new finding(s), {len(findings)} total{tail}")
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
