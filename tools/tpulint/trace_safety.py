"""Pass 1 — trace-safety (TPU1xx).

Host-sync constructs that silently graph-break ``to_static``/SOT/program
capture: tensor materialization (``.numpy()``/``.item()``/``float()``),
``np.*`` applied to tensor-derived data, and python control flow predicated
on tensor values. All detection lives in the shared taint engine; this
module owns the code family.
"""
from __future__ import annotations

from .core import SourceFile
from .taint import analyze_file

CODES = {"TPU101", "TPU102", "TPU103", "TPU104", "TPU105", "TPU106"}


def run(sf: SourceFile):
    analyze_file(sf, CODES)
