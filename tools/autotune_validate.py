"""Validate the kernel autotuner on the real chip.

For S in {1k, 2k, 8k, 32k}: time flash fwd and bwd with (a) the hand-tuned
v5e constants and (b) the autotuner's measured winner, plus the serving
decode tick block-size probe. Prints a table; the autotuned choice must
match or beat the constants (VERDICT r4 item 3 'Done' criterion), and the
cache file must round-trip.

Timing discipline (this host's chip sits behind a remote-dispatch tunnel):
jitted closures only (steady state, no retracing), DISTINCT inputs per
timed call (the tunnel replays identical executions from cache), and
value-read syncs (block_until_ready does not drain the tunnel).

Run with the ambient (TPU) environment: python tools/autotune_validate.py
"""
import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp


NVAR = 3


def timeit(fn, warmup=2, iters=9):
    """fn(i) runs probe input i; median of per-call value-synced times."""
    for i in range(warmup):
        float(jnp.sum(fn(i)))
    ts = []
    for i in range(iters):
        t0 = time.perf_counter()
        float(jnp.sum(fn(warmup + i)))
        ts.append(time.perf_counter() - t0)
    ts.sort()
    return ts[len(ts) // 2]


def main():
    from paddle_tpu.ops.pallas import autotune as at
    from paddle_tpu.ops.pallas import flash_attention as fa

    cache_file = at.cache_path()
    print(f"backend={jax.default_backend()} chip={at.chip_kind()} "
          f"cache={cache_file}")
    assert at.should_autotune(), "autotune disabled — nothing to validate"

    B, H, D = 2, 8, 128
    dt = jnp.bfloat16
    rows = []
    for S in (1024, 2048, 8192, 32768):
        bh = B * H if S <= 8192 else 4   # fit 32k on one chip
        qs, ks, vs = [], [], []
        for v in range(NVAR):
            kp = jax.random.key(100 + v)
            qs.append(jax.random.normal(kp, (bh, S, D)).astype(dt))
            ks.append(jax.random.normal(
                jax.random.fold_in(kp, 1), (bh, S, D)).astype(dt))
            vs.append(jax.random.normal(
                jax.random.fold_in(kp, 2), (bh, S, D)).astype(dt))
        scale = 1.0 / (D ** 0.5)

        kernel_flops = 4.0 * bh * S * S * D * 0.5
        reps = at.probe_reps(kernel_flops)

        def jfwd(bq, bk):
            kern = functools.partial(
                fa._flash_fwd_bhsd, causal=True, scale=scale,
                block_q=bq, block_k=bk)
            f = jax.jit(lambda q0, k0, v0: jax.lax.fori_loop(
                0, reps, lambda _, q: kern(q, k0, v0)[0], q0))
            return lambda i: f(qs[i % NVAR], ks[i % NVAR], vs[i % NVAR])

        # ---------------- forward
        t_def = timeit(jfwd(fa.DEFAULT_BLOCK_Q, fa.DEFAULT_BLOCK_K))
        tuned = fa._tuned_blocks("fwd", bh, S, S, D, dt, True, scale)
        t_tun = timeit(jfwd(*tuned))
        rows.append(("fwd", S, (fa.DEFAULT_BLOCK_Q, fa.DEFAULT_BLOCK_K),
                     t_def, tuned, t_tun))

        # ---------------- backward
        f0 = jax.jit(functools.partial(
            fa._flash_fwd_bhsd, causal=True, scale=scale,
            block_q=fa.DEFAULT_BLOCK_Q, block_k=fa.DEFAULT_BLOCK_K))
        outs, lses = zip(*(f0(qs[v], ks[v], vs[v]) for v in range(NVAR)))

        def jbwd(bq, bk):
            kern = functools.partial(
                fa._flash_bwd_bhsd, causal=True, scale=scale,
                block_q=bq, block_k=bk)
            f = jax.jit(lambda q0, k0, v0, o0, l0: jax.lax.fori_loop(
                0, reps, lambda _, q: kern(q, k0, v0, o0, l0, o0)[0], q0))
            return lambda i: f(qs[i % NVAR], ks[i % NVAR], vs[i % NVAR],
                               outs[i % NVAR], lses[i % NVAR])

        bdef = (fa._bwd_block_for(S), fa._bwd_block_for(S))
        t_def = timeit(jbwd(*bdef))
        btun = fa._tuned_blocks("bwd", bh, S, S, D, dt, True, scale)
        t_tun = timeit(jbwd(*btun))
        rows.append(("bwd", S, bdef, t_def, btun, t_tun))

    print(f"\n{'pass':4} {'S':>6} {'constants':>12} {'t_const':>9} "
          f"{'tuned':>12} {'t_tuned':>9} {'speedup':>8}")
    worst = 1e9
    for kind, S, cdef, td, ctun, tt in rows:
        sp = td / tt
        worst = min(worst, sp)
        print(f"{kind:4} {S:>6} {str(cdef):>12} {td*1e3:8.2f}m "
              f"{str(tuple(ctun)):>12} {tt*1e3:8.2f}m {sp:7.3f}x")

    # serving decode probe
    from paddle_tpu.inference.serving import _tuned_decode_block_size
    from paddle_tpu.models import GPTConfig
    cfg = GPTConfig(vocab_size=50304, hidden_size=1024, num_layers=1,
                    num_heads=16, max_seq_len=1024,
                    use_flash_attention=False)
    bs = _tuned_decode_block_size(cfg, 16, 8, 32)
    print(f"serving decode block_size -> {bs}")

    # cache round-trip
    with open(cache_file) as f:
        data = json.load(f)
    n = len(data)
    fresh = at.AutotuneCache(cache_file)
    for key in data:
        assert fresh.get(key) is not None
    print(f"cache round-trip ok: {n} keys persisted")
    # tolerance: "match" = within tunnel measurement noise (10%)
    assert worst > 0.90, f"autotuned choice lost to constants ({worst:.3f}x)"
    print(f"VALIDATED: autotuned >= constants everywhere "
          f"(worst {worst:.3f}x)")


if __name__ == "__main__":
    main()
