"""Human-readable auto-parallel plan report.

Renders a ``distributed.planner.PlanResult`` as the placement
engineer's view of the search: the winner's emitted specs, the full
candidate table (modeled compute / collective / memory per candidate)
and, for every loser, WHY it lost — rejected (over HBM, blinded by a
hot-op fallback) or simply slower, with the dominating term named.

Library use (what ``PlanResult.report()`` calls)::

    from tools.plan_report import render
    print(render(plan_result))

CLI demo (plans a small GPT over a virtual (data, tp) mesh)::

    python tools/plan_report.py [--data N --tp N] [--json PATH]
"""
from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _fmt_s(s: float) -> str:
    if s >= 1.0:
        return f"{s:.3f} s"
    if s >= 1e-3:
        return f"{s * 1e3:.3f} ms"
    return f"{s * 1e6:.1f} us"


def _fmt_b(b: float) -> str:
    if b >= 1e9:
        return f"{b / 1e9:.2f} GB"
    return f"{b / 1e6:.1f} MB"


def _why_lost(sc, winner) -> str:
    if sc.score.rejected:
        return f"REJECTED: {sc.score.rejected}"
    dt = sc.score.total_s - winner.score.total_s
    if dt <= 0:
        return "winner"
    terms = {
        "compute": sc.score.compute_s - winner.score.compute_s,
        **{f"coll:{k}": v - winner.score.collective_breakdown.get(k, 0.0)
           for k, v in sc.score.collective_breakdown.items()},
    }
    dom = max(terms, key=lambda k: terms[k])
    pct = 100.0 * dt / max(winner.score.total_s, 1e-12)
    return (f"+{pct:.0f}% step time, dominated by {dom} "
            f"(+{_fmt_s(max(terms[dom], 0.0))})")


def render(result) -> str:
    """PlanResult -> multi-section text report."""
    win = result.winner
    lines = []
    mesh = result.mesh
    shape = ", ".join(f"{a}={int(mesh.shape[a])}"
                      for a in mesh.axis_names)
    lines.append("# Auto-parallel plan report")
    lines.append("")
    lines.append(f"mesh: ({shape})   candidates: {len(result.ranked)} "
                 f"({len(result.rejected)} rejected)")
    lines.append(f"winner: **{win.candidate.name}** "
                 f"[{win.candidate.origin}] — modeled step "
                 f"{_fmt_s(win.score.total_s)} "
                 f"(compute {_fmt_s(win.score.compute_s)}, "
                 f"collective {_fmt_s(win.score.collective_s)}), "
                 f"HBM {_fmt_b(win.score.hbm_bytes)}/device")
    lines.append("")
    lines.append("## Candidate table")
    lines.append("")
    lines.append("| candidate | total | compute | collective | "
                 "HBM/device | verdict |")
    lines.append("|---|---|---|---|---|---|")
    for sc in result.ranked:
        s = sc.score
        lines.append(
            f"| {sc.candidate.name} | {_fmt_s(s.total_s)} | "
            f"{_fmt_s(s.compute_s)} | {_fmt_s(s.collective_s)} | "
            f"{_fmt_b(s.hbm_bytes)} | {_why_lost(sc, win)} |")
    lines.append("")
    lines.append("## Winner breakdown")
    lines.append("")
    lines.append("collective seconds by source:")
    for k, v in sorted(win.score.collective_breakdown.items()):
        lines.append(f"  - {k}: {_fmt_s(v)}")
    lines.append("memory by class:")
    for k, v in sorted(win.score.memory_breakdown.items()):
        lines.append(f"  - {k}: {_fmt_b(v)}")
    if win.score.penalty_ops:
        lines.append("penalty-table ops (explicitly surcharged, "
                     "see planner.cost.PENALTY_OPS):")
        for k, v in sorted(win.score.penalty_ops.items()):
            lines.append(f"  - {k} x{v}")
    if win.score.unscored_ops:
        lines.append("UNSCORED ops (no cost model — "
                     "tools/planner_audit.py should have caught this):")
        for k, v in sorted(win.score.unscored_ops.items()):
            lines.append(f"  - {k} x{v}")
    lines.append("")
    lines.append("## Emitted placement (winner)")
    lines.append("")
    for name, spec in sorted(result.param_spec_table.items()):
        if spec is not None and any(e is not None for e in spec):
            lines.append(f"  {name}: {spec}")
    lines.append(f"  <inputs>: batch dim over {result.batch_entry!r}")
    return "\n".join(lines)


def _demo(data: int, tp: int):
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.distributed import mesh as mesh_mod, planner
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    mesh = mesh_mod.build_mesh({"data": data, "tp": tp})
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                    num_heads=4, max_seq_len=32,
                    use_flash_attention=False)
    model = GPTForCausalLM(cfg)
    ids = np.random.RandomState(0).randint(0, 256, (4, 32)) \
        .astype(np.int64)

    def loss_fn(x):
        _, loss = model(x, labels=x)
        return loss

    return planner.plan(loss_fn, mesh, example_inputs=(ids,),
                        model=model)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0])
    ap.add_argument("--data", type=int, default=2,
                    help="data-axis size of the demo mesh")
    ap.add_argument("--tp", type=int, default=4,
                    help="tp-axis size of the demo mesh")
    ap.add_argument("--json", metavar="PATH",
                    help="also write the machine-readable summary "
                         "('-' = stdout)")
    args = ap.parse_args(argv)
    res = _demo(args.data, args.tp)
    print(render(res))
    if args.json:
        payload = json.dumps(res.summary(), indent=1, sort_keys=True)
        if args.json == "-":
            print(payload)
        else:
            with open(args.json, "w") as f:
                f.write(payload + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
