"""Experiment: bf16-resident weights + f32 master copy vs f32 weights
with per-step bf16 autocast, on the GPT-2 bench rung.

Rationale: with f32-resident params the forward/backward re-reads 4-byte
weights every step (the autocast is fused but the HBM traffic is f32);
keeping params bf16-resident halves weight bytes on the hot path while
the optimizer updates a f32 master (standard mixed-precision discipline,
reference amp O2 + master_weights).

Run on the real chip: ``python tools/bench_weight_dtype.py``.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def main():
    import paddle_tpu as paddle
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from bench import chip_peak_flops

    small = jax.default_backend() not in ("tpu", "axon")
    if small:
        cfg = GPTConfig(vocab_size=512, hidden_size=128, num_layers=2,
                        num_heads=4, max_seq_len=128,
                        use_flash_attention=False)
        batch, seq, iters = 2, 128, 2
    else:
        cfg = GPTConfig(max_seq_len=1024)
        batch, seq, iters = 8, 1024, 10
    model = GPTForCausalLM(cfg)
    params = [p for p in model.parameters() if not p.stop_gradient]
    b1, b2, eps, wd, lr = 0.9, 0.95, 1e-8, 0.1, 2.5e-4

    def make_ids(i):
        rng = np.random.RandomState(i)
        return jnp.asarray(rng.randint(0, cfg.vocab_size,
                                       (batch, seq)).astype(np.int64))

    def loss_of(pa, ids):
        originals = [p._data for p in params]
        for p, a in zip(params, pa):
            p._data = a
        try:
            from paddle_tpu import amp
            with amp.auto_cast(level="O1", dtype="bfloat16"):
                _, loss = model(paddle.Tensor(ids),
                                labels=paddle.Tensor(ids))
            return loss._data.astype(jnp.float32)
        finally:
            for p, o in zip(params, originals):
                p._data = o

    def run(variant):
        bf16 = variant == "bf16_weights"
        # explicit copy: same-dtype astype aliases the model's arrays and
        # donation would delete them for the next variant
        master = [jnp.array(p._data, jnp.float32, copy=True)
                  for p in params]
        live = [m.astype(jnp.bfloat16) for m in master] if bf16 else None
        m_st = [jnp.zeros_like(m) for m in master]
        v_st = [jnp.zeros_like(m) for m in master]

        def adam(mw, g, m, v, tf):
            g32 = g.astype(jnp.float32)
            m = b1 * m + (1 - b1) * g32
            v = b2 * v + (1 - b2) * g32 * g32
            mh = m / (1 - b1 ** tf)
            vh = v / (1 - b2 ** tf)
            mw = mw * (1 - lr * wd) - lr * mh / (jnp.sqrt(vh) + eps)
            return mw, m, v

        if bf16:
            def step(live, master, m_st, v_st, t, ids):
                loss, grads = jax.value_and_grad(loss_of)(live, ids)
                tf = t.astype(jnp.float32)
                outs = [adam(mw, g, m, v, tf) for mw, g, m, v
                        in zip(master, grads, m_st, v_st)]
                return (loss, [mw.astype(jnp.bfloat16) for mw, _, _ in outs],
                        [mw for mw, _, _ in outs],
                        [m for _, m, _ in outs], [v for _, _, v in outs])

            jitted = jax.jit(step, donate_argnums=(0, 1, 2, 3))
            state = (live, master, m_st, v_st)

            def call(state, t, ids):
                loss, live, master, m_st, v_st = jitted(*state, t, ids)
                return loss, (live, master, m_st, v_st)
        else:
            def step(master, m_st, v_st, t, ids):
                loss, grads = jax.value_and_grad(loss_of)(master, ids)
                tf = t.astype(jnp.float32)
                outs = [adam(mw, g, m, v, tf) for mw, g, m, v
                        in zip(master, grads, m_st, v_st)]
                return (loss, [mw for mw, _, _ in outs],
                        [m for _, m, _ in outs], [v for _, _, v in outs])

            jitted = jax.jit(step, donate_argnums=(0, 1, 2))
            state = (master, m_st, v_st)

            def call(state, t, ids):
                loss, master, m_st, v_st = jitted(*state, t, ids)
                return loss, (master, m_st, v_st)

        batches = [make_ids(i) for i in range(iters + 1)]
        loss, state = call(state, jnp.asarray(1, jnp.int32), batches[0])
        float(loss)   # force real execution (tunnel-safe sync)
        t0 = time.perf_counter()
        for i in range(iters):
            loss, state = call(state, jnp.asarray(2 + i, jnp.int32),
                               batches[1 + i])
        lv = float(loss)  # chained state forces all iters to execute
        dt = (time.perf_counter() - t0) / iters
        n_params = sum(int(np.prod(p.shape)) for p in params)
        tok_s = batch * seq / dt
        fpt = 6 * n_params + 12 * cfg.num_layers * cfg.hidden_size * seq
        mfu = fpt * tok_s / chip_peak_flops(jax.devices()[0])
        print(f"{variant}: {tok_s:,.0f} tok/s  step {dt*1e3:.1f} ms  "
              f"MFU {mfu:.4f}  loss {lv:.3f}")
        return tok_s

    a = run("f32_weights")
    b = run("bf16_weights")
    print(f"bf16/f32 speedup: {b / a:.4f}x")


if __name__ == "__main__":
    main()
