"""A/B: bf16-resident weights + f32 master vs f32-resident weights, on
the GPT-2 bench rung — driven through bench.py's OWN harness
(`_run_train_bench(bf16_weights=...)`) so the comparison always measures
the shipped timing/donation/sync discipline rather than a copy that can
drift.

Run on the real chip: ``python tools/bench_weight_dtype.py``.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def main():
    import paddle_tpu as paddle
    from bench import _run_train_bench, chip_peak_flops
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    small = jax.default_backend() not in ("tpu", "axon")
    if small:
        cfg = GPTConfig(vocab_size=512, hidden_size=128, num_layers=2,
                        num_heads=4, max_seq_len=128,
                        use_flash_attention=False)
        batch, seq, iters = 2, 128, 2
    else:
        cfg = GPTConfig(max_seq_len=1024)
        batch, seq, iters = 8, 1024, 10
    model = GPTForCausalLM(cfg)
    params = [p for p in model.parameters() if not p.stop_gradient]

    def make_inputs(i):
        rng = np.random.RandomState(i)
        return (jnp.asarray(rng.randint(
            0, cfg.vocab_size, (batch, seq)).astype(np.int64)),)

    def loss_of(model, ids):
        _, loss = model(paddle.Tensor(ids), labels=paddle.Tensor(ids))
        return loss

    results = {}
    for flag in (False, True):
        dt, loss0, loss_end, n_params, _attr = _run_train_bench(
            model, params, make_inputs, loss_of, iters,
            bf16_weights=flag)
        tok_s = batch * seq / dt
        fpt = 6 * n_params + 12 * cfg.num_layers * cfg.hidden_size * seq
        mfu = fpt * tok_s / chip_peak_flops(jax.devices()[0])
        name = "bf16_weights" if flag else "f32_weights"
        results[name] = tok_s
        print(f"{name}: {tok_s:,.0f} tok/s  step {dt*1e3:.1f} ms  "
              f"MFU {mfu:.4f}  loss {loss_end:.3f}")
    print(f"bf16/f32 speedup: "
          f"{results['bf16_weights'] / results['f32_weights']:.4f}x")


if __name__ == "__main__":
    main()
