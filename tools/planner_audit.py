"""Planner scoring-coverage audit — no silently-unscored ops.

The planner ranks placements by an analytical cost walk; an op the walk
cannot see (no spmd rule AND no cost model AND no explicit penalty
entry) silently biases every score. This audit traces the workload
programs the planner is pointed at — GPT, llama, the MoE layer, and
the DLRM recommender (sharded-embedding path: ``embedding_bag`` /
``scatter_add``) — and asserts every emitted op is covered one of two
ways:

* a **sharding tier** that isn't replicate-warn (named ``spmd_rule`` or
  category fallback) AND a cost model (``cost_of`` returns non-None), or
* an explicit entry in ``distributed.planner.cost.PENALTY_OPS`` — a
  documented surcharge for by-design opaque ops (the monolithic
  ``moe_layer``/``moe_gate`` dispatch).

An op in neither bucket FAILS the audit (exit 1) —
``tests/test_planner.py::test_planner_audit_clean`` runs it in tier-1,
so a new workload op lands with a rule or a penalty entry, never
silently.

Run::

    python tools/planner_audit.py            # audit, print table
    python tools/planner_audit.py --json -   # machine-readable
"""
from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _trace_gpt():
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.distributed import planner
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    paddle.seed(0)
    model = GPTForCausalLM(GPTConfig(
        vocab_size=128, hidden_size=32, num_layers=1, num_heads=4,
        max_seq_len=16, use_flash_attention=False))
    ids = np.zeros((2, 16), dtype=np.int64)

    def loss_fn(x):
        _, loss = model(x, labels=x)
        return loss

    prog, _ = planner.trace_program(loss_fn, (ids,))
    return prog


def _trace_llama():
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.distributed import planner
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    paddle.seed(0)
    model = LlamaForCausalLM(LlamaConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_layers=1, num_heads=4, num_kv_heads=4, max_seq_len=16,
        use_flash_attention=False))
    ids = np.zeros((2, 16), dtype=np.int64)

    def loss_fn(x):
        _, loss = model(x, labels=x)
        return loss

    prog, _ = planner.trace_program(loss_fn, (ids,))
    return prog


def _trace_moe():
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.distributed import planner
    from paddle_tpu.distributed.fleet import MoELayer

    paddle.seed(0)
    layer = MoELayer(d_model=16, num_experts=4, d_hidden=32, top_k=2)
    x = np.zeros((8, 16), dtype=np.float32)

    def fwd(xt):
        out = layer(xt)
        return (out * out).mean() + layer.l_aux

    prog, _ = planner.trace_program(fwd, (x,))
    return prog


def _trace_dlrm():
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.distributed import planner
    from paddle_tpu.models import DLRM, dlrm_tiny

    paddle.seed(0)
    cfg = dlrm_tiny()
    model = DLRM(cfg)
    dense = np.zeros((4, cfg.n_dense), dtype=np.float32)
    ids = np.zeros((4, cfg.n_sparse, cfg.bag_size), dtype=np.int64)
    labels = np.zeros((4,), dtype=np.float32)

    def loss_fn(d, i, y):
        return model.loss(d, i, y)

    prog, _ = planner.trace_program(loss_fn, (dense, ids, labels))
    return prog


WORKLOADS = {
    "gpt": _trace_gpt,
    "llama": _trace_llama,
    "moe": _trace_moe,
    "dlrm": _trace_dlrm,
}


def audit() -> dict:
    """Trace each workload, classify every emitted op. Returns
    {"ok": bool, "workloads": {name: {op: status}}, "uncovered": [...]}
    where status is 'rule' / 'category-fallback' / 'penalty' /
    'UNCOVERED'."""
    from paddle_tpu.distributed.planner.cost import PENALTY_OPS
    from paddle_tpu.distributed.spmd import attach_spmd_rules, rule_for
    from paddle_tpu.observability.perf.costmodel import (
        attach_cost_models, cost_of)

    attach_spmd_rules()
    attach_cost_models()
    out = {"ok": True, "workloads": {}, "uncovered": []}
    for wname, tracer in WORKLOADS.items():
        prog = tracer()
        statuses = {}
        for op in prog.global_block().ops:
            if op.name in statuses:
                continue
            if op.name in PENALTY_OPS:
                statuses[op.name] = "penalty"
                continue
            _, tier = rule_for(op.name)
            cost = cost_of(op.name, op.in_shapes or (), (), op.attrs,
                           op.out_shapes or ())
            if tier != "replicate-warn" and cost is not None:
                statuses[op.name] = tier
            else:
                why = []
                if tier == "replicate-warn":
                    why.append("no spmd rule")
                if cost is None:
                    why.append("no cost model")
                statuses[op.name] = "UNCOVERED"
                out["uncovered"].append(
                    {"workload": wname, "op": op.name,
                     "why": ", ".join(why)})
                out["ok"] = False
        out["workloads"][wname] = statuses
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--json", metavar="PATH",
                    help="write machine-readable result ('-' = stdout)")
    args = ap.parse_args(argv)
    rep = audit()
    if args.json:
        payload = json.dumps(rep, indent=1, sort_keys=True)
        if args.json == "-":
            print(payload)
        else:
            with open(args.json, "w") as f:
                f.write(payload + "\n")
    for wname, statuses in rep["workloads"].items():
        tiers = {}
        for s in statuses.values():
            tiers[s] = tiers.get(s, 0) + 1
        print(f"{wname}: {len(statuses)} distinct ops — " +
              ", ".join(f"{k}={v}" for k, v in sorted(tiers.items())))
    if not rep["ok"]:
        print("\nUNCOVERED ops (add an spmd rule + cost model, or an "
              "explicit planner.cost.PENALTY_OPS entry):",
              file=sys.stderr)
        for u in rep["uncovered"]:
            print(f"  [{u['workload']}] {u['op']}: {u['why']}",
                  file=sys.stderr)
        return 1
    print("planner scoring coverage: OK (every emitted op is ruled, "
          "category-covered, or explicitly penalized)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
