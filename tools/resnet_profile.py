"""Where does the ResNet50 train step spend its time? (VERDICT r4 #2)

Ablation-based profile on the real chip (a sampling profiler cannot see
through the remote-dispatch tunnel): times the full train step, then
variants that remove one cost at a time, plus achieved TF/s for the
dominant conv shapes in isolation. Timing discipline: jitted closures,
distinct inputs per iter, value-read syncs.

Run: python tools/resnet_profile.py  (ambient TPU env)
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

BATCH = int(os.environ.get("PROFILE_BATCH", "256"))


def timeit(fn, inputs, warmup=2, iters=5):
    for i in range(warmup):
        float(jnp.sum(fn(*inputs[i % len(inputs)])))
    ts = []
    for i in range(iters):
        t0 = time.perf_counter()
        float(jnp.sum(fn(*inputs[(warmup + i) % len(inputs)])))
        ts.append(time.perf_counter() - t0)
    ts.sort()
    return ts[len(ts) // 2]


def main():
    import paddle_tpu as paddle
    from paddle_tpu import amp
    from paddle_tpu.vision.models import resnet50

    print(f"backend={jax.default_backend()} batch={BATCH}")
    paddle.seed(0)
    model = resnet50()
    params = [p for p in model.parameters() if not p.stop_gradient]
    pa0 = [p._data for p in params]

    xs = [jnp.asarray(np.random.RandomState(i).randn(
        BATCH, 3, 224, 224).astype(np.float32)) for i in range(3)]
    ys = [jnp.asarray(np.random.RandomState(100 + i).randint(
        0, 1000, (BATCH,)).astype(np.int64)) for i in range(3)]

    buffers = [b for _, b in model.named_buffers()]

    def loss_fn_of(amp_level, amp_on=True):
        def loss_fn(pa, x, y):
            originals = [p._data for p in params]
            buf0 = [b._data for b in buffers]
            for p, a in zip(params, pa):
                p._data = a
            try:
                if amp_on:
                    with amp.auto_cast(level=amp_level, dtype="bfloat16"):
                        out = model(paddle.Tensor(x))
                else:
                    out = model(paddle.Tensor(x))
                import paddle_tpu.nn.functional as F
                return F.cross_entropy(
                    out, paddle.Tensor(y))._data.astype(jnp.float32)
            finally:
                for p, o in zip(params, originals):
                    p._data = o
                # BN running stats mutate in train mode — restore so the
                # traced values never leak out of the transform
                for b, o in zip(buffers, buf0):
                    b._data = o
        return loss_fn

    rows = []

    def add(name, fn, inputs):
        dt = timeit(jax.jit(fn), inputs)
        rows.append((name, dt))
        print(f"{name:34}: {dt * 1e3:8.1f} ms")

    lf = loss_fn_of("O1")
    # full train step (fwd+bwd+SGD), the bench's shape
    def step(pa, x, y):
        loss, grads = jax.value_and_grad(lf)(pa, x, y)
        return loss + jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(g)) * 0 for g in grads]))

    def step_full(pa, x, y):
        loss, grads = jax.value_and_grad(lf)(pa, x, y)
        new = [p - 0.1 * g for p, g in zip(pa, grads)]
        return sum(jnp.sum(n) * 1e-12 for n in new) + loss

    inputs = [(pa0, x, y) for x, y in zip(xs, ys)]
    add("train step (fwd+bwd+sgd, O1)", step_full, inputs)
    add("fwd+bwd only (O1)", step, inputs)
    add("forward only (O1)", lf, inputs)
    add("forward only (f32, no amp)", loss_fn_of("O1", amp_on=False),
        inputs)

    # BN ablation: eval-mode BN (running stats; no batch reductions)
    model.eval()
    add("forward only (O1, BN eval)", loss_fn_of("O1"), inputs)
    model.train()

    # isolated conv shapes (bf16): achieved TF/s on this chip's XLA conv
    convs = [
        ("stem 7x7s2 3->64 @224", (BATCH, 3, 224, 224), (64, 3, 7, 7), 2),
        ("3x3 64->64 @56", (BATCH, 64, 56, 56), (64, 64, 3, 3), 1),
        ("3x3 128->128 @28", (BATCH, 128, 28, 28), (128, 128, 3, 3), 1),
        ("3x3 256->256 @14", (BATCH, 256, 14, 14), (256, 256, 3, 3), 1),
        ("3x3 512->512 @7", (BATCH, 512, 7, 7), (512, 512, 3, 3), 1),
        ("1x1 256->1024 @14", (BATCH, 256, 14, 14), (1024, 256, 1, 1), 1),
    ]
    for name, xshape, wshape, stride in convs:
        x = jnp.asarray(np.random.RandomState(0).randn(*xshape),
                        jnp.bfloat16)
        w = jnp.asarray(np.random.RandomState(1).randn(*wshape) * 0.05,
                        jnp.bfloat16)
        dn = jax.lax.conv_dimension_numbers(
            xshape, wshape, ("NCHW", "OIHW", "NCHW"))

        def conv(x, w):
            return jax.lax.conv_general_dilated(
                x, w, (stride, stride), "SAME", dimension_numbers=dn)

        # chain to amortize dispatch when spatial/channels allow it: use
        # 3 distinct inputs instead (convs here are big enough to time)
        cxs = [(x + i * jnp.bfloat16(0.001), w) for i in range(3)]
        dt = timeit(jax.jit(conv), cxs)
        out_sp = conv(x, w).shape
        flops = 2 * np.prod(out_sp) * wshape[1] * wshape[2] * wshape[3]
        print(f"  conv {name:22}: {dt*1e3:7.2f} ms  "
              f"{flops/dt/1e12:6.1f} TF/s achieved")

    # NHWC variant of one mid conv for layout comparison
    x = jnp.asarray(np.random.RandomState(0).randn(BATCH, 28, 28, 128),
                    jnp.bfloat16)
    w = jnp.asarray(np.random.RandomState(1).randn(128, 128, 3, 3) * .05,
                    jnp.bfloat16)
    dn = jax.lax.conv_dimension_numbers(
        x.shape, w.shape, ("NHWC", "OIHW", "NHWC"))

    def conv_nhwc(x, w):
        return jax.lax.conv_general_dilated(
            x, w, (1, 1), "SAME", dimension_numbers=dn)

    cxs = [(x + i * jnp.bfloat16(0.001), w) for i in range(3)]
    dt = timeit(jax.jit(conv_nhwc), cxs)
    flops = 2 * BATCH * 28 * 28 * 128 * 128 * 9
    print(f"  conv 3x3 128->128 @28 NHWC   : {dt*1e3:7.2f} ms  "
          f"{flops/dt/1e12:6.1f} TF/s achieved")


if __name__ == "__main__":
    main()
