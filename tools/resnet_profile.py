"""Where does the ResNet50 train step spend its time? (VERDICT r4 #2)

Ablation-based profile on the real chip (a sampling profiler cannot see
through the remote-dispatch tunnel). Every measurement chains ``REPS``
iterations data-dependently inside ONE jitted program (scalar feedback:
``x_next = x * (1 + 0*loss)``), so the ~120 ms per-call transport floor
divides out; syncs are value reads.

Run: python tools/resnet_profile.py  (ambient TPU env)
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

BATCH = int(os.environ.get("PROFILE_BATCH", "256"))
REPS = int(os.environ.get("PROFILE_REPS", "4"))


def timeit(fn, inputs, warmup=2, iters=3):
    for i in range(warmup):
        float(jnp.sum(fn(*inputs[i % len(inputs)])))
    ts = []
    for i in range(iters):
        t0 = time.perf_counter()
        float(jnp.sum(fn(*inputs[(warmup + i) % len(inputs)])))
        ts.append(time.perf_counter() - t0)
    ts.sort()
    return ts[len(ts) // 2]


def main():
    import paddle_tpu as paddle
    from paddle_tpu import amp
    from paddle_tpu.vision.models import resnet50

    print(f"backend={jax.default_backend()} batch={BATCH} reps={REPS}",
          flush=True)
    paddle.seed(0)
    model = resnet50()
    params = [p for p in model.parameters() if not p.stop_gradient]
    buffers = [b for _, b in model.named_buffers()]
    pa0 = [p._data for p in params]

    xs = [jnp.asarray(np.random.RandomState(i).randn(
        BATCH, 3, 224, 224).astype(np.float32)) for i in range(3)]
    ys = [jnp.asarray(np.random.RandomState(100 + i).randint(
        0, 1000, (BATCH,)).astype(np.int64)) for i in range(3)]

    def loss_fn_of(amp_on=True):
        def loss_fn(pa, x, y):
            originals = [p._data for p in params]
            buf0 = [b._data for b in buffers]
            for p, a in zip(params, pa):
                p._data = a
            try:
                if amp_on:
                    with amp.auto_cast(level="O1", dtype="bfloat16"):
                        out = model(paddle.Tensor(x))
                else:
                    out = model(paddle.Tensor(x))
                import paddle_tpu.nn.functional as F
                return F.cross_entropy(
                    out, paddle.Tensor(y))._data.astype(jnp.float32)
            finally:
                for p, o in zip(params, originals):
                    p._data = o
                for b, o in zip(buffers, buf0):
                    b._data = o
        return loss_fn

    def chained(per_iter):
        """Chain REPS iterations: the scalar result scales next input."""
        def f(pa, x, y):
            def body(i, carry):
                x, acc = carry
                s = per_iter(pa, x, y)
                return (x * (1.0 + 0.0 * s), acc + s)
            _, acc = jax.lax.fori_loop(0, REPS, body,
                                       (x, jnp.float32(0)))
            return acc
        return f

    inputs = [(pa0, x, y) for x, y in zip(xs, ys)]

    def add(name, per_iter):
        dt = timeit(jax.jit(chained(per_iter)), inputs) / REPS
        print(f"{name:34}: {dt * 1e3:8.1f} ms/iter", flush=True)
        return dt

    lf = loss_fn_of()

    def fwd_bwd(pa, x, y):
        loss, grads = jax.value_and_grad(lf)(pa, x, y)
        return loss + sum(jnp.sum(g) * 1e-12 for g in grads)

    def full_step(pa, x, y):
        loss, grads = jax.value_and_grad(lf)(pa, x, y)
        return loss + sum(jnp.sum(p - 0.1 * g) * 1e-12
                          for p, g in zip(pa, grads))

    t_step = add("train step (fwd+bwd+sgd, O1)", full_step)
    add("fwd+bwd (O1)", fwd_bwd)
    t_fwd = add("forward only (O1)", lf)
    add("forward only (f32)", loss_fn_of(amp_on=False))
    model.eval()
    add("forward only (O1, BN eval)", loss_fn_of())
    model.train()

    flops_step = 3 * BATCH * 4.1e9 * 2 / 2  # ~2x fwd for bwd; fwd 4.1GF
    print(f"-> step {t_step*1e3:.0f} ms = {BATCH/t_step:.0f} img/s; "
          f"fwd fraction {t_fwd/t_step:.2f}", flush=True)

    # isolated conv shapes (bf16, chained): achieved TF/s of XLA conv
    convs = [
        ("3x3 64->64 @56", (BATCH, 64, 56, 56), (64, 64, 3, 3)),
        ("3x3 128->128 @28", (BATCH, 128, 28, 28), (128, 128, 3, 3)),
        ("3x3 256->256 @14", (BATCH, 256, 14, 14), (256, 256, 3, 3)),
        ("3x3 512->512 @7", (BATCH, 512, 7, 7), (512, 512, 3, 3)),
    ]
    for name, xshape, wshape in convs:
        for fmt in ("NCHW", "NHWC"):
            if fmt == "NHWC":
                xsh = (xshape[0], xshape[2], xshape[3], xshape[1])
            else:
                xsh = xshape
            x = jnp.asarray(np.random.RandomState(0).randn(*xsh) * 0.1,
                            jnp.bfloat16)
            w = jnp.asarray(
                np.random.RandomState(1).randn(*wshape) * 0.05,
                jnp.bfloat16)
            dn = jax.lax.conv_dimension_numbers(
                xsh, wshape, (fmt, "OIHW", fmt))

            def conv_chain(x, w):
                def body(i, c):
                    y = jax.lax.conv_general_dilated(
                        c, w, (1, 1), "SAME", dimension_numbers=dn)
                    return y * jnp.bfloat16(0.1)
                return jax.lax.fori_loop(0, 16, body, x)

            cxs = [(x + jnp.bfloat16(0.001 * i), w) for i in range(3)]
            dt = timeit(jax.jit(conv_chain), cxs) / 16
            flops = 2 * np.prod(xshape) * wshape[0] * 9
            print(f"  conv {name:18} {fmt}: {dt*1e3:7.2f} ms  "
                  f"{flops/dt/1e12:6.1f} TF/s", flush=True)


if __name__ == "__main__":
    main()
