"""Host-overhead measurement for the SOT steady-state bypass.

An un-jitted GPT-2 eval step with a forced mid-frame host sync (the
graph-break pattern that routes to SOT partial-frame capture), measured
two ways:

* replay  — the pre-bypass behavior: every call re-runs the Python frame,
  re-records ops into segments, re-fingerprints guards (cached XLA
  programs, no recompiles)
* bypass  — the steady state: one frame-level guard check, then the
  stitched compiled segments run directly

Run on the chip: python tools/sot_bypass_bench.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                    num_heads=12, max_seq_len=1024,
                    use_flash_attention=False)
    net = GPTForCausalLM(cfg)
    for p in net.parameters():
        p.stop_gradient = True   # eval: grad-free -> bypass-eligible

    x = paddle.to_tensor(
        np.random.RandomState(0).randint(0, cfg.vocab_size,
                                         (1, 128)).astype(np.int64))

    def step(ids):
        s = float(paddle.ops.mean(
            paddle.ops.cast(ids, "float32")).numpy())  # mid-frame break
        logits = net(ids)
        if s > 1e12:
            logits = logits * 0.0
        return logits

    st = paddle.jit.to_static(step, full_graph=False)

    # warm up: record + compile (call 1), journal-match (call 2)
    jax.block_until_ready(st(x)._data)
    jax.block_until_ready(st(x)._data)
    sig = next(iter(st._sot_frames))
    n = 20

    # ---- replay steady state (pre-bypass behavior)
    ts = []
    for _ in range(n):
        st._sot_frames[sig]["stable"] = False   # force Python replay
        t0 = time.perf_counter()
        out = st(x)
        jax.block_until_ready(out._data)
        ts.append(time.perf_counter() - t0)
    replay_ms = 1e3 * float(np.median(ts))
    assert st.sot_stats["bypassed"] is False

    # ---- bypass steady state
    st(x)
    st(x)
    assert st.sot_stats["bypassed"] is True, st.sot_stats
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        out = st(x)
        jax.block_until_ready(out._data)
        ts.append(time.perf_counter() - t0)
    bypass_ms = 1e3 * float(np.median(ts))
    assert st.sot_stats["bypassed"] is True

    # ---- plain eager for context (per-op dispatch, no SOT at all)
    def eager_step(ids):
        logits = net(ids)
        return logits

    jax.block_until_ready(eager_step(x)._data)
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        out = eager_step(x)
        jax.block_until_ready(out._data)
        ts.append(time.perf_counter() - t0)
    eager_ms = 1e3 * float(np.median(ts))

    print(f"GPT-2 124M eval step (B=1, S=128), {jax.default_backend()}:")
    print(f"  eager per-op dispatch : {eager_ms:8.2f} ms/call")
    print(f"  SOT replay (before)   : {replay_ms:8.2f} ms/call")
    print(f"  SOT bypass (after)    : {bypass_ms:8.2f} ms/call")
    print(f"  bypass vs replay      : {replay_ms / bypass_ms:8.2f}x "
          f"less host time")


if __name__ == "__main__":
    main()
