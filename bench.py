"""Benchmark driver hook.

Default run covers the whole BASELINE.md ladder (gpt2 + resnet50 + bert +
llama): one JSON line per rung as it lands, then a combined summary line
LAST — {"metric": "train_ladder_vs_baseline_geomean", ...} with per-rung
results in "extra" — so a driver that keeps only the final line records
the full ladder. Each rung is a full training step — forward + backward +
AdamW update compiled as ONE XLA program (the steady-state path) —
reporting tokens/s / images/s plus MFU versus the chip's peak bf16 FLOPs.
``vs_baseline`` is MFU / 0.40 for token models (the published A100
GPT-class MFU bar; BASELINE.md: the reference repo publishes no absolute
numbers) and img/s / 2080 for ResNet50.

``BENCH_MODEL=gpt2|resnet50|bert|llama`` runs a single rung and prints
exactly one JSON line.
"""
import json
import os
import sys
import time

# The ladder reports against PINNED, hand-validated kernel constants:
# a first-sight autotune probe taken while the chip transport happens to
# be degraded would cache a bad winner and silently change what this
# benchmark measures. The autotuner is a user feature, validated
# separately by tools/autotune_validate.py. BENCH_AUTOTUNE=1 opts in.
if os.environ.get("BENCH_AUTOTUNE") != "1":
    os.environ.setdefault("FLAGS_use_autotune", "0")

import jax
import jax.numpy as jnp
import numpy as np


def chip_peak_flops(device) -> float:
    # canonical spec table lives with the roofline layer
    from paddle_tpu.observability.perf import chip_peak_flops as _cpf
    return _cpf(device)


def _run_train_bench(model, params, make_inputs, loss_of, iters,
                     bf16_weights=True, moment_dtype=None):
    """Shared harness: jit fwd+bwd+AdamW as one program; each timed iter
    uses a DIFFERENT input batch (the axon tunnel replays identical
    executions from cache, which would fake the timing otherwise), and
    the final sync is a VALUE read (block_until_ready does not reliably
    drain the tunnel). With ``bf16_weights`` float params live
    bf16-resident with an f32 master in the optimizer (mixed-precision
    discipline: halves weight HBM traffic on the hot path; measured +3%
    tok/s on GPT-2 — but bf16-resident CONV weights compile ~15 min via
    the remote-compile tunnel for no gain, so the conv rung opts out)."""
    import paddle_tpu as paddle  # noqa: F401
    from paddle_tpu import amp

    b1, b2, eps, wd, lr = 0.9, 0.95, 1e-8, 0.1, 2.5e-4

    def bf16_resident(p):
        return bf16_weights and np.dtype(p._data.dtype) == np.float32

    # live and master are SEPARATELY donated arguments: each leaf must be
    # a distinct buffer (an aliased buffer donated twice is a runtime
    # error), so both are materialized as copies
    from paddle_tpu.optimizer.optimizer import (_moment_decode,
                                                _moment_encode)

    master = [jnp.array(p._data, copy=True) for p in params]
    live = [m.astype(jnp.bfloat16) if bf16_resident(p)
            else jnp.array(m, copy=True) for p, m in zip(params, master)]
    # free the model's ORIGINAL f32 arrays: master already holds the f32
    # copy, live the compute copy. Keeping the originals pinned costs
    # 4 B/param of dead HBM — at 1.3B params that alone is the difference
    # between fitting a 16 GB chip and RESOURCE_EXHAUSTED. (The params
    # are re-bound to traced values inside loss_fn on every step; the
    # eager payload is never read again in the bench.)
    for p, l in zip(params, live):
        p._data = l
    # moment_dtype: optimizer-state precision — "int8" stores m/v as
    # blockwise-quantized int8 (+1/256 f32 scales), the HBM knob that
    # fits the 1.4B rung on one 16 GB v5e (see optimizer.Adam)
    m_state = [_moment_encode(jnp.zeros_like(m), moment_dtype)
               for m in master]
    v_state = [_moment_encode(jnp.zeros_like(m), moment_dtype,
                              nonneg=True) for m in master]

    def train_step(live_arrays, master_arrays, m_st, v_st, step_t,
                   *inputs):
        def loss_fn(pa):
            originals = [p._data for p in params]
            for p, a in zip(params, pa):
                p._data = a
            try:
                with amp.auto_cast(level="O1", dtype="bfloat16"):
                    loss = loss_of(model, *inputs)
                return loss._data.astype(jnp.float32)
            finally:
                for p, o in zip(params, originals):
                    p._data = o

        loss, grads = jax.value_and_grad(loss_fn)(live_arrays)
        t = step_t.astype(jnp.float32)
        new_live, new_master, new_m, new_v = [], [], [], []
        for w, mw, g, m_enc, v_enc in zip(live_arrays, master_arrays,
                                          grads, m_st, v_st):
            g = g.astype(jnp.float32)
            shape = tuple(mw.shape)
            m = _moment_decode(m_enc, shape, moment_dtype)
            v = _moment_decode(v_enc, shape, moment_dtype, nonneg=True)
            m = b1 * m + (1 - b1) * g
            v = b2 * v + (1 - b2) * g * g
            m_hat = m / (1 - b1 ** t)
            v_hat = v / (1 - b2 ** t)
            mw = mw * (1 - lr * wd)
            mw = mw - lr * m_hat / (jnp.sqrt(v_hat) + eps)
            new_master.append(mw)
            new_live.append(mw.astype(w.dtype))
            new_m.append(_moment_encode(m, moment_dtype))
            new_v.append(_moment_encode(v, moment_dtype, nonneg=True))
        return loss, new_live, new_master, new_m, new_v

    jitted = jax.jit(train_step, donate_argnums=(0, 1, 2, 3))
    batches = [make_inputs(i) for i in range(iters + 1)]

    loss0, live, master, m_state, v_state = jitted(
        live, master, m_state, v_state, jnp.asarray(1, jnp.int32),
        *batches[0])
    loss0 = float(loss0)

    t0 = time.perf_counter()
    for i in range(iters):
        loss, live, master, m_state, v_state = jitted(
            live, master, m_state, v_state, jnp.asarray(2 + i, jnp.int32),
            *batches[1 + i])
    loss_end = float(loss)  # chained state: forces every iter to execute
    dt = (time.perf_counter() - t0) / iters
    n_params = sum(int(np.prod(m.shape)) for m in master)

    # attribution pass: two SYNCED steps under the span tracer (the timed
    # loop above stays async — per-step sync would change what it
    # measures). step_t keeps advancing, so the axon tunnel cannot serve
    # these as replays of the timed iterations.
    attribution = None
    try:
        from paddle_tpu.observability import perf as _perf

        state = {"s": (live, master, m_state, v_state), "i": 0}

        def attr_step():
            i, (lv, ms, m_s, v_s) = state["i"], state["s"]
            state["i"] += 1
            loss, *new = jitted(lv, ms, m_s, v_s,
                                jnp.asarray(2 + iters + i, jnp.int32),
                                *batches[1 + (i % iters)])
            state["s"] = tuple(new)
            return loss

        att = _perf.step_attribution(attr_step, iters=2, warmup=0,
                                     name="train_step")["total"]
        attribution = {k: round(att[k], 4) for k in
                       ("compute_frac", "collective_frac", "host_frac",
                        "idle_frac")}
        attribution["synced_step_s"] = round(att["step_s"]
                                             / max(att["n_steps"], 1), 4)
    except Exception:
        pass
    return dt, loss0, loss_end, n_params, attribution


def _env_int(name, default):
    v = os.environ.get(name)
    return default if v is None else int(v)


def _env_bool(name, default):
    v = os.environ.get(name)
    if v is None:
        return default
    return v.strip().lower() not in ("0", "false", "no", "off", "")


def _fusion_on() -> bool:
    """Ladder rungs record the graph-fusion flag state in extra, so a
    BENCH_*.json trajectory always says which regime it measured."""
    from paddle_tpu.core import flags
    return bool(flags.get_flag("enable_fusion"))


def _bench_gpt(small):
    import paddle_tpu as paddle
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    if small:
        cfg = GPTConfig(vocab_size=512, hidden_size=128, num_layers=2,
                        num_heads=4, max_seq_len=128,
                        use_flash_attention=False)
        batch, seq, iters = 2, 128, 2
    else:
        # BASELINE.md config #4: GPT-2 345M (gpt2-medium geometry)
        cfg = GPTConfig(hidden_size=1024, num_layers=24, num_heads=16,
                        max_seq_len=1024,
                        recompute=_env_bool("BENCH_RECOMPUTE", False),
                        fused_loss=_env_bool("BENCH_FUSED", True))
        batch, seq, iters = _env_int("BENCH_BATCH", 8), 1024, 10
    model = GPTForCausalLM(cfg)
    params = [p for p in model.parameters() if not p.stop_gradient]

    def make_inputs(i):
        rng = np.random.RandomState(i)
        return (jnp.asarray(rng.randint(
            0, cfg.vocab_size, (batch, seq)).astype(np.int64)),)

    def loss_of(model, ids):
        import paddle_tpu as paddle
        _, loss = model(paddle.Tensor(ids), labels=paddle.Tensor(ids))
        return loss

    dt, loss0, loss_end, n_params, attribution = _run_train_bench(
        model, params, make_inputs, loss_of, iters)
    tokens_per_sec = batch * seq / dt
    flops_per_token = 6 * n_params + \
        12 * cfg.num_layers * cfg.hidden_size * seq
    mfu = flops_per_token * tokens_per_sec / chip_peak_flops(
        jax.devices()[0])
    return {
        "metric": "gpt2_345m_train_tokens_per_sec_per_chip"
                  if not small else "gpt_tiny_train_tokens_per_sec",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.40, 4),
        "extra": {"step_time_s": round(dt, 4), "mfu": round(mfu, 4),
                  "params": n_params,
                  "device": str(getattr(jax.devices()[0], "device_kind",
                                        jax.default_backend())),
                  "attribution": attribution,
                  "fusion": _fusion_on(),
                  "loss_first": round(loss0, 3),
                  "loss_last": round(loss_end, 3)},
    }


def _bench_resnet50(small):
    import paddle_tpu as paddle
    from paddle_tpu.nn import functional as F
    from paddle_tpu.vision.models import resnet50

    batch, hw, iters = (4, 64, 2) if small else (256, 224, 10)
    model = resnet50()
    model.train()
    params = [p for p in model.parameters() if not p.stop_gradient]

    def make_inputs(i):
        rng = np.random.RandomState(i)
        return (jnp.asarray(rng.randn(batch, 3, hw, hw)
                            .astype(np.float32)),
                jnp.asarray(rng.randint(0, 1000, (batch,))
                            .astype(np.int64)))

    def loss_of(model, x, y):
        logits = model(paddle.Tensor(x))
        return F.cross_entropy(logits, paddle.Tensor(y))

    dt, loss0, loss_end, n_params, attribution = _run_train_bench(
        model, params, make_inputs, loss_of, iters, bf16_weights=False)
    imgs_per_sec = batch / dt
    # chip-relative utilization bar, consistent with the token rungs'
    # MFU-vs-0.40 treatment: ResNet50 training is ~12.3 GFLOPs/img
    # (3x the 4.1 GFLOP forward); the A100 reference 2080 img/s is
    # 2080*12.3e12/312e12 = 8.2% utilization of A100 peak bf16. Raw
    # img/s would compare chips, not frameworks.
    flops_per_img = 3 * 4.1e9
    util = flops_per_img * imgs_per_sec / chip_peak_flops(jax.devices()[0])
    a100_util = 2080 * flops_per_img / 312e12
    return {
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": round(imgs_per_sec, 1),
        "unit": "images/s",
        "vs_baseline": round(util / a100_util, 4),
        "extra": {"step_time_s": round(dt, 4), "params": n_params,
                  "batch": batch, "mfu": round(util, 4),
                  "a100_ref_util": round(a100_util, 4),
                  "attribution": attribution,
                  "fusion": _fusion_on(),
                  "loss_first": round(loss0, 3),
                  "loss_last": round(loss_end, 3)},
    }


def _bench_bert(small):
    import paddle_tpu as paddle
    from paddle_tpu.models.bert import BertConfig, BertForPretraining

    if small:
        cfg = BertConfig(vocab_size=512, hidden_size=128,
                         num_hidden_layers=2, num_attention_heads=4,
                         intermediate_size=256,
                         max_position_embeddings=128,
                         hidden_dropout_prob=0.0,
                         attention_probs_dropout_prob=0.0)
        batch, seq, iters = 2, 128, 2
    else:
        # vocab padded 30522 -> 30592 (next multiple of 128: MXU lane
        # alignment for the MLM head matmul, the standard GPT-2-style
        # padded-vocab trick); fused chunked head+loss
        import paddle_tpu as _p
        if not _env_bool("BENCH_FLASH", True):
            _p.set_flags({"use_pallas_kernels": False})
        cfg = BertConfig(vocab_size=_env_int("BENCH_VOCAB", 30592),
                         hidden_dropout_prob=0.0,
                         attention_probs_dropout_prob=0.0,
                         recompute=_env_bool("BENCH_RECOMPUTE", False),
                         fused_loss=_env_bool("BENCH_FUSED", True))
        batch, seq, iters = _env_int("BENCH_BATCH", 48), 512, 10
    model = BertForPretraining(cfg)
    params = [p for p in model.parameters() if not p.stop_gradient]

    def make_inputs(i):
        rng = np.random.RandomState(i)
        return (jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq))
                            .astype(np.int64)),)

    def loss_of(model, ids):
        _, _, loss = model(paddle.Tensor(ids),
                           masked_lm_labels=paddle.Tensor(ids))
        return loss

    dt, loss0, loss_end, n_params, attribution = _run_train_bench(
        model, params, make_inputs, loss_of, iters)
    tokens_per_sec = batch * seq / dt
    flops_per_token = 6 * n_params + \
        12 * cfg.num_hidden_layers * cfg.hidden_size * seq
    mfu = flops_per_token * tokens_per_sec / chip_peak_flops(
        jax.devices()[0])
    return {
        "metric": "bert_base_mlm_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.40, 4),
        "extra": {"step_time_s": round(dt, 4), "mfu": round(mfu, 4),
                  "params": n_params, "attribution": attribution,
                  "fusion": _fusion_on(),
                  "loss_first": round(loss0, 3),
                  "loss_last": round(loss_end, 3)},
    }


def _bench_llama(small):
    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaConfig, llama_tiny

    if small:
        cfg = llama_tiny(use_flash_attention=False)
        batch, seq, iters = 2, 128, 2
    else:
        # largest LLaMA that trains on one 16 GB v5e at S=2048 with
        # bf16-resident weights + f32 master + f32 Adam moments
        # (14 B/param of state) and block remat: ~770M params
        cfg = LlamaConfig(vocab_size=32000, hidden_size=1536,
                          intermediate_size=4096, num_layers=24,
                          num_heads=12, max_seq_len=2048,
                          recompute=_env_bool("BENCH_RECOMPUTE", True),
                          fused_loss=_env_bool("BENCH_FUSED", True))
        batch, seq, iters = _env_int("BENCH_BATCH", 4), 2048, 5
    from paddle_tpu.models import LlamaForCausalLM
    model = LlamaForCausalLM(cfg)
    params = [p for p in model.parameters() if not p.stop_gradient]

    def make_inputs(i):
        rng = np.random.RandomState(i)
        return (jnp.asarray(rng.randint(
            0, cfg.vocab_size, (batch, seq)).astype(np.int64)),)

    def loss_of(model, ids):
        _, loss = model(paddle.Tensor(ids), labels=paddle.Tensor(ids))
        return loss

    dt, loss0, loss_end, n_params, attribution = _run_train_bench(
        model, params, make_inputs, loss_of, iters)
    tokens_per_sec = batch * seq / dt
    flops_per_token = 6 * n_params + \
        12 * cfg.num_layers * cfg.hidden_size * seq
    mfu = flops_per_token * tokens_per_sec / chip_peak_flops(
        jax.devices()[0])
    return {
        "metric": "llama_770m_s2048_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.40, 4),
        "extra": {"step_time_s": round(dt, 4), "mfu": round(mfu, 4),
                  "params": n_params, "attribution": attribution,
                  "fusion": _fusion_on(),
                  "loss_first": round(loss0, 3),
                  "loss_last": round(loss_end, 3)},
    }


def _bench_llama14(small):
    """LLaMA-1.3B-class rung (BASELINE.md ladder #5 direction): the
    largest LLaMA one 16 GB v5e trains, enabled by int8 blockwise
    optimizer moments (~8 B/param of state vs 14 with f32 moments) +
    bf16-resident weights + block remat + fused chunked loss. The HBM
    budget table in README extrapolates this recipe to 7B on v5p-32."""
    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM, llama_tiny

    if small:
        cfg = llama_tiny(use_flash_attention=False)
        batch, seq, iters = 2, 128, 2
        moment_dtype = "int8"
    else:
        # LLaMA-1.3B geometry (h=2048, L=24, heads=16, inter=5504),
        # 1.345B params — the largest config that clears 1.0x baseline
        # on 16 GB (1.45B ALSO trains via BENCH_LAYERS=26 BENCH_BATCH=1,
        # measured MFU 0.354: memory fits, batch-1 underutilizes)
        cfg = LlamaConfig(vocab_size=32000, hidden_size=2048,
                          intermediate_size=5504,
                          num_layers=_env_int("BENCH_LAYERS", 24),
                          num_heads=16, max_seq_len=2048,
                          recompute=_env_bool("BENCH_RECOMPUTE", True),
                          fused_loss=_env_bool("BENCH_FUSED", True))
        batch, seq, iters = _env_int("BENCH_BATCH", 2), 2048, 4
        moment_dtype = os.environ.get("BENCH_MOMENT_DTYPE", "int8")
    model = LlamaForCausalLM(cfg)
    params = [p for p in model.parameters() if not p.stop_gradient]

    def make_inputs(i):
        rng = np.random.RandomState(i)
        return (jnp.asarray(rng.randint(
            0, cfg.vocab_size, (batch, seq)).astype(np.int64)),)

    def loss_of(model, ids):
        _, loss = model(paddle.Tensor(ids), labels=paddle.Tensor(ids))
        return loss

    dt, loss0, loss_end, n_params, attribution = _run_train_bench(
        model, params, make_inputs, loss_of, iters,
        moment_dtype=moment_dtype)
    tokens_per_sec = batch * seq / dt
    flops_per_token = 6 * n_params + \
        12 * cfg.num_layers * cfg.hidden_size * seq
    mfu = flops_per_token * tokens_per_sec / chip_peak_flops(
        jax.devices()[0])
    return {
        "metric": "llama_1p3b_s2048_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.40, 4),
        "extra": {"step_time_s": round(dt, 4), "mfu": round(mfu, 4),
                  "params": n_params, "moment_dtype": moment_dtype,
                  "attribution": attribution,
                  "fusion": _fusion_on(),
                  "loss_first": round(loss0, 3),
                  "loss_last": round(loss_end, 3)},
    }


def _bench_compile_cache(small):
    """Cold-start vs warm-start compile wall time through the persistent
    compilation cache (BENCH_MODEL=compile_cache; paddle_tpu/compile/).

    Cold = first call of a fresh StaticFunction with an empty cache
    (trace + lower + XLA compile + publish). Warm = first call of another
    fresh StaticFunction over the SAME program with the populated cache
    (deserialize the executable — the path a warmed serving replica's
    first request takes). vs_baseline is the cold/warm speedup.
    """
    import shutil
    import tempfile

    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.jit.api import to_static

    tmp = tempfile.mkdtemp(prefix="pcc_bench_")
    paddle.set_flags({"FLAGS_compile_cache": True,
                      "FLAGS_compile_cache_dir": tmp})
    try:
        d = 256 if small else 1024
        paddle.seed(0)

        class _Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.a = nn.Linear(d, d)
                self.b = nn.Linear(d, d)

            def forward(self, x):
                return paddle.ops.tanh(self.b(paddle.ops.tanh(self.a(x))))

        net = _Net()
        x = paddle.to_tensor(np.random.randn(8, d).astype(np.float32))

        def first_call_seconds():
            sf = to_static(net.forward, full_graph=True)
            t0 = time.perf_counter()
            out = sf(x)
            jax.block_until_ready(out._data)
            return time.perf_counter() - t0

        cold = first_call_seconds()   # miss: trace+lower+compile+publish
        warm = first_call_seconds()   # hit: deserialize the executable
    finally:
        paddle.set_flags({"FLAGS_compile_cache": False,
                          "FLAGS_compile_cache_dir": ""})
        shutil.rmtree(tmp, ignore_errors=True)
    speedup = cold / max(warm, 1e-9)
    return {
        "metric": "compile_cache_warm_speedup",
        "value": round(speedup, 3),
        "unit": "x_cold_start",
        "vs_baseline": round(speedup, 3),
        "extra": {"cold_start_s": round(cold, 4),
                  "warm_start_s": round(warm, 4),
                  "hidden": d, "host": jax.default_backend()},
    }


def _bench_serving(small):
    """Continuous-batching serving throughput (BENCH_MODEL=serving).

    Measures aggregate decode tokens/s of the paged-KV engine over a
    mixed-length request burst, against the SAME model decoding the same
    requests one at a time (single stream) — so vs_baseline is the
    continuous-batching speedup on this chip, an apples-to-apples ratio
    that needs no external reference number. bf16 weights/KV.
    """
    import paddle_tpu as paddle
    from paddle_tpu.inference.serving import LlamaPagedEngine
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    if small:
        cfg = LlamaConfig(vocab_size=97, hidden_size=64,
                          intermediate_size=128, num_layers=2, num_heads=4,
                          max_seq_len=256, use_flash_attention=False)
        n_req, new_tokens, max_batch = 4, 8, 2
        prompt_lens = (5, 9, 3, 7)
    else:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=1024,
                          intermediate_size=2816, num_layers=16,
                          num_heads=16, max_seq_len=1024,
                          use_flash_attention=False)
        n_req = _env_int("BENCH_REQUESTS", 24)
        new_tokens = _env_int("BENCH_NEW_TOKENS", 96)
        max_batch = _env_int("BENCH_BATCH", 8)
        rng = np.random.RandomState(7)
        prompt_lens = rng.randint(32, 192, size=n_req)
    model = LlamaForCausalLM(cfg)
    if not small:
        for p in model.parameters():  # bf16 weights: serving discipline
            if np.dtype(p._data.dtype) == np.float32:
                p._swap_payload(p._data.astype(jnp.bfloat16))
    rng = np.random.RandomState(11)
    prompts = [[int(t) for t in rng.randint(1, cfg.vocab_size, size=int(n))]
               for n in prompt_lens]

    def engine(batch):
        return LlamaPagedEngine(
            model, max_batch=batch, block_size=32,
            num_blocks=max(64, (max(len(p) for p in prompts)
                                + new_tokens) // 32 * batch * 2),
            max_blocks_per_seq=64)

    # ONE engine per mode, reused across requests — fresh engines would
    # re-jit their closures and the timings would measure compilation
    eng = engine(max_batch)
    e1 = engine(1)

    # warmup: compile prefill+decode programs for both engines
    for e in (eng, e1):
        e.add_request(prompts[0], max_new_tokens=4)
        e.run_to_completion()

    # continuous batching: one burst, all requests queued up front
    t0 = time.perf_counter()
    rids = [eng.add_request(p, max_new_tokens=new_tokens) for p in prompts]
    out = eng.run_to_completion()
    dt_batched = time.perf_counter() - t0
    total_new = sum(len(out[r]) for r in rids)

    # single stream: same requests through the single-slot engine, one
    # at a time (no batching, no recompiles)
    t0 = time.perf_counter()
    single_total = 0
    for p in prompts:
        rid = e1.add_request(p, max_new_tokens=new_tokens)
        single_total += len(e1.run_to_completion()[rid])
    dt_single = time.perf_counter() - t0

    batched_tps = total_new / dt_batched
    single_tps = single_total / dt_single
    return {
        "metric": "llama_serving_decode_tokens_per_sec_per_chip",
        "value": round(batched_tps, 1),
        "unit": "tokens/s",
        "vs_baseline": round(batched_tps / max(single_tps, 1e-9), 4),
        "extra": {"requests": int(n_req), "new_tokens": int(new_tokens),
                  "max_batch": int(max_batch),
                  "single_stream_tokens_per_sec": round(single_tps, 1),
                  "batched_wall_s": round(dt_batched, 3),
                  "single_wall_s": round(dt_single, 3)},
    }


def _bench_serving_resilience(small):
    """Serving-resilience rung (BENCH_MODEL=serving_resilience).

    Open-loop Poisson goodput-vs-offered-load curve through the paged
    engine with admission control + deadlines armed: a capacity probe
    (saturating arrivals, no deadlines) sizes the ladder, then 0.5x /
    1x / 2x capacity points run with SLO deadlines and a queue
    high-water mark, recording p50/p99 TTFT, inter-token latency,
    goodput, and shed/deadline-miss counts per point. vs_baseline is
    goodput retention under 2x overload (goodput@2x / goodput@1x) — a
    replica that collapses under overload scores near 0, one that sheds
    cleanly holds ~1.
    """
    import paddle_tpu as paddle
    from paddle_tpu.inference import PagedEngine, ResilienceConfig
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from tools.loadgen import run_load

    paddle.seed(7)
    if small:
        cfg = LlamaConfig(vocab_size=97, hidden_size=64,
                          intermediate_size=128, num_layers=2, num_heads=4,
                          max_seq_len=256, use_flash_attention=False)
        n_req, new_tokens, max_batch = 16, 6, 4
        prompt_range = (4, 16)
    else:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=1024,
                          intermediate_size=2816, num_layers=16,
                          num_heads=16, max_seq_len=1024,
                          use_flash_attention=False)
        n_req = _env_int("BENCH_REQUESTS", 48)
        new_tokens = _env_int("BENCH_NEW_TOKENS", 64)
        max_batch = _env_int("BENCH_BATCH", 8)
        prompt_range = (32, 160)
    model = LlamaForCausalLM(cfg)
    if not small:
        for p in model.parameters():  # bf16 weights: serving discipline
            if np.dtype(p._data.dtype) == np.float32:
                p._swap_payload(p._data.astype(jnp.bfloat16))
    blocks_needed = (prompt_range[1] + new_tokens + 31) // 32
    eng = PagedEngine(
        model, max_batch=max_batch, block_size=32,
        num_blocks=max(64, blocks_needed * max_batch * 2),
        max_blocks_per_seq=max(blocks_needed + 1, 8),
        resilience=ResilienceConfig(max_queue=4 * n_req,
                                    queue_high_water=4 * max_batch))
    eng.warmup(prompt_len=prompt_range[1] // 2,
               max_new_tokens=new_tokens)

    common = dict(n_requests=n_req, vocab_size=cfg.vocab_size,
                  prompt_len_range=prompt_range,
                  max_new_tokens=new_tokens, seed=13)
    # capacity probe: saturating arrivals, no deadlines — how fast can
    # this replica actually drain the stream
    probe = run_load(eng, offered_rps=10_000.0, **common)
    cap_rps = max(probe["goodput_requests_per_sec"], 1e-3)
    # SLO knobs sized from the probe so the ladder is chip-relative:
    # generous at 1x, binding under 2x overload queue delay
    ttft_dl = max((probe["p99_ttft_s"] or 0.01) * 8, 1e-3)
    total_dl = ttft_dl + 4 * new_tokens * (probe["p99_itl_s"] or 0.01)
    curve = []
    for mult in (0.5, 1.0, 2.0):
        pt = run_load(eng, offered_rps=mult * cap_rps,
                      ttft_deadline_s=ttft_dl, deadline_s=total_dl,
                      **common)
        pt["load_multiplier"] = mult
        curve.append(pt)
    eng.drain()
    health = eng.health()
    at_1x = curve[1]["goodput_tokens_per_sec"]
    at_2x = curve[2]["goodput_tokens_per_sec"]
    return {
        "metric": "serving_resilience_goodput_tokens_per_sec",
        "value": round(at_1x, 2),
        "unit": "tokens/s",
        # overload retention: sheds/misses must bound latency without
        # collapsing useful throughput (zero 1x goodput scores 0, not inf)
        "vs_baseline": round(at_2x / at_1x, 4) if at_1x > 0 else 0.0,
        "extra": {
            "capacity_requests_per_sec": round(cap_rps, 3),
            "ttft_deadline_s": round(ttft_dl, 5),
            "total_deadline_s": round(total_dl, 5),
            "goodput_vs_offered_load": curve,
            "final_replica_state": health["state"],
            "kv_blocks_leaked": (health["kv_blocks_total"]
                                 - health["kv_blocks_free"]),
        },
    }


def _bench_serving_router(small):
    """Multi-replica serving-tier rung (BENCH_MODEL=serving_router;
    paddle_tpu/serving/).

    Three questions, one rung:

    1. **Goodput scaling vs R** — the open-loop Poisson stream through
       the Router at saturating arrivals for R=1 and R=2 replicas (the
       replicas share one model, so compiled tick programs are shared).
       vs_baseline is goodput(R=2)/goodput(R=1): ~linear (≈2) on real
       chips where each replica owns a device; ≈1 on the CPU smoke host
       where all replicas share one core's compute — the frozen CPU
       value is a no-regression floor, the TPU ladder refreezes per
       PERF.md §7.
    2. **2x-overload SLO curve at R=2** — deadlines sized from the
       capacity probe, 0.5x/1x/2x offered load; overload must shed AT
       THE ROUTER (``shed_at_router``), never inside a replica
       (replicas run without a high-water mark), with p99 TTFT held.
    3. **int8-KV / speculative parity + efficiency** — greedy tokens
       from a ``kv_dtype="int8"`` engine and a ``speculate="ngram"``
       engine must equal the baseline engine's exactly; records the
       KV-bytes-per-token shrink (resident-batch multiplier) and the
       draft acceptance rate.
    """
    import paddle_tpu as paddle
    from paddle_tpu.inference import PagedEngine, ResilienceConfig
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.serving import Router, SchedulerConfig
    from tools.loadgen import run_load

    paddle.seed(7)
    if small:
        cfg = LlamaConfig(vocab_size=97, hidden_size=64,
                          intermediate_size=128, num_layers=2, num_heads=4,
                          max_seq_len=256, use_flash_attention=False)
        n_req, new_tokens, max_batch = 16, 6, 4
        prompt_range = (4, 16)
    else:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=1024,
                          intermediate_size=2816, num_layers=16,
                          num_heads=16, max_seq_len=1024,
                          use_flash_attention=False)
        n_req = _env_int("BENCH_REQUESTS", 48)
        new_tokens = _env_int("BENCH_NEW_TOKENS", 64)
        max_batch = _env_int("BENCH_BATCH", 8)
        prompt_range = (32, 160)
    model = LlamaForCausalLM(cfg)
    if not small:
        for p in model.parameters():  # bf16 weights: serving discipline
            if np.dtype(p._data.dtype) == np.float32:
                p._swap_payload(p._data.astype(jnp.bfloat16))
    blocks_needed = (prompt_range[1] + new_tokens + 31) // 32

    def mk_replica(max_queue):
        # phase-split on (one chunk batch worth of prefill per tick) and
        # NO replica-side high-water mark: the router owns shedding
        return PagedEngine(
            model, max_batch=max_batch, block_size=32,
            num_blocks=max(64, blocks_needed * max_batch * 2),
            max_blocks_per_seq=max(blocks_needed + 1, 8),
            scheduler=SchedulerConfig(prefill_token_budget=32 * max_batch),
            resilience=ResilienceConfig(max_queue=max_queue,
                                        queue_high_water=None))

    common = dict(n_requests=n_req, vocab_size=cfg.vocab_size,
                  prompt_len_range=prompt_range,
                  max_new_tokens=new_tokens, seed=13)
    # --- goodput scaling vs R (saturating arrivals, no deadlines) ---
    goodput_vs_r = {}
    for r in (1, 2):
        # deep queues for the capacity probe: it measures drain rate.
        # 2x the request count here — the scaling ratio is the frozen
        # headline and short probes are noisy on the CPU smoke host
        tier = Router([mk_replica(8 * n_req) for _ in range(r)]).warmup()
        pt = run_load(tier, offered_rps=10_000.0,
                      **dict(common, n_requests=2 * n_req))
        tier.drain()
        goodput_vs_r[r] = pt
    g1 = goodput_vs_r[1]["goodput_tokens_per_sec"]
    g2 = goodput_vs_r[2]["goodput_tokens_per_sec"]
    scaling = (g2 / g1) if g1 > 0 else 0.0
    cap_rps = max(goodput_vs_r[2]["goodput_requests_per_sec"], 1e-3)
    ttft_dl = max((goodput_vs_r[2]["p99_ttft_s"] or 0.01) * 8, 1e-3)
    total_dl = ttft_dl + 4 * new_tokens * (
        goodput_vs_r[2]["p99_itl_s"] or 0.01)

    # --- 2x-overload SLO curve at R=2, shedding at the router ---
    curve = []
    replica_side_shed = 0
    # the final point is an instantaneous burst of 4x the request count:
    # arrivals the tier can NEVER absorb must shed at the router (bounded
    # replica queues bounce them back), not pile into replica queues
    points = [(0.5, n_req), (1.0, n_req), (2.0, n_req),
              ("burst", 4 * n_req)]
    for mult, n in points:
        # bounded queues for the SLO curve: past-capacity arrivals must
        # bounce off replica admission and shed at the router
        tier = Router([mk_replica(max(max_batch, 4))
                       for _ in range(2)]).warmup()
        rate = 10_000.0 if mult == "burst" else mult * cap_rps
        pt = run_load(tier, offered_rps=rate,
                      ttft_deadline_s=ttft_dl, deadline_s=total_dl,
                      **dict(common, n_requests=n))
        tier.drain()
        pt["load_multiplier"] = mult
        pt["shed_at_router"] = pt["router"]["shed_at_router"]
        # replica-internal sheds must stay 0 — overload policy lives at
        # the router (replicas have no high-water mark; their bounded
        # queues surface as router retries, not drops)
        replica_side_shed += pt["shed"] - pt["shed_at_router"]
        curve.append(pt)
    at_1x = curve[1]["goodput_tokens_per_sec"]
    at_2x = curve[2]["goodput_tokens_per_sec"]

    # --- int8-KV + speculative parity against the baseline engine ---
    rng = np.random.RandomState(5)
    prompts = [[int(t) for t in rng.randint(1, cfg.vocab_size, size=n)]
               for n in rng.randint(prompt_range[0], prompt_range[1],
                                    size=4)]

    def greedy_tokens(**kw):
        eng = PagedEngine(model, max_batch=max_batch, block_size=32,
                          num_blocks=max(64, blocks_needed * max_batch * 2),
                          max_blocks_per_seq=max(blocks_needed + 1, 8),
                          **kw)
        rids = [eng.add_request(p, max_new_tokens=new_tokens)
                for p in prompts]
        out = eng.run_to_completion()
        return [out[rid] for rid in rids], eng

    base_toks, base_eng = greedy_tokens()
    int8_toks, int8_eng = greedy_tokens(kv_dtype="int8")
    spec_toks, spec_eng = greedy_tokens(speculate="ngram", speculate_k=4)

    return {
        "metric": "serving_router_goodput_scaling",
        "value": round(scaling, 4),
        "unit": "x_R1",
        # overload retention through the ROUTER's shedding (same shape
        # as the serving_resilience rung, now tier-level)
        "vs_baseline": round(at_2x / at_1x, 4) if at_1x > 0 else 0.0,
        "extra": {
            "goodput_tokens_per_sec_R1": round(g1, 2),
            "goodput_tokens_per_sec_R2": round(g2, 2),
            "capacity_requests_per_sec_R2": round(cap_rps, 3),
            "ttft_deadline_s": round(ttft_dl, 5),
            "total_deadline_s": round(total_dl, 5),
            "goodput_vs_offered_load_R2": curve,
            "shed_at_router_total": sum(
                pt["shed_at_router"] for pt in curve),
            "replica_side_shed_total": replica_side_shed,
            "int8_kv_parity": int8_toks == base_toks,
            "int8_kv_bytes_per_token": int8_eng.kv_bytes_per_token,
            "base_kv_bytes_per_token": base_eng.kv_bytes_per_token,
            "resident_batch_multiplier": round(
                base_eng.kv_bytes_per_token
                / int8_eng.kv_bytes_per_token, 3),
            "speculative_parity": spec_toks == base_toks,
            "spec_acceptance_rate": round(
                spec_eng.spec_accepted / spec_eng.spec_proposed, 4)
            if spec_eng.spec_proposed else None,
        },
    }


def _bench_serving_reqtrace(small):
    """Request-trace overhead rung (BENCH_MODEL=serving_reqtrace;
    paddle_tpu/observability/reqtrace.py). The SAME steady-state decode
    tick — a full batch of long-running requests, so every tick records
    one decode_tick event per slot plus the per-token exemplar/TTFT
    bookkeeping — timed with ``FLAGS_reqtrace`` fully OFF vs fully ON.
    value = off/on tick-time ratio (1.0 = free); the acceptance bar is
    overhead < 2%. Paired per-tick A/B with alternating order (the
    round-14 fleet_observability estimator: median over ALL signed pair
    diffs, so host drift cancels inside pairs and slot-position bias
    across them)."""
    import paddle_tpu as paddle
    from paddle_tpu.core import flags
    from paddle_tpu.inference import PagedEngine
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.observability import reqtrace

    paddle.seed(7)
    if small:
        cfg = LlamaConfig(vocab_size=97, hidden_size=64,
                          intermediate_size=128, num_layers=2,
                          num_heads=4, max_seq_len=4096,
                          use_flash_attention=False)
        pairs, max_batch = 300, 4
    else:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=1024,
                          intermediate_size=2816, num_layers=16,
                          num_heads=16, max_seq_len=4096,
                          use_flash_attention=False)
        pairs, max_batch = _env_int("BENCH_REQTRACE_PAIRS", 150), 8
    model = LlamaForCausalLM(cfg)
    warm = 20
    ticks_needed = warm + 2 * pairs + 16
    prompt_len = 8
    bs = 16
    bps = -(-(prompt_len + ticks_needed + bs) // bs) + 1
    eng = PagedEngine(model, max_batch=max_batch, block_size=bs,
                      num_blocks=max_batch * bps + 2,
                      max_blocks_per_seq=bps)
    rng = np.random.RandomState(3)
    for _ in range(max_batch):
        eng.add_request(
            [int(t) for t in rng.randint(1, cfg.vocab_size,
                                         size=prompt_len)],
            max_new_tokens=ticks_needed)

    prev = flags.get_flag("reqtrace")
    t_off, diffs = [], []

    def one_tick():
        t0 = time.perf_counter()
        eng.step()
        return time.perf_counter() - t0

    try:
        flags.set_flags({"reqtrace": True})
        for _ in range(warm):          # compiles + steady decode shape
            eng.step()
        for i in range(pairs):
            if i % 2 == 0:
                flags.set_flags({"reqtrace": False})
                d_off = one_tick()
                flags.set_flags({"reqtrace": True})
                d_on = one_tick()
            else:
                flags.set_flags({"reqtrace": True})
                d_on = one_tick()
                flags.set_flags({"reqtrace": False})
                d_off = one_tick()
            t_off.append(d_off)
            diffs.append(d_on - d_off)
        recorded = sum(len(tl["events"]) for tl in
                       reqtrace.RECORDER.live_timelines())
    finally:
        flags.set_flags({"reqtrace": prev})
        eng.drain()
        # the measurement's torn half-traced timelines and exemplars
        # must not pollute the process stores a later rung might inspect
        reqtrace.RECORDER.clear()
        reqtrace.EXEMPLARS.clear()
    off = float(np.median(t_off))
    on = off + float(np.median(diffs))
    ratio = off / max(on, 1e-12)
    overhead_pct = (on / max(off, 1e-12) - 1.0) * 100.0
    return {
        "metric": "serving_reqtrace_overhead_ratio",
        "value": round(ratio, 4),
        "unit": "x_untraced",
        "vs_baseline": round(ratio, 4),
        "extra": {"overhead_pct": round(overhead_pct, 3),
                  "tick_off_us": round(off * 1e6, 1),
                  "tick_on_us": round(on * 1e6, 1),
                  "ticks_per_config": pairs,
                  "batch": max_batch,
                  "events_recorded": recorded,
                  "within_budget": bool(overhead_pct < 2.0)},
    }


def _bench_verifier_overhead(small):
    """Program-verifier overhead rung (BENCH_MODEL=verifier_overhead;
    paddle_tpu/static/verifier.py). The verifier runs ONCE per new
    compile signature, so its budget is a fraction of trace+lower —
    not of the step. Measures (a) trace+lower wall of the GPT ladder
    block's recorded program (fresh jax.jit + .lower per rep, verifier
    off) and (b) the full verifier pass stack over the same recorded
    op list; value = trace_lower / (trace_lower + verify) (1.0 = free),
    acceptance bar: verify < 2% of trace+lower."""
    import paddle_tpu as paddle
    from paddle_tpu import static
    from paddle_tpu.core import flags
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.nn import functional as F
    from paddle_tpu.static import verifier
    import paddle_tpu.ops as pops

    paddle.seed(7)
    model = GPTForCausalLM(GPTConfig(
        vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
        max_seq_len=16, use_flash_attention=False))

    def record_once():
        """One program capture of the GPT block + loss (pays the
        recorder — and, in warn mode, the per-op provenance walk)."""
        prog = static.Program()
        with static.program_guard(prog):
            ids = static.data("ids", [2, 8], "int64")
            logits = model(ids)
            if isinstance(logits, (tuple, list)):
                logits = logits[0]
            v = logits.shape[-1]
            loss = F.cross_entropy(
                pops.reshape(logits[:, :-1, :], [-1, v]),
                pops.reshape(ids[:, 1:], [-1])).mean()
        return prog, [id(loss)]

    prev = flags.get_flag("verify_programs")
    reps = 5 if small else _env_int("BENCH_VERIFIER_REPS", 10)
    try:
        # per-op recording cost of the default-on warn mode: the
        # dispatch recorder pays mode() + the bounded user_loc stack
        # walk per op — measured as record-on minus record-off
        t_rec = {}
        for mode_ in ("off", "warn"):
            flags.set_flags({"verify_programs": mode_})
            samples = []
            for _ in range(reps):
                t0 = time.perf_counter()
                prog, fetch_ids = record_once()
                samples.append(time.perf_counter() - t0)
            t_rec[mode_] = float(np.median(samples))

        flags.set_flags({"verify_programs": "off"})
        prog, fetch_ids = record_once()     # loc-free timing substrate
        names = sorted(prog.feed_vars)
        feed_ids = [prog.feed_vars[n] for n in names]
        cap_ids = list(prog._captured.keys())
        cap_arrays = [t._data for t in prog._captured.values()]
        feeds = [jnp.zeros(tuple(abs(s) for s in prog._feed_shapes[n]),
                           dtype=np.dtype(prog._feed_dtypes[n]))
                 for n in names]

        t_tl = []
        for _ in range(reps):
            def replay(feed_arrays, caps):
                env = prog._replay_by_ids(feed_ids, feed_arrays,
                                          cap_ids, caps)
                return [env[i] for i in fetch_ids]

            t0 = time.perf_counter()
            jax.jit(replay).lower(feeds, cap_arrays)
            t_tl.append(time.perf_counter() - t0)

        t_v = []
        report = None
        for _ in range(reps * 4):
            t0 = time.perf_counter()
            report = verifier.check(prog, fetch_ids=fetch_ids)
            t_v.append(time.perf_counter() - t0)
        assert report is not None and not report.findings, \
            "ladder program must verify clean"
    finally:
        flags.set_flags({"verify_programs": prev})
    trace_lower = float(np.median(t_tl))
    verify = float(np.median(t_v))
    record = max(0.0, t_rec["warn"] - t_rec["off"])
    overhead = verify + record
    ratio = trace_lower / max(trace_lower + overhead, 1e-12)
    overhead_pct = overhead / max(trace_lower, 1e-12) * 100.0
    return {
        "metric": "verifier_overhead_ratio",
        "value": round(ratio, 4),
        "unit": "x_unverified_compile",
        "vs_baseline": round(ratio, 4),
        "extra": {"overhead_pct": round(overhead_pct, 3),
                  "trace_lower_ms": round(trace_lower * 1e3, 2),
                  "verify_ms": round(verify * 1e3, 3),
                  "record_overhead_ms": round(record * 1e3, 3),
                  "ops": len(prog.global_block().ops),
                  "within_budget": bool(overhead_pct < 2.0)},
    }


def _bench_static_analysis(small):
    """Static memory-analyzer rung (BENCH_MODEL=static_analysis;
    paddle_tpu/static/liveness.py). Like the verifier rung, the
    analyzer runs ONCE per new compile signature, so its budget is a
    fraction of trace+lower. Measures (a) trace+lower wall of the GPT
    ladder block's recorded program (fresh jax.jit + .lower per rep)
    and (b) the full round-22 static stack over the same op list —
    liveness intervals + peak curve (peak_report), the TPU75x alias
    pass, and the TPU9xx capacity pass; value =
    trace_lower / (trace_lower + analysis) (1.0 = free), acceptance
    bar: analysis < 2% of trace+lower."""
    import paddle_tpu as paddle
    from paddle_tpu import static
    from paddle_tpu.core import flags
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.nn import functional as F
    from paddle_tpu.static import liveness, verifier
    import paddle_tpu.ops as pops

    paddle.seed(7)
    model = GPTForCausalLM(GPTConfig(
        vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
        max_seq_len=16, use_flash_attention=False))

    prev = flags.get_flag("verify_programs")
    reps = 5 if small else _env_int("BENCH_STATIC_ANALYSIS_REPS", 10)
    try:
        flags.set_flags({"verify_programs": "off"})
        prog = static.Program()
        with static.program_guard(prog):
            ids = static.data("ids", [2, 8], "int64")
            logits = model(ids)
            if isinstance(logits, (tuple, list)):
                logits = logits[0]
            v = logits.shape[-1]
            loss = F.cross_entropy(
                pops.reshape(logits[:, :-1, :], [-1, v]),
                pops.reshape(ids[:, 1:], [-1])).mean()
        fetch_ids = [id(loss)]
        names = sorted(prog.feed_vars)
        feed_ids = [prog.feed_vars[n] for n in names]
        cap_ids = list(prog._captured.keys())
        cap_arrays = [t._data for t in prog._captured.values()]
        feeds = [jnp.zeros(tuple(abs(s) for s in prog._feed_shapes[n]),
                           dtype=np.dtype(prog._feed_dtypes[n]))
                 for n in names]

        t_tl = []
        for _ in range(reps):
            def replay(feed_arrays, caps):
                env = prog._replay_by_ids(feed_ids, feed_arrays,
                                          cap_ids, caps)
                return [env[i] for i in fetch_ids]

            t0 = time.perf_counter()
            jax.jit(replay).lower(feeds, cap_arrays)
            t_tl.append(time.perf_counter() - t0)

        t_a = []
        rep_out = None
        peak = None
        for _ in range(reps * 4):
            t0 = time.perf_counter()
            rep_out = verifier.Report(label="bench_static")
            liveness.alias_pass(prog, rep_out, fetch_ids=fetch_ids)
            liveness.memory_pass(prog, rep_out, fetch_ids=fetch_ids)
            peak = liveness.peak_report(prog, fetch_ids=fetch_ids)
            t_a.append(time.perf_counter() - t0)
        assert rep_out is not None and not rep_out.findings, \
            "ladder program must analyze clean"
        assert peak is not None and peak["peak_bytes"] > 0
    finally:
        flags.set_flags({"verify_programs": prev})
    trace_lower = float(np.median(t_tl))
    analysis = float(np.median(t_a))
    ratio = trace_lower / max(trace_lower + analysis, 1e-12)
    overhead_pct = analysis / max(trace_lower, 1e-12) * 100.0
    return {
        "metric": "static_analysis_overhead_ratio",
        "value": round(ratio, 4),
        "unit": "x_unanalyzed_compile",
        "vs_baseline": round(ratio, 4),
        "extra": {"overhead_pct": round(overhead_pct, 3),
                  "trace_lower_ms": round(trace_lower * 1e3, 2),
                  "analysis_ms": round(analysis * 1e3, 3),
                  "static_peak_bytes": peak["peak_bytes"],
                  "peak_op": peak["peak_op"]["name"],
                  "ops": len(prog.global_block().ops),
                  "within_budget": bool(overhead_pct < 2.0)},
    }


def _bench_spmd_auto(small):
    """SPMD auto-sharding rung (BENCH_MODEL=spmd_auto;
    paddle_tpu/distributed/spmd/). The SAME weights run one GPT
    fwd+bwd step two ways on the same (data, tp) mesh: (a) the
    hand-built fleet TP layers (ColumnParallel/RowParallel +
    VocabParallelEmbedding), (b) the plain model auto-sharded by the
    propagation subsystem. Records loss parity, both step times, their
    ratio (vs_baseline: >= 1 means auto is at least as fast as the
    hand-built path), fallback count (must be 0), and the round-12
    per-step attribution of the auto step."""
    import paddle_tpu as paddle
    import paddle_tpu.distributed.fleet as fleet_pkg
    from jax.sharding import PartitionSpec as P
    from paddle_tpu.distributed import mesh as mesh_mod, spmd
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    n_dev = jax.device_count()
    tp = 2 if n_dev >= 2 else 1
    data = max(n_dev // tp, 1)
    if small:
        cfg_kw = dict(vocab_size=512, hidden_size=128, num_layers=2,
                      num_heads=4, max_seq_len=128,
                      use_flash_attention=False)
        batch, seq, iters = 4, 128, 3
    else:
        cfg_kw = dict(hidden_size=1024, num_layers=24, num_heads=16,
                      max_seq_len=1024)
        batch, seq, iters = _env_int("BENCH_BATCH", 8), 1024, 5
    rng = np.random.RandomState(0)
    ids = rng.randint(0, GPTConfig(**cfg_kw).vocab_size,
                      (batch, seq)).astype(np.int64)

    def step_fn_for(model, mesh=None):
        params = [p for p in model.parameters() if not p.stop_gradient]

        def f(pa, ids_a):
            originals = [p._data for p in params]
            for p, a in zip(params, pa):
                p._data = a
            try:
                if mesh is None:
                    t = paddle.Tensor(ids_a)
                    _, loss = model(t, labels=t)
                    return loss._data
                sc = spmd.trace_scope(mesh)
                with sc:
                    for p in params:
                        spec = spmd.param_spec_of(p)
                        if spec is not None:
                            sc.seed(p, spec)
                    t = paddle.Tensor(ids_a)
                    sc.seed(t, P("data"))
                    _, loss = model(t, labels=t)
                stats["scope"] = dict(sc.stats)
                return loss._data
            finally:
                for p, o in zip(params, originals):
                    p._data = o

        stats = {}
        grad_f = jax.jit(jax.value_and_grad(f))
        pa = [p._data for p in params]
        return grad_f, pa, stats

    def timed(grad_f, pa):
        loss, grads = grad_f(pa, ids)       # compile + warm
        jax.block_until_ready(grads)
        t0 = time.perf_counter()
        for _ in range(iters):
            loss, grads = grad_f(pa, ids)
        jax.block_until_ready(grads)
        jax.block_until_ready(loss)
        return (time.perf_counter() - t0) / iters, float(loss)

    prev_mesh = mesh_mod._global_mesh
    try:
        # (a) hand-built fleet TP path
        strategy = fleet_pkg.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": data, "mp_degree": tp}
        fleet_pkg.fleet.init(is_collective=True, strategy=strategy)
        paddle.seed(1234)
        tp_model = GPTForCausalLM(GPTConfig(mp_degree=tp, **cfg_kw))
        state = {k: np.asarray(v.numpy())
                 for k, v in tp_model.state_dict().items()}
        fleet_f, fleet_pa, _ = step_fn_for(tp_model)
        fleet_dt, fleet_loss = timed(fleet_f, fleet_pa)

        # (b) plain model auto-sharded over the same mesh, SAME weights
        mesh_mod._global_mesh = None
        mesh = mesh_mod.build_mesh({"data": data, "tp": tp})
        mesh_mod.set_mesh(mesh)
        paddle.seed(1234)
        auto_model = GPTForCausalLM(GPTConfig(**cfg_kw))
        auto_model.set_state_dict(state)
        spmd.shard_params(auto_model, mesh, [
            (r".*qkv_proj\.weight", P(None, "tp")),
            (r".*qkv_proj\.bias", P("tp")),
            (r".*fc1\.weight", P(None, "tp")),
            (r".*fc1\.bias", P("tp")),
            (r".*(out_proj|fc2)\.weight", P("tp", None)),
            (r".*wte\.weight", P("tp", None)),
        ])
        auto_f, auto_pa, auto_stats = step_fn_for(auto_model, mesh=mesh)
        auto_dt, auto_loss = timed(auto_f, auto_pa)

        # per-step device attribution of the auto path (round-12 layer)
        attribution = None
        try:
            from paddle_tpu.observability import perf as _perf
            att = _perf.step_attribution(
                lambda: jax.block_until_ready(
                    auto_f(auto_pa, ids)[0]),
                iters=2, warmup=0, name="spmd_auto_step")["total"]
            attribution = {k: round(att[k], 4) for k in
                           ("compute_frac", "collective_frac",
                            "host_frac", "idle_frac")}
        except Exception:
            pass
    finally:
        mesh_mod._global_mesh = prev_mesh

    scope = auto_stats.get("scope", {})
    parity = abs(auto_loss - fleet_loss) <= 1e-3 * max(
        abs(fleet_loss), 1.0)
    return {
        "metric": "spmd_auto_vs_fleet_tp_step_ratio",
        "value": round(fleet_dt / max(auto_dt, 1e-9), 4),
        "unit": "x_fleet_tp",
        # parity is the gate: a fast-but-wrong program scores 0
        "vs_baseline": round(fleet_dt / max(auto_dt, 1e-9), 4)
        if parity else 0.0,
        "extra": {"mesh": {"data": data, "tp": tp},
                  "auto_step_s": round(auto_dt, 4),
                  "fleet_tp_step_s": round(fleet_dt, 4),
                  "loss_auto": round(auto_loss, 5),
                  "loss_fleet_tp": round(fleet_loss, 5),
                  "loss_parity": bool(parity),
                  "fallback_ops": scope.get("fallback", {}),
                  "ops_annotated": scope.get("annotated"),
                  "attribution": attribution},
    }


def _bench_embedding(small):
    """Giant-embedding rung (BENCH_MODEL=embedding;
    paddle_tpu/distributed/embedding/ + models/dlrm.py). The SAME DLRM
    weights run one fwd+bwd step two ways: (a) table replicated (the
    baseline — only possible at smoke scale), (b) table row-sharded
    over the (data, fsdp) mesh with dedup-before-exchange lookups.
    Three gates ride the score:

    * loss parity (rtol 1e-3) between the sharded and replicated step,
    * the static capacity proof: on the virtual 8-chip pod mesh the
      liveness analyzer shows the replicated program over a synthetic
      per-chip HBM budget while the row-sharded placement (zero
      replicate-fallbacks on the embedding path) fits under it,
    * the dedup win: modeled exchange bytes for the deduped rows <
      naive per-id gather bytes on a zipf id batch (the live
      paddle_tpu_embedding_unique_ratio gauge rides in extra).

    Value = replicated/sharded step-time ratio — a no-regression floor
    at smoke scale (dedup costs a sort); on a real pod the replicated
    baseline cannot even materialize the table, which is the point."""
    import types

    import paddle_tpu as paddle
    from jax.sharding import PartitionSpec as P
    from paddle_tpu import static
    from paddle_tpu.distributed import embedding as emb
    from paddle_tpu.distributed import mesh as mesh_mod, spmd
    from paddle_tpu.distributed.spmd.propagate import propagate_program
    from paddle_tpu.models import DLRM, DLRMConfig
    from paddle_tpu.observability import metrics as _metrics
    from paddle_tpu.static import liveness

    n_dev = jax.device_count()
    data = 2 if n_dev >= 4 else 1
    fsdp = max(n_dev // data, 1)
    if small:
        cfg_kw = dict(num_embeddings=65536, embedding_dim=64,
                      n_dense=8, n_sparse=8, bag_size=4,
                      bottom_mlp=(32,), top_mlp=(64,))
        batch, iters = 64, 3
    else:
        cfg_kw = dict(num_embeddings=4_000_000, embedding_dim=128,
                      n_dense=13, n_sparse=26, bag_size=8,
                      bottom_mlp=(512, 256), top_mlp=(512, 256))
        batch, iters = _env_int("BENCH_BATCH", 1024), 5
    cfg = DLRMConfig(**cfg_kw)
    F_, L = cfg.n_sparse, cfg.bag_size
    rng = np.random.RandomState(0)
    dense_np = rng.randn(batch, cfg.n_dense).astype(np.float32)
    # zipf ids: the recsys regime dedup exists for — a few hot rows
    # dominate, so uniques << total lookups
    ids_np = (rng.zipf(1.5, (batch, F_, L)) - 1) % cfg.num_embeddings
    ids_np = ids_np.astype(np.int64)
    labels_np = rng.randint(0, 2, (batch,)).astype(np.float32)

    def step_fn_for(model, mesh=None):
        params = [p for p in model.parameters() if not p.stop_gradient]

        def f(pa, dense_a, ids_a, labels_a):
            originals = [p._data for p in params]
            for p, a in zip(params, pa):
                p._data = a
            try:
                if mesh is None:
                    return model.loss(paddle.Tensor(dense_a),
                                      paddle.Tensor(ids_a),
                                      paddle.Tensor(labels_a))._data
                sc = spmd.trace_scope(mesh)
                with sc:
                    for p in params:
                        spec = spmd.param_spec_of(p)
                        if spec is not None:
                            sc.seed(p, spec)
                    d = paddle.Tensor(dense_a)
                    i = paddle.Tensor(ids_a)
                    y = paddle.Tensor(labels_a)
                    sc.seed(d, P("data"))
                    sc.seed(i, P("data"))
                    sc.seed(y, P("data"))
                    loss = model.loss(d, i, y)
                stats["scope"] = dict(sc.stats)
                return loss._data
            finally:
                for p, o in zip(params, originals):
                    p._data = o

        stats = {}
        grad_f = jax.jit(jax.value_and_grad(f))
        pa = [p._data for p in params]
        return grad_f, pa, stats

    def timed(grad_f, pa):
        loss, grads = grad_f(pa, dense_np, ids_np, labels_np)
        jax.block_until_ready(grads)
        t0 = time.perf_counter()
        for _ in range(iters):
            loss, grads = grad_f(pa, dense_np, ids_np, labels_np)
        jax.block_until_ready(grads)
        jax.block_until_ready(loss)
        return (time.perf_counter() - t0) / iters, float(loss)

    prev_mesh = mesh_mod._global_mesh
    prev_metrics = paddle.get_flags(["FLAGS_enable_metrics"])[
        "FLAGS_enable_metrics"]
    try:
        # (a) replicated baseline: same weights, table on every chip
        paddle.seed(1234)
        repl_model = DLRM(cfg)
        state = {k: np.asarray(v.numpy())
                 for k, v in repl_model.state_dict().items()}
        repl_f, repl_pa, _ = step_fn_for(repl_model)
        repl_dt, repl_loss = timed(repl_f, repl_pa)

        # (b) table row-sharded over (data, fsdp), dedup lookups
        mesh_mod._global_mesh = None
        mesh = mesh_mod.build_mesh({"data": data, "fsdp": fsdp})
        mesh_mod.set_mesh(mesh)
        paddle.seed(1234)
        shard_model = DLRM(cfg, mesh=mesh)
        shard_model.set_state_dict(state)
        shard_model.shard_(mesh)      # re-pin: set_state_dict swaps payloads
        paddle.set_flags({"FLAGS_enable_metrics": True})
        # one eager lookup feeds the dedup gauges (the jitted step's
        # tracer skips host-side metric reads by design)
        shard_model.embedding.bag(paddle.Tensor(ids_np))
        ureg = _metrics.REGISTRY.get("paddle_tpu_embedding_unique_ratio")
        unique_ratio_gauge = ureg.value() if ureg is not None else None
        shard_f, shard_pa, shard_stats = step_fn_for(shard_model,
                                                     mesh=mesh)
        shard_dt, shard_loss = timed(shard_f, shard_pa)
    finally:
        paddle.set_flags({"FLAGS_enable_metrics": prev_metrics})
        mesh_mod._global_mesh = prev_mesh

    # ---- static capacity proof on the virtual pod mesh -------------
    # The proof is device-independent: propagation + liveness only read
    # axis SIZES, so the 8-chip (data=2, fsdp=4) pod is analyzed even
    # when the smoke host has one device.
    pod = types.SimpleNamespace(shape={"data": 2, "fsdp": 4})
    table_param = shard_model.embedding.weight
    prog = static.Program()
    with static.program_guard(prog):
        d_s = static.data("dense", [batch, cfg.n_dense], "float32")
        i_s = static.data("ids", [batch, F_, L], "int64")
        y_s = static.data("labels", [batch], "float32")
        out = shard_model.loss(d_s, i_s, y_s)
    fetch = [id(out)]
    in_specs = {"dense": P("data"), "ids": P("data"),
                "labels": P("data")}

    def pod_table_spec(t):
        return ("fsdp", None) if t is table_param else None

    plan = propagate_program(prog, pod, in_specs,
                             param_specs=pod_table_spec)
    emb_ops = ("embedding", "embedding_bag", "scatter_add")
    emb_fallbacks = {k: v for k, v in plan.fallback_ops.items()
                     if k in emb_ops}
    rep_shard = liveness.peak_report(prog, fetch_ids=fetch, plan=plan,
                                     mesh=pod)
    rep_repl = liveness.peak_report(prog, fetch_ids=fetch)
    # synthetic per-chip budget between the two peaks: the replicated
    # program provably does NOT fit where the sharded one does
    budget = (rep_shard["peak_bytes"] * rep_repl["peak_bytes"]) ** 0.5
    liveness_ok = (rep_repl["peak_bytes"] > budget
                   > rep_shard["peak_bytes"])

    # ---- dedup exchange model on the zipf batch --------------------
    stats = emb.dedup_stats(ids_np)
    pod_shards = 4                    # the pod proof's fsdp extent
    ex_bytes = emb.exchange_bytes(stats["n_unique"], cfg.embedding_dim,
                                  pod_shards)
    naive_bytes = emb.naive_gather_bytes(stats["n_ids"],
                                         cfg.embedding_dim, pod_shards)
    dedup_ok = ex_bytes < naive_bytes

    parity = abs(shard_loss - repl_loss) <= 1e-3 * max(
        abs(repl_loss), 1.0)
    gate = (parity and liveness_ok and dedup_ok
            and not emb_fallbacks)
    scope = shard_stats.get("scope", {})
    ratio = repl_dt / max(shard_dt, 1e-9)
    return {
        "metric": "embedding_sharded_vs_replicated_step_ratio",
        "value": round(ratio, 4),
        "unit": "x_replicated",
        # parity + capacity proof + dedup win gate the score: a
        # fast-but-wrong (or secretly replicated) program scores 0
        "vs_baseline": round(ratio, 4) if gate else 0.0,
        "extra": {
            "mesh": {"data": data, "fsdp": fsdp},
            "table": {"rows": cfg.num_embeddings,
                      "dim": cfg.embedding_dim,
                      "bytes": cfg.num_embeddings
                      * cfg.embedding_dim * 4},
            "sharded_step_s": round(shard_dt, 4),
            "replicated_step_s": round(repl_dt, 4),
            "loss_sharded": round(shard_loss, 5),
            "loss_replicated": round(repl_loss, 5),
            "loss_parity": bool(parity),
            "unique_ratio": round(stats["unique_ratio"], 4),
            "unique_ratio_gauge": unique_ratio_gauge,
            "exchange_bytes": int(ex_bytes),
            "naive_gather_bytes": int(naive_bytes),
            "dedup_shrinks_exchange": bool(dedup_ok),
            "pod_proof": {
                "budget_bytes": int(budget),
                "replicated_peak": int(rep_repl["peak_bytes"]),
                "sharded_peak": int(rep_shard["peak_bytes"]),
                "replicated_fits": bool(
                    rep_repl["peak_bytes"] <= budget),
                "sharded_fits": bool(
                    rep_shard["peak_bytes"] <= budget)},
            "embedding_fallbacks": emb_fallbacks,
            "fallback_ops": dict(plan.fallback_ops),
            "ops_annotated": scope.get("annotated"),
        },
    }


def _bench_planner_vs_manual(small):
    """Auto-parallel planner rung (BENCH_MODEL=planner_vs_manual;
    paddle_tpu/distributed/planner/). The SAME GPT weights run one
    fwd+bwd step four ways on one (data, tp) mesh: (a) the hand-built
    fleet TP layers, (b) manual megatron-TP placement via
    spmd.shard_params (the spmd_auto rung's placement), (c) manual
    FSDP placement (every param dim 0 over the model axis), (d) the
    PLANNER-emitted placement (candidate search scored by the cost
    model, no human in the loop). value = best-manual step time /
    planner step time (>= 1 means the planner matched or beat the best
    hand-written placement); loss parity vs the fleet path gates the
    score, and the winning plan must report zero replicate-fallbacks
    (extra.planner_fallbacks)."""
    import paddle_tpu as paddle
    import paddle_tpu.distributed.fleet as fleet_pkg
    from jax.sharding import PartitionSpec as P
    from paddle_tpu.distributed import (mesh as mesh_mod, planner,
                                        spmd)
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    n_dev = jax.device_count()
    tp = 2 if n_dev >= 2 else 1
    data = max(n_dev // tp, 1)
    if small:
        cfg_kw = dict(vocab_size=512, hidden_size=128, num_layers=2,
                      num_heads=4, max_seq_len=128,
                      use_flash_attention=False)
        batch, seq, iters = 4, 128, 3
    else:
        cfg_kw = dict(hidden_size=1024, num_layers=24, num_heads=16,
                      max_seq_len=1024)
        batch, seq, iters = _env_int("BENCH_BATCH", 8), 1024, 5
    rng = np.random.RandomState(0)
    ids = rng.randint(0, GPTConfig(**cfg_kw).vocab_size,
                      (batch, seq)).astype(np.int64)

    def step_fn_for(model, mesh=None, in_spec=None):
        params = [p for p in model.parameters() if not p.stop_gradient]

        def f(pa, ids_a):
            originals = [p._data for p in params]
            for p, a in zip(params, pa):
                p._data = a
            try:
                if mesh is None:
                    t = paddle.Tensor(ids_a)
                    _, loss = model(t, labels=t)
                    return loss._data
                sc = spmd.trace_scope(mesh)
                with sc:
                    for p in params:
                        spec = spmd.param_spec_of(p)
                        if spec is not None:
                            sc.seed(p, spec)
                    t = paddle.Tensor(ids_a)
                    sc.seed(t, in_spec if in_spec is not None
                            else P("data"))
                    _, loss = model(t, labels=t)
                stats["scope"] = dict(sc.stats)
                return loss._data
            finally:
                for p, o in zip(params, originals):
                    p._data = o

        stats = {}
        grad_f = jax.jit(jax.value_and_grad(f))
        pa = [p._data for p in params]
        return grad_f, pa, stats

    def warm(grad_f, pa):
        loss, grads = grad_f(pa, ids)       # compile + warm
        jax.block_until_ready(grads)
        return float(loss)

    def timed_interleaved(progs, rounds=4):
        """progs: {name: (grad_f, pa)} — measure in interleaved chunks
        (a,b,c,d, a,b,c,d, ...), min of chunk means per program, so
        host drift hits every program equally instead of whichever ran
        last."""
        best = {name: float("inf") for name in progs}
        for _ in range(rounds):
            for name, (grad_f, pa) in progs.items():
                t0 = time.perf_counter()
                for _ in range(iters):
                    loss, grads = grad_f(pa, ids)
                jax.block_until_ready(grads)
                jax.block_until_ready(loss)
                dt = (time.perf_counter() - t0) / iters
                best[name] = min(best[name], dt)
        return best

    def fresh_model(state):
        paddle.seed(1234)
        m = GPTForCausalLM(GPTConfig(**cfg_kw))
        m.set_state_dict(state)
        return m

    prev_mesh = mesh_mod._global_mesh
    try:
        # (a) hand-built fleet TP path — the weights source of truth
        strategy = fleet_pkg.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": data, "mp_degree": tp}
        fleet_pkg.fleet.init(is_collective=True, strategy=strategy)
        paddle.seed(1234)
        tp_model = GPTForCausalLM(GPTConfig(mp_degree=tp, **cfg_kw))
        state = {k: np.asarray(v.numpy())
                 for k, v in tp_model.state_dict().items()}
        fleet_f, fleet_pa, _ = step_fn_for(tp_model)
        fleet_loss = warm(fleet_f, fleet_pa)

        mesh_mod._global_mesh = None
        mesh = mesh_mod.build_mesh({"data": data, "tp": tp})
        mesh_mod.set_mesh(mesh)

        # (b) manual megatron-TP placement (spmd_auto rung's rules)
        man_tp = fresh_model(state)
        spmd.shard_params(man_tp, mesh, [
            (r".*qkv_proj\.weight", P(None, "tp")),
            (r".*qkv_proj\.bias", P("tp")),
            (r".*fc1\.weight", P(None, "tp")),
            (r".*fc1\.bias", P("tp")),
            (r".*(out_proj|fc2)\.weight", P("tp", None)),
            (r".*wte\.weight", P("tp", None)),
        ])
        tp_f, tp_pa, _ = step_fn_for(man_tp, mesh=mesh)
        man_tp_loss = warm(tp_f, tp_pa)

        # (c) manual FSDP placement (every param dim 0 over the model
        # axis, batch over both axes)
        man_fs = fresh_model(state)
        spmd.shard_params(man_fs, mesh, [
            (r".*\.weight", P("tp")), (r".*\.bias", P("tp"))])
        fs_f, fs_pa, _ = step_fn_for(man_fs, mesh=mesh,
                                     in_spec=P(("data", "tp")))
        man_fs_loss = warm(fs_f, fs_pa)

        # (d) the planner's own placement — search + cost model
        plan_model = fresh_model(state)

        def plan_loss(x):
            _, loss = plan_model(x, labels=x)
            return loss

        res = planner.plan(plan_loss, mesh, example_inputs=(ids,),
                           model=plan_model)
        res.apply(plan_model)
        batch_entry = res.batch_entry
        pl_f, pl_pa, pl_stats = step_fn_for(
            plan_model, mesh=mesh,
            in_spec=P(batch_entry) if batch_entry is not None else P())
        planner_loss = warm(pl_f, pl_pa)

        dts = timed_interleaved({
            "fleet": (fleet_f, fleet_pa), "man_tp": (tp_f, tp_pa),
            "man_fs": (fs_f, fs_pa), "planner": (pl_f, pl_pa)})
        fleet_dt, man_tp_dt = dts["fleet"], dts["man_tp"]
        man_fs_dt, planner_dt = dts["man_fs"], dts["planner"]
    finally:
        mesh_mod._global_mesh = prev_mesh

    scope = pl_stats.get("scope", {})
    parity = abs(planner_loss - fleet_loss) <= 1e-3 * max(
        abs(fleet_loss), 1.0)
    zero_fallbacks = not scope.get("fallback")
    best_manual = min(fleet_dt, man_tp_dt, man_fs_dt)
    ratio = best_manual / max(planner_dt, 1e-9)
    return {
        "metric": "planner_vs_manual_step_ratio",
        "value": round(ratio, 4),
        "unit": "x_best_manual",
        # parity AND zero replicate-fallbacks are the gate: a
        # fast-but-wrong placement, or one the propagator could not
        # fully see, scores 0
        "vs_baseline": round(ratio, 4)
        if (parity and zero_fallbacks) else 0.0,
        "extra": {"mesh": {"data": data, "tp": tp},
                  "planner_winner": res.winner.candidate.name,
                  "planner_step_s": round(planner_dt, 4),
                  "fleet_tp_step_s": round(fleet_dt, 4),
                  "manual_tp_step_s": round(man_tp_dt, 4),
                  "manual_fsdp_step_s": round(man_fs_dt, 4),
                  "loss_planner": round(planner_loss, 5),
                  "loss_fleet_tp": round(fleet_loss, 5),
                  "loss_manual_tp": round(man_tp_loss, 5),
                  "loss_manual_fsdp": round(man_fs_loss, 5),
                  "loss_parity": bool(parity),
                  "planner_fallbacks": scope.get("fallback", {}),
                  "candidates_scored": len(res.ranked),
                  "candidates_rejected": len(res.rejected),
                  "modeled_winner_step_s": round(
                      res.winner.score.total_s, 6)},
    }


def _bench_fusion(small):
    """Graph-fusion rung (BENCH_MODEL=fusion; paddle_tpu/compile/fusion/).

    The SAME GPT transformer block — rms_norm → q/k projections →
    rotary embedding (attention prologue), layernorm → FFN → gelu →
    down-projection (MLP), residual add → rms_norm — measured fused vs
    unfused in the two regimes it actually runs in:

    * ``train``: the full fwd+bwd step through
      ``to_static(full_graph=True)`` + ``jax.value_and_grad`` — with
      ``FLAGS_enable_fusion`` on, the pass rewrites the traced program
      (rope_proj x2 + norm_linear + residual_norm) before the single
      XLA compile. Loss parity between the two programs gates the leg.
    * ``eager``: the block's forward dispatched op-by-op (the
      decode/serving regime the reference's fused_ops.yaml hot set
      targets) — the unfused chain is 10 dispatches / 10 program
      boundaries; the fused-op spelling is 4. Output parity gates it.

    value = geomean of the two fused-vs-unfused step-time ratios;
    vs_baseline is the same, zeroed if either parity gate fails (a
    fast-but-wrong rewrite scores 0, not a speedup). The acceptance
    bar in tools/perf_baseline.json is >= 1.10x.

    Timing: both programs are compiled/warmed up front, then measured
    in INTERLEAVED chunks (u,f,u,f,…) with min-of-chunk-means per leg —
    drift inside a ladder run (allocator state, co-tenant load, turbo)
    hits both programs equally instead of biasing whichever leg ran
    second, and the min is the contention-free estimate a ratio wants.
    """
    import paddle_tpu as paddle
    import paddle_tpu.ops as ops
    from paddle_tpu import nn
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.models import llama
    from paddle_tpu.nn import functional as F

    if small:
        B, S, H, FF, heads, iters = 4, 128, 256, 1024, 4, 10
    else:
        B, S, H, FF, heads, iters = 8, 512, 1024, 4096, 16, 20
    hd = H // heads
    paddle.seed(0)
    q_proj, k_proj = nn.Linear(H, H), nn.Linear(H, H)
    ln2 = nn.LayerNorm(H)
    fc1, fc2 = nn.Linear(H, FF), nn.Linear(FF, H)
    layers = (q_proj, k_proj, ln2, fc1, fc2)
    params = [p for m in layers for p in m.parameters()]
    rng = np.random.RandomState(0)
    # distinct inputs per timed iter (replay-caching backends fake the
    # timing on repeat-identical executions; see _run_train_bench)
    xs = [(rng.randn(B, S, H) * 0.5).astype(np.float32)
          for _ in range(3)]

    def block(xt):
        # attention prologue: the input norm feeds BOTH projections
        # (multi-consumer → stays), each projection+reshape+rope chain
        # fuses to ONE fused_rope_proj
        hn = F.rms_norm(xt)
        q = llama.rotary_embedding(
            ops.reshape(q_proj(hn), [B, S, heads, hd]))
        k = llama.rotary_embedding(
            ops.reshape(k_proj(hn), [B, S, heads, hd]))
        # MLP: layernorm → linear → gelu fuses to fused_norm_linear
        h = fc2(F.gelu(fc1(ln2(xt))))
        # residual add + rms_norm fuses to fused_residual_norm (the sum
        # is re-emitted, so the residual stream stays a real value)
        s = xt + h
        y = F.rms_norm(s)
        return y + ops.reshape(q, [B, S, H]) + ops.reshape(k, [B, S, H])

    def build_train(fused):
        paddle.set_flags({"FLAGS_enable_fusion": fused})
        sf = paddle.jit.to_static(block, full_graph=True)

        def f(pa, xa):
            originals = [p._data for p in params]
            for p, a in zip(params, pa):
                p._data = a
            try:
                out = sf(Tensor(xa))._data
                return (out * out).mean()
            finally:
                for p, o in zip(params, originals):
                    p._data = o

        g = jax.jit(jax.value_and_grad(f))
        pa = [p._data for p in params]
        loss, grads = g(pa, xs[0])          # compile + warm (flag is
        jax.block_until_ready(grads)        # read at THIS trace)
        return (g, pa, float(loss),
                (sf.fusion_stats or {}).get("rewritten", {}))

    def train_chunk(g, pa):
        t0 = time.perf_counter()
        for i in range(iters):
            loss, grads = g(pa, xs[i % len(xs)])
        jax.block_until_ready(grads)
        return (time.perf_counter() - t0) / iters

    def eager_unfused(xa):
        xt = Tensor(xa)
        hn = F.rms_norm(xt)
        q = llama.rotary_embedding(
            ops.reshape(F.linear(hn, q_proj.weight, q_proj.bias),
                        [B, S, heads, hd]))
        k = llama.rotary_embedding(
            ops.reshape(F.linear(hn, k_proj.weight, k_proj.bias),
                        [B, S, heads, hd]))
        h = fc2(F.gelu(fc1(ln2(xt))))
        s = xt + h
        y = F.rms_norm(s)
        return y + ops.reshape(q, [B, S, H]) + ops.reshape(k, [B, S, H])

    def eager_fused(xa):
        xt = Tensor(xa)
        hn = F.rms_norm(xt)
        q = F.fused_rope_proj(hn, q_proj.weight, q_proj.bias,
                              num_heads=heads)
        k = F.fused_rope_proj(hn, k_proj.weight, k_proj.bias,
                              num_heads=heads)
        h = fc2(F.fused_norm_linear(
            xt, fc1.weight, fc1.bias, ln2.weight, ln2.bias,
            activation="gelu", norm_type="layer_norm"))
        y, _s = F.fused_residual_norm(xt, h, norm_type="rms_norm",
                                      epsilon=1e-6)
        return y + ops.reshape(q, [B, S, H]) + ops.reshape(k, [B, S, H])

    def eager_chunk(fn):
        t0 = time.perf_counter()
        for i in range(iters):
            out = fn(xs[i % len(xs)])
        out.numpy()                          # value read drains the queue
        return (time.perf_counter() - t0) / iters

    prior_fusion = _fusion_on()          # BENCH_FUSION=1 ladder opt-in
    try:
        g_u, pa_u, loss_u, _ = build_train(False)
        g_f, pa_f, loss_f, patterns = build_train(True)
    finally:
        paddle.set_flags({"FLAGS_enable_fusion": prior_fusion})
    # both programs are compiled now (the fused trace already happened;
    # the flag no longer matters) — interleave the measurement
    chunks = 4
    t_u, t_f = [], []
    for _ in range(chunks):
        t_u.append(train_chunk(g_u, pa_u))
        t_f.append(train_chunk(g_f, pa_f))
    dt_u, dt_f = min(t_u), min(t_f)

    e_out_u = eager_unfused(xs[0]).numpy()   # warm + parity reference
    e_out_f = eager_fused(xs[0]).numpy()
    e_u, e_f = [], []
    for _ in range(chunks):
        e_u.append(eager_chunk(eager_unfused))
        e_f.append(eager_chunk(eager_fused))
    e_dt_u, e_dt_f = min(e_u), min(e_f)

    train_ratio = dt_u / max(dt_f, 1e-12)
    eager_ratio = e_dt_u / max(e_dt_f, 1e-12)
    loss_parity = abs(loss_u - loss_f) <= 1e-3 * max(abs(loss_u), 1.0)
    scale = max(float(np.abs(e_out_u).max()), 1e-6)
    eager_parity = float(np.abs(e_out_u - e_out_f).max()) <= 1e-3 * scale
    value = float(np.sqrt(train_ratio * eager_ratio))
    return {
        "metric": "fusion_fused_vs_unfused_step_ratio",
        "value": round(value, 4),
        "unit": "x_unfused",
        # parity is the gate: a fast-but-wrong rewrite scores 0
        "vs_baseline": round(value, 4)
        if (loss_parity and eager_parity and patterns) else 0.0,
        "extra": {
            "block": f"B{B} S{S} H{H} FF{FF} heads{heads}",
            "patterns": patterns,
            "train_unfused_step_s": round(dt_u, 5),
            "train_fused_step_s": round(dt_f, 5),
            "train_ratio": round(train_ratio, 4),
            "train_loss_unfused": round(loss_u, 6),
            "train_loss_fused": round(loss_f, 6),
            "loss_parity": bool(loss_parity),
            "eager_unfused_step_s": round(e_dt_u, 5),
            "eager_fused_step_s": round(e_dt_f, 5),
            "eager_ratio": round(eager_ratio, 4),
            "eager_parity": bool(eager_parity),
        },
    }


def _bench_fleet_observability(small):
    """Fleet-observability overhead rung (BENCH_MODEL=fleet_observability;
    paddle_tpu/observability/fleet.py + flight.py). The SAME step loop —
    a jitted matmul step plus one eager collective per step (so the
    flight recorder is actually on the path) — timed with the beacon +
    flight recorder fully OFF vs fully ON (beacon window 16, one probe
    step per window, straggler reduction each window). value = off/on
    step-time ratio (1.0 = free); the acceptance bar is overhead < 2%.
    A/B/A/B interleaved with min-of-passes so machine drift can't fake a
    regression either way."""
    import paddle_tpu as paddle
    from paddle_tpu.core import flags
    from paddle_tpu.distributed.communication import collective as C
    from paddle_tpu.observability import fleet, flight

    # step sized to the small end of REAL training steps (~ms-scale);
    # the beacon's absolute cost is µs-level, so judging it against a
    # sub-ms toy step would overstate the relative overhead 10x
    D, B = (768, 256) if small else (2048, 512)
    # the per-step cost sits near the host noise floor (~±30µs pair
    # jitter on a shared box), so the median needs many pairs to
    # resolve a <2% effect on a ~ms step; pairs cost ~2 steps each
    iters = 600 if small else 200
    rng = np.random.RandomState(0)
    w = jnp.asarray(rng.randn(D, D) * 0.01, jnp.float32)
    x0 = jnp.asarray(rng.randn(B, D), jnp.float32)
    step = jax.jit(lambda x: jnp.tanh(x @ w))
    tok = paddle.to_tensor(np.zeros(64, np.float32))

    OFF = {"flight_recorder": False, "fleet_beacon": False}
    ON = {"flight_recorder": True, "fleet_beacon": True}

    def one_step(instrumented, b):
        t0 = time.perf_counter()
        if instrumented:
            b.step_begin()
        y = step(x0)
        C.all_reduce(tok)
        jax.block_until_ready(y)
        if instrumented:
            b.step_end()
        return time.perf_counter() - t0

    # PAIRED per-step A/B, alternating order: each iteration times one
    # uninstrumented and one instrumented step back to back (off-first
    # on even iterations, on-first on odd), so host-load drift cancels
    # inside every pair and slot-position bias cancels across pairs; the
    # median pair-difference is the beacon's true cost even when
    # scheduler noise is 10x larger than it. (A plain before/after
    # split measures the machine, not the beacon.)
    prev = {k: flags.get_flag(k) for k in ("flight_recorder",
                                           "fleet_beacon")}
    t_off, diffs = [], []
    try:
        bcn = fleet.reset_beacon(window=16)
        for _ in range(5):                       # warm compiles/caches
            jax.block_until_ready(step(x0))
            C.all_reduce(tok)
        for i in range(iters):
            if i % 2 == 0:
                flags.set_flags(OFF)
                d_off = one_step(False, bcn)
                flags.set_flags(ON)
                d_on = one_step(True, bcn)
            else:
                flags.set_flags(ON)
                d_on = one_step(True, bcn)
                flags.set_flags(OFF)
                d_off = one_step(False, bcn)
            t_off.append(d_off)
            diffs.append(d_on - d_off)
        entries = len(flight.RECORDER.tail())
    finally:
        flags.set_flags(prev)
        fleet.reset_beacon()
    off = float(np.median(t_off))
    # median over ALL paired diffs: the pairing already cancels drift
    # and the diffs are signed two-sided noise, so min-of-chunk-medians
    # would systematically pick the most-negative chunk and under-report
    # the instrumentation cost the gate exists to catch
    on = off + float(np.median(diffs))
    n_steps = iters                  # steps PER CONFIG (one each/pair)
    ratio = off / max(on, 1e-12)
    overhead_pct = (on / max(off, 1e-12) - 1.0) * 100.0
    return {
        "metric": "fleet_observability_overhead_ratio",
        "value": round(ratio, 4),
        "unit": "x_uninstrumented",
        "vs_baseline": round(ratio, 4),
        "extra": {"overhead_pct": round(overhead_pct, 3),
                  "step_off_us": round(off * 1e6, 1),
                  "step_on_us": round(on * 1e6, 1),
                  "beacon_window": 16,
                  "steps_per_config": n_steps,
                  "windows_flushed": bcn.windows,
                  "flight_ring_entries": entries,
                  "within_budget": bool(overhead_pct < 2.0)},
    }


def _bench_goodput_overhead(small):
    """Goodput-ledger + sentinel overhead rung (BENCH_MODEL=
    goodput_overhead; paddle_tpu/observability/goodput.py +
    sentinel.py). The SAME jitted step timed bare vs with the full
    per-step job-health plane on the path — ledger step brackets
    (clock reads + billed-overlap accounting) and the sentinel's
    median/MAD + EWMA update per step. value = off/on step-time ratio
    (1.0 = free); the acceptance bar is overhead < 2% of the
    un-instrumented loop, same discipline as the fleet_observability
    and serving_reqtrace rungs (paired per-step A/B, alternating
    order, median over ALL signed pair diffs)."""
    import io

    from paddle_tpu.core import flags
    from paddle_tpu.observability import goodput, sentinel

    # step sized to the small end of REAL training steps (~ms-scale),
    # like the fleet rung: the ledger's absolute cost is µs-level
    D, B = (768, 256) if small else (2048, 512)
    iters = 600 if small else 200
    rng = np.random.RandomState(0)
    w = jnp.asarray(rng.randn(D, D) * 0.01, jnp.float32)
    x0 = jnp.asarray(rng.randn(B, D), jnp.float32)
    step = jax.jit(lambda x: jnp.tanh(x @ w))

    OFF = {"goodput": False, "sentinel": False}
    ON = {"goodput": True, "sentinel": True}

    def one_step(instrumented, led, snt):
        t0 = time.perf_counter()
        if instrumented:
            led.step_begin()
        y = step(x0)
        jax.block_until_ready(y)
        if instrumented:
            snt.observe_step(led.step_end(), loss=0.0)
        return time.perf_counter() - t0

    prev = {k: flags.get_flag(k) for k in ("goodput", "sentinel")}
    t_off, diffs = [], []
    try:
        flags.set_flags(ON)
        led = goodput.reset_ledger().run_begin()
        # incidents print nowhere: overhead is what this rung measures,
        # and a GC-pause spike must not spam the bench log
        snt = sentinel.reset(stream=io.StringIO())
        for _ in range(5):                       # warm compiles/caches
            jax.block_until_ready(step(x0))
        for i in range(iters):
            if i % 2 == 0:
                flags.set_flags(OFF)
                d_off = one_step(False, led, snt)
                flags.set_flags(ON)
                d_on = one_step(True, led, snt)
            else:
                flags.set_flags(ON)
                d_on = one_step(True, led, snt)
                flags.set_flags(OFF)
                d_off = one_step(False, led, snt)
            t_off.append(d_off)
            diffs.append(d_on - d_off)
        incidents = len(snt.incidents())
        ledger_steps = led.snapshot()["steps"]
    finally:
        flags.set_flags(prev)
        goodput.reset_ledger()
        sentinel.reset()
    off = float(np.median(t_off))
    # median over ALL paired diffs (see the fleet rung's rationale)
    on = off + float(np.median(diffs))
    ratio = off / max(on, 1e-12)
    overhead_pct = (on / max(off, 1e-12) - 1.0) * 100.0
    return {
        "metric": "goodput_overhead_ratio",
        "value": round(ratio, 4),
        "unit": "x_uninstrumented",
        "vs_baseline": round(ratio, 4),
        "extra": {"overhead_pct": round(overhead_pct, 3),
                  "step_off_us": round(off * 1e6, 1),
                  "step_on_us": round(on * 1e6, 1),
                  "steps_per_config": iters,
                  "ledger_steps": ledger_steps,
                  "sentinel_incidents": incidents,
                  "within_budget": bool(overhead_pct < 2.0)},
    }


_MTTR_CHILD = r'''
import os, sys, time
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from paddle_tpu.fault import CheckpointManager, capture_train_state
from paddle_tpu.fault.checkpoint_manager import auto_resume

out = sys.argv[1]
epoch = int(os.environ.get("PADDLE_ELASTIC_EPOCH", "0") or 0)

class Net:
    def __init__(self):
        self.w = np.zeros(8, np.float32)
    def state_dict(self):
        return {"w": self.w.copy()}
    def set_state_dict(self, sd):
        self.w = np.asarray(sd["w"], np.float32).copy()

net = Net()
mgr = CheckpointManager(os.path.join(out, "ckpt"), keep_n=3)
start = 0
if epoch > 0:
    meta = auto_resume(mgr, network=net)
    start = int(meta["step"]) if meta else 0
    print("MTTR_RESUMED step=%d t=%.6f" % (start, time.time()),
          flush=True)
for s in range(start + 1, 9):
    if epoch == 0 and s == 5:
        print("MTTR_CRASH t=%.6f" % time.time(), flush=True)
        os.kill(os.getpid(), 9)
    net.w += 0.1
    mgr.save(capture_train_state(network=net), step=s)
print("MTTR_DONE", flush=True)
'''


def _bench_fault_recovery(small):
    """Self-healing-fleet rung (BENCH_MODEL=fault_recovery;
    paddle_tpu/fault/supervisor.py). Two measurements:

    (1) disarmed-vs-armed A/B — the SAME jitted step timed with the
    fault plane off (FLAGS_collective_timeout_s=0, no monitor thread,
    no supervisor tick on the path) vs fully armed (monitor thread
    live + the per-step supervisor heartbeat tick the hapi loop
    issues). The supervisor's background publish thread runs during
    BOTH configs (it is per-interval, not per-step, so its cost
    cancels in the pair diffs). value = off/on step-time ratio (1.0 =
    free); acceptance bar: overhead < 2%, same paired-median
    discipline as the goodput rung.

    (2) MTTR — a real subprocess trainer under the elastic launcher is
    SIGKILLed mid-step at epoch 0 and relaunched with
    ``--max_restarts 1``; the wall from the crash stamp to the
    relaunched process's restored-step stamp is the measured
    mean-time-to-recovery. Reported in extra, NOT gated: it is
    dominated by interpreter + jax import time, a machine property."""
    import socket
    import subprocess
    import tempfile

    from paddle_tpu.core import flags
    from paddle_tpu.fault import supervisor as sup

    D, B = (768, 256) if small else (2048, 512)
    iters = 600 if small else 200
    rng = np.random.RandomState(0)
    w = jnp.asarray(rng.randn(D, D) * 0.01, jnp.float32)
    x0 = jnp.asarray(rng.randn(B, D), jnp.float32)
    step = jax.jit(lambda x: jnp.tanh(x @ w))

    tmp = tempfile.mkdtemp(prefix="fault_bench_")
    lease = sup.FileLease(os.path.join(tmp, "leases"), rank=0, world=1,
                          ttl=600.0)
    svr = sup.Supervisor(lease, interval=5.0).start()

    def one_step(armed, i):
        t0 = time.perf_counter()
        if armed:
            sup.tick(i)
        y = step(x0)
        jax.block_until_ready(y)
        return time.perf_counter() - t0

    prev = flags.get_flag("collective_timeout_s")
    t_off, diffs = [], []
    try:
        for _ in range(5):                       # warm compiles/caches
            jax.block_until_ready(step(x0))
        for i in range(iters):
            if i % 2 == 0:
                flags.set_flags({"collective_timeout_s": 0.0})
                d_off = one_step(False, i)
                flags.set_flags({"collective_timeout_s": 2.0})
                d_on = one_step(True, i)
            else:
                flags.set_flags({"collective_timeout_s": 2.0})
                d_on = one_step(True, i)
                flags.set_flags({"collective_timeout_s": 0.0})
                d_off = one_step(False, i)
            t_off.append(d_off)
            diffs.append(d_on - d_off)
    finally:
        flags.set_flags({"collective_timeout_s": prev})
        svr.stop()
    off = float(np.median(t_off))
    on = off + float(np.median(diffs))
    ratio = off / max(on, 1e-12)
    overhead_pct = (on / max(off, 1e-12) - 1.0) * 100.0

    # -------- MTTR: kill -> elastic restart -> consensus-free resume
    child = os.path.join(tmp, "mttr_child.py")
    with open(child, "w") as f:
        f.write(_MTTR_CHILD)
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = (os.path.dirname(os.path.abspath(__file__))
                         + os.pathsep + env.get("PYTHONPATH", ""))
    mttr_s, mttr_rc = None, None
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nproc_per_node", "1", "--master", f"127.0.0.1:{port}",
             "--max_restarts", "1", "--abort_grace", "2",
             child, tmp],
            env=env, capture_output=True, text=True, timeout=300)
        mttr_rc = proc.returncode
        stamps = {}
        for line in proc.stdout.splitlines():
            if line.startswith("MTTR_CRASH"):
                stamps["crash"] = float(line.rsplit("t=", 1)[1])
            elif line.startswith("MTTR_RESUMED"):
                stamps["resumed"] = float(line.rsplit("t=", 1)[1])
        if mttr_rc == 0 and "crash" in stamps and "resumed" in stamps:
            mttr_s = stamps["resumed"] - stamps["crash"]
    except (subprocess.TimeoutExpired, OSError):
        pass

    return {
        "metric": "fault_recovery_overhead_ratio",
        "value": round(ratio, 4),
        "unit": "x_disarmed",
        "vs_baseline": round(ratio, 4),
        "extra": {"overhead_pct": round(overhead_pct, 3),
                  "step_off_us": round(off * 1e6, 1),
                  "step_on_us": round(on * 1e6, 1),
                  "steps_per_config": iters,
                  "within_budget": bool(overhead_pct < 2.0),
                  "mttr_s": (round(mttr_s, 3)
                             if mttr_s is not None else None),
                  "mttr_recovered": bool(mttr_rc == 0
                                         and mttr_s is not None)},
    }


def _bench_dispatch(small):
    """Per-op eager dispatch latency (VERDICT: SURVEY §7 hard part #1).

    Measures µs/op for a 128×128 matmul in a Python loop: eager with grad
    tape recording, eager under no_grad, and the same loop jitted. The
    eager path must not linearize (lazy-vjp dispatch), so tape-on overhead
    is bookkeeping only. Reference bar: generated C++ ad_func pipeline is
    µs-level (eager_gen.py:301)."""
    import paddle_tpu as paddle

    n = 50 if small else 300
    x = paddle.to_tensor(np.random.randn(128, 128).astype(np.float32))
    w = paddle.to_tensor(np.random.randn(128, 128).astype(np.float32))
    w.stop_gradient = False

    def loop_eager():
        y = x
        for _ in range(n):
            y = paddle.ops.matmul(y, w)
        return y

    def timed(f):
        out = f()
        jax.block_until_ready(out._data if hasattr(out, "_data") else out)
        t0 = time.perf_counter()
        out = f()
        jax.block_until_ready(out._data if hasattr(out, "_data") else out)
        return (time.perf_counter() - t0) / n * 1e6  # µs/op

    with_tape = timed(loop_eager)
    with paddle.no_grad():
        no_tape = timed(loop_eager)

    def jit_loop(xa, wa):
        def body(y, _):
            return y @ wa, None
        y, _ = jax.lax.scan(body, xa, None, length=n)
        return y

    jitted = jax.jit(jit_loop)
    # warm up on a DIFFERENT input: the axon tunnel replays identical
    # executions from cache, which would fake the timed run
    x2 = jnp.asarray(np.random.randn(128, 128).astype(np.float32))
    jax.block_until_ready(jitted(x2, w._data))
    t0 = time.perf_counter()
    jax.block_until_ready(jitted(x._data, w._data))
    jit_us = (time.perf_counter() - t0) / n * 1e6

    return {
        "metric": "eager_dispatch_overhead_us_per_op",
        "value": round(with_tape, 2),
        "unit": "us/op",
        "vs_baseline": round(jit_us / max(with_tape, 1e-9), 4),
        "extra": {"eager_tape_us": round(with_tape, 2),
                  "eager_no_grad_us": round(no_tape, 2),
                  "jit_us": round(jit_us, 2),
                  "matmul": "128x128", "iters": n},
    }


def _async_gpt_parts(small):
    """Shared GPT harness of the async-runtime rungs: model + a
    functional AdamW step buildable donated or undonated (SAME math —
    donation is pure buffer aliasing, so loss parity is exact)."""
    import paddle_tpu as paddle
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    if small:
        cfg = GPTConfig(vocab_size=512, hidden_size=128, num_layers=2,
                        num_heads=4, max_seq_len=128,
                        use_flash_attention=False)
        batch, seq, iters = 4, 128, 6
    else:
        cfg = GPTConfig(hidden_size=1024, num_layers=24, num_heads=16,
                        max_seq_len=1024)
        batch, seq, iters = _env_int("BENCH_BATCH", 8), 1024, 8
    paddle.seed(7)
    model = GPTForCausalLM(cfg)
    params = [p for p in model.parameters() if not p.stop_gradient]
    b1, b2, eps, lr = 0.9, 0.95, 1e-8, 2.5e-4

    def loss_fn(pa, ids):
        originals = [p._data for p in params]
        for p, a in zip(params, pa):
            p._data = a
        try:
            t = paddle.Tensor(ids)
            _, loss = model(t, labels=t)
            return loss._data.astype(jnp.float32)
        finally:
            for p, o in zip(params, originals):
                p._data = o

    def make_step(donate):
        def step(state, ids):
            pa, m_st, v_st, t = state
            loss, grads = jax.value_and_grad(loss_fn)(pa, ids)
            t = t + 1
            tf = t.astype(jnp.float32)
            new_pa, new_m, new_v = [], [], []
            for w, m, v, g in zip(pa, m_st, v_st, grads):
                g = g.astype(jnp.float32)
                m = b1 * m + (1 - b1) * g
                v = b2 * v + (1 - b2) * g * g
                m_hat = m / (1 - b1 ** tf)
                v_hat = v / (1 - b2 ** tf)
                w = w - lr * m_hat / (jnp.sqrt(v_hat) + eps)
                new_pa.append(w)
                new_m.append(m)
                new_v.append(v)
            return loss, (new_pa, new_m, new_v, t)

        return jax.jit(step, donate_argnums=(0,) if donate else ())

    def init_state():
        pa = [jnp.array(p._data, copy=True) for p in params]
        return (pa, [jnp.zeros_like(a) for a in pa],
                [jnp.zeros_like(a) for a in pa],
                jnp.asarray(0, jnp.int32))

    return cfg, model, params, make_step, init_state, batch, seq, iters


def _bench_async_overlap(small):
    """Async-runtime rung (BENCH_MODEL=async_overlap; io/prefetch.py +
    donated steps + sharding/decomposed.py).

    The SAME GPT AdamW step runs two ways on the same batches:

    * ``off`` — the synchronous baseline: batch transferred inline on
      the consumer, undonated step, per-step host sync on the loss (the
      pre-round-17 ``Engine.fit`` shape).
    * ``on`` — the async runtime: DevicePrefetcher transfers batch k+1
      while step k computes, the step donates its param/optimizer-state
      buffers, and the loss is read once at the end.

    Loss parity between the legs gates the score (donation and
    prefetch change scheduling, never math). extra records the
    round-12 attribution of both legs — the acceptance bar is
    idle+host share strictly lower with overlap on — plus the
    perf.memory high-water census of each leg (donated buffers count 0
    the moment the step consumes them) and, when >= 2 devices are
    visible, the decomposed vs serial stage-2 parameter re-gather."""
    import paddle_tpu as paddle
    from paddle_tpu.io.prefetch import DevicePrefetcher
    from paddle_tpu.observability import perf as _perf, trace as _tr
    from paddle_tpu.observability.perf import memory as _mem
    from paddle_tpu.observability.perf.device import DEVICE_CAT

    cfg, model, params, make_step, init_state, batch, seq, iters = \
        _async_gpt_parts(small)
    rng = np.random.RandomState(0)
    # the loader hands out device Tensors (DataLoader._to_output);
    # the pre-round-17 Engine.fit pulled them back to host and re-put
    # them per step — that round trip is part of the off leg
    loader_batches = [
        paddle.Tensor(jnp.asarray(rng.randint(
            0, cfg.vocab_size, (batch, seq)).astype(np.int64)))
        for _ in range(iters)]
    step_off = make_step(False)
    step_on = make_step(True)

    def place(t):
        """The Engine's batch placement: Tensor → host → device."""
        return jnp.asarray(t.numpy())

    def run_off(state, census=False):
        lf = None
        for i, t in enumerate(loader_batches):
            with _tr.span("io.transfer", "io"):
                x = place(t)               # inline, on the critical path
            loss, new_state = step_off(state, x)
            if census and i == 1:
                # old state still referenced here — the undonated
                # execution window really holds both copies
                _mem.update_high_water("async_overlap_off")
            state = new_state
            lf = float(loss)               # per-step host sync
        return lf, state

    def run_on(state, census=False):
        pf = DevicePrefetcher(iter(loader_batches), depth=2,
                              place_fn=place)
        loss = None
        try:
            for i, x in enumerate(pf):
                prev = state
                loss, state = step_on(prev, x)
                if census and i == 1:
                    # prev was just donated: its buffers census as 0 —
                    # the high-water drop donation buys
                    _mem.update_high_water("async_overlap_on")
        finally:
            pf.close()
        return float(loss), state

    # warmup (compiles both programs) + parity + census
    state_off = init_state()
    state_on = init_state()
    loss_off, state_off = run_off(state_off, census=True)
    loss_on, state_on = run_on(state_on, census=True)
    parity = abs(loss_on - loss_off) <= 1e-3 * max(abs(loss_off), 1.0)

    # interleaved timed chunks, min per leg, alternating order per
    # round so host drift hits both legs equally
    best_off = best_on = float("inf")
    for r in range(5):
        legs = ("off", "on") if r % 2 == 0 else ("on", "off")
        for leg in legs:
            t0 = time.perf_counter()
            if leg == "off":
                _, state_off = run_off(state_off)
                best_off = min(best_off,
                               (time.perf_counter() - t0) / iters)
            else:
                _, state_on = run_on(state_on)
                best_on = min(best_on,
                              (time.perf_counter() - t0) / iters)

    # round-12 attribution of one step per leg. The jit call is
    # bracketed as a device span: on an async-dispatch backend it is a
    # ~ms enqueue (the block in timed_section covers the real execution
    # window); on a backend that serializes donated dispatch (CPU) the
    # call IS the execution — either way the device share lands where
    # the device actually worked, and the off leg's inline transfer +
    # per-step sync stay host/idle.
    attr_off = attr_on = None
    pf_attr = None
    try:
        import itertools

        st = {"s": state_off, "i": 0}

        def off_step():
            t = loader_batches[st["i"] % iters]
            st["i"] += 1
            with _tr.span("io.transfer", "io"):
                x = place(t)
            with _tr.span("bench.step", DEVICE_CAT):
                loss, st["s"] = step_off(st["s"], x)
            float(loss)                     # the sync the off leg pays
            return loss

        att = _perf.step_attribution(off_step, iters=2, warmup=1,
                                     name="async_off")["total"]
        attr_off = {k: round(att[k], 4) for k in
                    ("compute_frac", "collective_frac", "host_frac",
                     "idle_frac")}

        pf_attr = DevicePrefetcher(
            iter(itertools.cycle(loader_batches)), depth=2,
            place_fn=place)
        st2 = {"s": state_on}

        def on_step():
            x = next(pf_attr)
            with _tr.span("bench.step", DEVICE_CAT):
                loss, st2["s"] = step_on(st2["s"], x)
            return loss

        att = _perf.step_attribution(on_step, iters=2, warmup=1,
                                     name="async_on")["total"]
        attr_on = {k: round(att[k], 4) for k in
                   ("compute_frac", "collective_frac", "host_frac",
                    "idle_frac")}
    except Exception:
        pass
    finally:
        if pf_attr is not None:
            pf_attr.close()

    # decomposed vs serial stage-2 parameter re-gather (the old serial
    # front) — needs a multi-device sharding mesh
    gather = None
    if jax.device_count() >= 2:
        try:
            from jax.sharding import NamedSharding, PartitionSpec as P
            from paddle_tpu.distributed import mesh as mesh_mod
            from paddle_tpu.distributed.fleet.meta_optimizers. \
                dygraph_sharding_optimizer import shard_spec_for
            from paddle_tpu.distributed.sharding import (gather_grouped,
                                                         plan_groups)
            prev_mesh = mesh_mod._global_mesh
            try:
                mesh_mod._global_mesh = None
                deg = jax.device_count()
                mesh = mesh_mod.build_mesh({"sharding": deg})
                mesh_mod.set_mesh(mesh)
                shardable = [
                    (p, NamedSharding(
                        mesh, shard_spec_for(p.shape, deg, "sharding")))
                    for p in params
                    if shard_spec_for(p.shape, deg, "sharding")]
                rep = NamedSharding(mesh, P())

                def to_sharded():
                    for p, sh in shardable:
                        p._data = jax.device_put(p._data, sh)
                    jax.block_until_ready([p._data for p, _ in shardable])

                def timed_gather(fn):
                    best = float("inf")
                    for _ in range(3):
                        to_sharded()
                        t0 = time.perf_counter()
                        fn()
                        jax.block_until_ready(
                            [p._data for p, _ in shardable])
                        best = min(best, time.perf_counter() - t0)
                    return best

                def serial():
                    for p, _sh in shardable:
                        p._data = jax.device_put(p._data, rep)

                def decomposed():
                    gather_grouped([(p, rep) for p, _ in shardable],
                                   site="bench")

                gather = {
                    "serial_s": round(timed_gather(serial), 5),
                    "decomposed_s": round(timed_gather(decomposed), 5),
                    "groups": len(plan_groups(
                        [p for p, _ in shardable])),
                    "params": len(shardable)}
            finally:
                mesh_mod._global_mesh = prev_mesh
        except Exception:
            gather = None

    hbm_off = _mem.high_water("async_overlap_off")
    hbm_on = _mem.high_water("async_overlap_on")
    ratio = best_off / max(best_on, 1e-9)
    overlap_win = None
    if attr_off and attr_on:
        overlap_win = bool(
            attr_on["host_frac"] + attr_on["idle_frac"]
            < attr_off["host_frac"] + attr_off["idle_frac"])
    return {
        "metric": "async_overlap_step_ratio",
        "value": round(ratio, 4),
        "unit": "x_sync",
        # parity is the gate: a fast-but-wrong async pipeline scores 0
        "vs_baseline": round(ratio, 4) if parity else 0.0,
        "extra": {"sync_step_s": round(best_off, 4),
                  "async_step_s": round(best_on, 4),
                  "loss_sync": round(loss_off, 5),
                  "loss_async": round(loss_on, 5),
                  "loss_parity": bool(parity),
                  "attribution_off": attr_off,
                  "attribution_on": attr_on,
                  "idle_host_shrinks": overlap_win,
                  "hbm_high_water_off": hbm_off.get("total"),
                  "hbm_high_water_on": hbm_on.get("total"),
                  "gather_decomposition": gather,
                  "batch": batch, "seq": seq},
    }


def _bench_async_batch_sweep(small):
    """steps/sec-vs-batch sweep (BENCH_MODEL=async_batch_sweep): the
    SAME GPT step donated vs undonated across a batch ladder. Donation
    halves the params+optimizer-state working set of the step (inputs
    alias outputs), which is headroom for bigger batches — the sweep
    records tokens/s AND the alias-aware compiled peak bytes
    (memory_analysis) at every batch so the headroom is visible even on
    hosts where nothing OOMs. value = donated/undonated tokens/s at the
    largest batch, parity-gated."""
    cfg, model, params, make_step, init_state, _batch, seq, _iters = \
        _async_gpt_parts(small)
    batches = (2, 4, 8) if small else (4, 8, _env_int("BENCH_BATCH", 16))
    iters = 3 if small else 5
    step_off = make_step(False)
    step_on = make_step(True)
    rng = np.random.RandomState(0)

    def peak_bytes(compiled):
        from paddle_tpu.observability.perf.device import memory_breakdown
        mb = memory_breakdown(compiled)
        return mb["peak_bytes"] if mb else None

    def leg(step, state, ids):
        loss, state = step(state, ids)      # compile + warm
        first = float(loss)
        t0 = time.perf_counter()
        for _ in range(iters):
            loss, state = step(state, ids)
        float(loss)
        dt = (time.perf_counter() - t0) / iters
        return first, dt, state

    curve = []
    ratio_at_max = 0.0
    parity_all = True
    for b in batches:
        ids = jnp.asarray(rng.randint(0, cfg.vocab_size,
                                      (b, seq)).astype(np.int64))
        first_off, dt_off, _ = leg(step_off, init_state(), ids)
        first_on, dt_on, _ = leg(step_on, init_state(), ids)
        parity = abs(first_on - first_off) <= 1e-3 * max(
            abs(first_off), 1.0)
        parity_all = parity_all and parity
        pk_off = peak_bytes(step_off.lower(init_state(), ids).compile())
        pk_on = peak_bytes(step_on.lower(init_state(), ids).compile())
        tok_off = b * seq / dt_off
        tok_on = b * seq / dt_on
        ratio_at_max = tok_on / max(tok_off, 1e-9)
        curve.append({"batch": b,
                      "tokens_per_s_undonated": round(tok_off, 1),
                      "tokens_per_s_donated": round(tok_on, 1),
                      "peak_bytes_undonated": pk_off,
                      "peak_bytes_donated": pk_on,
                      "loss_parity": bool(parity)})
    comparable = [c for c in curve
                  if c["peak_bytes_donated"] and c["peak_bytes_undonated"]]
    # None (not a vacuous True) when the backend measured nothing — the
    # acceptance signal must never read as satisfied without evidence
    donated_smaller = (
        all(c["peak_bytes_donated"] < c["peak_bytes_undonated"]
            for c in comparable)
        if comparable else None)
    return {
        "metric": "async_batch_sweep_tokens_ratio",
        "value": round(ratio_at_max, 4),
        "unit": "x_undonated",
        "vs_baseline": round(ratio_at_max, 4) if parity_all else 0.0,
        "extra": {"sweep": curve, "seq": seq,
                  "donated_peak_below_undonated": donated_smaller,
                  "loss_parity": bool(parity_all)},
    }


def _bench_pipeline(small):
    """Wall-clock pipeline-schedule comparison (VERDICT r3 #4): step time
    of FThenB vs 1F1B vs VPP(K=2,4) vs ZBH1 at fixed (m, total blocks)
    on a pp=4 mesh. Single-chip hosts re-exec onto a 4-device virtual CPU
    mesh (the schedules are SPMD programs; the RELATIVE tick economics —
    VPP's smaller bubble, ZBH1's dW filler — are schedule properties, and
    the measurement reports its host so the caller can weigh it)."""
    import subprocess
    import sys

    if os.environ.get("BENCH_PIPE_CHILD") == "1":
        # the child runs on a virtual CPU mesh, which would flip main()'s
        # small-detection — honor the parent's choice instead
        small = os.environ.get("BENCH_PIPE_SMALL") == "1"
    if jax.device_count() < 4 and os.environ.get("BENCH_PIPE_CHILD") != "1":
        env = dict(os.environ)
        env.update(BENCH_PIPE_CHILD="1", BENCH_MODEL="pipeline",
                   BENCH_PIPE_SMALL="1" if small else "0",
                   JAX_PLATFORMS="cpu")
        flags = [f for f in env.get("XLA_FLAGS", "").split()
                 if not f.startswith("--xla_force_host_platform")]
        env["XLA_FLAGS"] = " ".join(
            flags + ["--xla_force_host_platform_device_count=4"])
        proc = subprocess.run([sys.executable, os.path.abspath(__file__)],
                              env=env, capture_output=True, text=True,
                              timeout=1800)
        for line in proc.stdout.splitlines():
            if line.startswith("{"):
                return json.loads(line)
        raise RuntimeError(f"pipeline child failed: {proc.stderr[-500:]}")

    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.distributed import mesh as mesh_mod
    from paddle_tpu.distributed.fleet import (DistributedStrategy,
                                              LayerDesc, PipelineLayer,
                                              PipelineParallel)

    mesh_mod.set_mesh(mesh_mod.build_mesh({"pp": 4},
                                          devices=jax.devices()[:4]))
    d = _env_int("BENCH_PIPE_HIDDEN", 192)
    mb_rows = _env_int("BENCH_PIPE_BATCH", 4 if small else 32)
    m = 8                      # micro-batches

    class _Blk(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(d, d)

        def forward(self, x):
            return paddle.ops.tanh(self.fc(x))

    x = paddle.to_tensor(
        np.random.randn(m * mb_rows, d).astype(np.float32))
    y = paddle.to_tensor(
        np.random.randn(m * mb_rows, d).astype(np.float32))

    def run_one(sched, L):
        paddle.seed(99)
        strategy = DistributedStrategy()
        strategy.pipeline_configs = {"accumulate_steps": m,
                                     "schedule_mode": sched}
        pl = PipelineLayer(
            layers=[LayerDesc(_Blk) for _ in range(L)],
            loss_fn=lambda o, t: paddle.ops.mean((o - t) ** 2))
        runtime = PipelineParallel(pl, None, strategy)
        runtime.forward_backward_pipeline((x, y))   # compile
        iters = 2 if small else 6
        t0 = time.perf_counter()
        for _ in range(iters):
            loss = runtime.forward_backward_pipeline((x, y))
        jax.block_until_ready(loss._data)
        return (time.perf_counter() - t0) / iters * 1e3  # ms

    # K = blocks/stage: VPP interleaves K chunks per rank, so K is set by
    # the model depth at fixed S=4. Compare each schedule at the SAME L.
    times = {}
    for L, ktag in ((8, "K2"), (16, "K4")):
        for sched in ("FThenB", "1F1B", "VPP", "ZBH1"):
            times[f"{sched}-L{L}"] = run_one(sched, L)
    speedups = {ktag: times[f"1F1B-L{L}"] / times[f"VPP-L{L}"]
                for L, ktag in ((8, "K2"), (16, "K4"))}
    best = max(speedups.values())
    return {
        "metric": "pipeline_vpp_speedup_vs_1f1b",
        "value": round(best, 4),
        "unit": "x",
        "vs_baseline": round(best, 4),
        "extra": {"step_ms": {k: round(v, 2) for k, v in times.items()},
                  "vpp_speedup": {k: round(v, 4)
                                  for k, v in speedups.items()},
                  "m": m, "stages": 4, "hidden": d, "micro_rows": mb_rows,
                  "host": jax.default_backend()},
    }


def _bench_pipeline_bubble(small):
    """Pipeline-bubble rung (BENCH_MODEL=pipeline_bubble;
    distributed/pipeline/). Partitions a stacked-MLP program into S=4
    cost-balanced stages, runs 1F1B train steps with per-step timing,
    and replays the measured durations through the schedule event
    simulation (``schedules.simulate``) — the measured bubble fraction
    must land within tolerance of the closed form ``(S-1)/(m+S-1)``.
    With balanced stages the closed form is independent of the F:B
    cost ratio, so the bar holds on any host; the value is the boolean
    gate (1.0 = in tolerance AND gradient parity vs the unpipelined
    reference), raw fractions in extra."""
    import paddle_tpu as paddle
    from paddle_tpu import nn, static
    from paddle_tpu.distributed.pipeline import (PipelinedProgram,
                                                 partition_program)

    S, m = 4, 8
    d = _env_int("BENCH_PIPE_HIDDEN", 192 if small else 512)
    rows = 4                     # per-microbatch batch rows
    paddle.seed(23)
    blocks = []
    for _ in range(2 * S):
        blocks += [nn.Linear(d, d), nn.GELU()]
    model = nn.Sequential(*blocks)
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [rows, d], "float32")
        y = static.data("y", [rows, d], "float32")
        loss = ((model(x) - y) ** 2).mean()
    part = partition_program(prog, S, fetch_ids=[id(loss)])
    pp = PipelinedProgram(part, schedule="1f1b", loss_id=id(loss),
                          check=False)
    rng = np.random.RandomState(3)
    feed = {"x": rng.randn(m * rows, d).astype(np.float32),
            "y": rng.randn(m * rows, d).astype(np.float32)}
    pp.train_step(feed, m)       # compile
    best = None
    for _ in range(2 if small else 5):
        _l, grads, stats = pp.train_step(feed, m, collect_timing=True)
        err = abs(stats["measured_bubble"]
                  - stats["analytical_bubble"])
        if best is None or err < best[0]:
            best = (err, stats, grads)
    err, stats, grads = best
    _lr, grads_ref = pp.run_unpipelined(feed, m)
    parity = all(np.allclose(np.asarray(grads[k]),
                             np.asarray(grads_ref[k]))
                 for k in grads_ref)
    # CPU smoke carries per-step host-dispatch overhead the closed form
    # does not model; 0.15 absolute holds with ~2x margin there while
    # still catching a broken schedule (fthenb at S=4/m=8 would read
    # ~0.45 off a 0.27 bar)
    tol = float(os.environ.get("BENCH_PIPE_TOL", "0.15"))
    ok = bool(parity and err <= tol)
    return {
        "metric": "pipeline_bubble_measured_vs_analytical",
        "value": 1.0 if ok else 0.0,
        "unit": "bool",
        "vs_baseline": 1.0 if ok else 0.0,
        "extra": {
            "measured_bubble": round(stats["measured_bubble"], 4),
            "analytical_bubble": round(stats["analytical_bubble"], 4),
            "abs_err": round(err, 4), "tolerance": tol,
            "grad_parity": bool(parity), "stages": S,
            "microbatches": m, "hidden": d, "schedule": "1f1b",
            "host": jax.default_backend()},
    }


def main():
    if os.environ.get("BENCH_SMALL") == "1":
        # local testing: force the host platform before any backend init
        jax.config.update("jax_platforms", "cpu")
    if os.environ.get("BENCH_PIPE_CHILD") == "1":
        # the image's sitecustomize re-registers the TPU backend and
        # overrides JAX_PLATFORMS, so the pipeline child's CPU-mesh
        # switch must be programmatic (same dance as __graft_entry__)
        jax.config.update("jax_platforms", "cpu")
    on_tpu = jax.default_backend() in ("tpu", "axon")
    small = (not on_tpu) or os.environ.get("BENCH_SMALL") == "1"

    benches = {"gpt2": _bench_gpt, "resnet50": _bench_resnet50,
               "bert": _bench_bert, "llama": _bench_llama,
               "llama14": _bench_llama14,
               "dispatch": _bench_dispatch, "pipeline": _bench_pipeline,
               "pipeline_bubble": _bench_pipeline_bubble,
               "serving": _bench_serving,
               "serving_resilience": _bench_serving_resilience,
               "serving_router": _bench_serving_router,
               "serving_reqtrace": _bench_serving_reqtrace,
               "verifier_overhead": _bench_verifier_overhead,
               "static_analysis": _bench_static_analysis,
               "compile_cache": _bench_compile_cache,
               "spmd_auto": _bench_spmd_auto,
               "embedding": _bench_embedding,
               "planner_vs_manual": _bench_planner_vs_manual,
               "fusion": _bench_fusion,
               "fleet_observability": _bench_fleet_observability,
               "goodput_overhead": _bench_goodput_overhead,
               "fault_recovery": _bench_fault_recovery,
               "async_overlap": _bench_async_overlap,
               "async_batch_sweep": _bench_async_batch_sweep}
    if _env_bool("BENCH_FUSION", False):
        # opt the LADDER rungs into the fusion pass (they record the
        # flag state in extra either way); the fusion rung itself
        # measures both states regardless
        import paddle_tpu as _p
        _p.set_flags({"FLAGS_enable_fusion": True})
    which = os.environ.get("BENCH_MODEL", "all")
    if which != "all":
        print(json.dumps(benches[which](small)))
        return

    # Default run: every ladder rung (BASELINE.md configs 1-4), one JSON
    # line per rung as it lands, then a combined summary as the FINAL line
    # so a driver that keeps only the last line still records the ladder.
    rungs = {}
    for name in ("gpt2", "resnet50", "bert", "llama", "llama14"):
        r = None
        for attempt in (1, 2):
            try:
                r = benches[name](small)
                break
            except Exception as e:  # pragma: no cover - rung isolation
                # the remote-compile service 500s transiently; one clean
                # retry (fresh caches) rides out a flaky window without
                # masking a real failure
                r = {"metric": name, "value": 0.0, "unit": "error",
                     "vs_baseline": 0.0, "extra": {"error": repr(e)[:300]}}
                import gc
                gc.collect()
                jax.clear_caches()
                time.sleep(20)
        print(json.dumps(r))
        sys.stdout.flush()
        rungs[name] = r
        # the 345M and 770M rungs each approach the 16 GB HBM ceiling;
        # drop cached executables/constants between rungs so one rung's
        # residue can't OOM the next
        import gc
        gc.collect()
        jax.clear_caches()

    # cold-vs-warm compile wall time rides along in every default run
    # (its own JSON line + a summary-extra entry) so the cache win shows
    # up in the round's BENCH_*.json perf trajectory — it does NOT join
    # the train-ladder geomean (different metric class)
    try:
        cc = benches["compile_cache"](small)
    except Exception as e:  # pragma: no cover - rung isolation
        cc = {"metric": "compile_cache_warm_speedup", "value": 0.0,
              "unit": "error", "vs_baseline": 0.0,
              "extra": {"error": repr(e)[:300]}}
    print(json.dumps(cc))
    sys.stdout.flush()

    # spmd_auto rung rides along in every default run: auto-sharded
    # LLM step vs the hand-built fleet-TP path on the same mesh —
    # loss parity gates the score, step-time ratio is the value (own
    # metric class — not in the train geomean)
    try:
        sa = benches["spmd_auto"](small)
    except Exception as e:  # pragma: no cover - rung isolation
        sa = {"metric": "spmd_auto_vs_fleet_tp_step_ratio",
              "value": 0.0, "unit": "error", "vs_baseline": 0.0,
              "extra": {"error": repr(e)[:300]}}
    print(json.dumps(sa))
    sys.stdout.flush()

    # giant-embedding rung rides along in every default run: DLRM with
    # the row-sharded table + dedup exchange vs the replicated
    # baseline, gated on loss parity + the pod capacity proof + the
    # dedup win (own metric class — not in the train geomean; the
    # frozen value is a no-regression floor, see perf_baseline)
    try:
        eb = benches["embedding"](small)
    except Exception as e:  # pragma: no cover - rung isolation
        eb = {"metric": "embedding_sharded_vs_replicated_step_ratio",
              "value": 0.0, "unit": "error", "vs_baseline": 0.0,
              "extra": {"error": repr(e)[:300]}}
    print(json.dumps(eb))
    sys.stdout.flush()

    # planner rung rides along in every default run: the auto-parallel
    # planner's emitted placement vs the best hand-written fleet-TP /
    # FSDP placements on the same GPT + mesh, loss-parity-gated (own
    # metric class — not in the train geomean; the bar is >= 1.0x, see
    # perf_baseline)
    try:
        pv = benches["planner_vs_manual"](small)
    except Exception as e:  # pragma: no cover - rung isolation
        pv = {"metric": "planner_vs_manual_step_ratio",
              "value": 0.0, "unit": "error", "vs_baseline": 0.0,
              "extra": {"error": repr(e)[:300]}}
    print(json.dumps(pv))
    sys.stdout.flush()

    # fusion rung rides along in every default run: fused-vs-unfused
    # step time on the GPT block, parity-gated (own metric class — not
    # in the train geomean; the bar is >= 1.10x, see perf_baseline)
    try:
        fu = benches["fusion"](small)
    except Exception as e:  # pragma: no cover - rung isolation
        fu = {"metric": "fusion_fused_vs_unfused_step_ratio",
              "value": 0.0, "unit": "error", "vs_baseline": 0.0,
              "extra": {"error": repr(e)[:300]}}
    print(json.dumps(fu))
    sys.stdout.flush()

    # fleet-observability overhead rung rides along in every default
    # run: beacon + flight-recorder instrumentation must stay < 2% of
    # step time (own metric class — not in the train geomean)
    try:
        fo = benches["fleet_observability"](small)
    except Exception as e:  # pragma: no cover - rung isolation
        fo = {"metric": "fleet_observability_overhead_ratio",
              "value": 0.0, "unit": "error", "vs_baseline": 0.0,
              "extra": {"error": repr(e)[:300]}}
    print(json.dumps(fo))
    sys.stdout.flush()

    # goodput-ledger + sentinel overhead rung rides along in every
    # default run: the job-health plane must stay < 2% of step time
    # (own metric class — not in the train geomean)
    try:
        go = benches["goodput_overhead"](small)
    except Exception as e:  # pragma: no cover - rung isolation
        go = {"metric": "goodput_overhead_ratio",
              "value": 0.0, "unit": "error", "vs_baseline": 0.0,
              "extra": {"error": repr(e)[:300]}}
    print(json.dumps(go))
    sys.stdout.flush()

    # fault-recovery rung rides along in every default run: the armed
    # abort plane (collective-timeout monitor + heartbeat tick) must
    # stay < 2% of step time, and the measured MTTR of a real
    # kill->restart->resume cycle lands in extra (own metric class —
    # not in the train geomean)
    try:
        fr = benches["fault_recovery"](small)
    except Exception as e:  # pragma: no cover - rung isolation
        fr = {"metric": "fault_recovery_overhead_ratio",
              "value": 0.0, "unit": "error", "vs_baseline": 0.0,
              "extra": {"error": repr(e)[:300]}}
    print(json.dumps(fr))
    sys.stdout.flush()

    # async-runtime rungs ride along in every default run: prefetch +
    # donation + decomposed gathers vs the synchronous baseline on the
    # same GPT (parity-gated; bar >= 1.0x, see perf_baseline) and the
    # donated-vs-undonated steps/sec-vs-batch sweep (own metric class —
    # not in the train geomean)
    try:
        ao = benches["async_overlap"](small)
    except Exception as e:  # pragma: no cover - rung isolation
        ao = {"metric": "async_overlap_step_ratio", "value": 0.0,
              "unit": "error", "vs_baseline": 0.0,
              "extra": {"error": repr(e)[:300]}}
    print(json.dumps(ao))
    sys.stdout.flush()
    try:
        ab = benches["async_batch_sweep"](small)
    except Exception as e:  # pragma: no cover - rung isolation
        ab = {"metric": "async_batch_sweep_tokens_ratio", "value": 0.0,
              "unit": "error", "vs_baseline": 0.0,
              "extra": {"error": repr(e)[:300]}}
    print(json.dumps(ab))
    sys.stdout.flush()

    # serving-resilience rung rides along the same way: goodput vs
    # offered load with shed/deadline-miss counts lands in BENCH_*.json
    # every default run (own metric class — not in the train geomean)
    try:
        sr = benches["serving_resilience"](small)
    except Exception as e:  # pragma: no cover - rung isolation
        sr = {"metric": "serving_resilience_goodput_tokens_per_sec",
              "value": 0.0, "unit": "error", "vs_baseline": 0.0,
              "extra": {"error": repr(e)[:300]}}
    print(json.dumps(sr))
    sys.stdout.flush()

    # serving-router rung: tier-level goodput scaling vs R with the 2x
    # overload curve + int8/speculative parity riders (own metric class —
    # not in the train geomean)
    try:
        srr = benches["serving_router"](small)
    except Exception as e:  # pragma: no cover - rung isolation
        srr = {"metric": "serving_router_goodput_scaling",
               "value": 0.0, "unit": "error", "vs_baseline": 0.0,
               "extra": {"error": repr(e)[:300]}}
    print(json.dumps(srr))
    sys.stdout.flush()

    # request-trace overhead rung: the per-request lifecycle recorder
    # must stay < 2% of a steady-state decode tick with FLAGS_reqtrace
    # on (own metric class — not in the train geomean)
    try:
        rt = benches["serving_reqtrace"](small)
    except Exception as e:  # pragma: no cover - rung isolation
        rt = {"metric": "serving_reqtrace_overhead_ratio",
              "value": 0.0, "unit": "error", "vs_baseline": 0.0,
              "extra": {"error": repr(e)[:300]}}
    print(json.dumps(rt))
    sys.stdout.flush()

    # program-verifier overhead rung: the per-compile contract /
    # collective / sharding / donation passes must stay < 2% of
    # trace+lower (own metric class — not in the train geomean)
    try:
        vo = benches["verifier_overhead"](small)
    except Exception as e:  # pragma: no cover - rung isolation
        vo = {"metric": "verifier_overhead_ratio",
              "value": 0.0, "unit": "error", "vs_baseline": 0.0,
              "extra": {"error": repr(e)[:300]}}
    print(json.dumps(vo))
    sys.stdout.flush()

    # pipeline-bubble rung: measured 1F1B bubble fraction (per-step
    # timings replayed through the schedule event sim) must land within
    # tolerance of the analytical (S-1)/(m+S-1), gradient-parity-gated
    # (own metric class — not in the train geomean)
    try:
        pb = benches["pipeline_bubble"](small)
    except Exception as e:  # pragma: no cover - rung isolation
        pb = {"metric": "pipeline_bubble_measured_vs_analytical",
              "value": 0.0, "unit": "error", "vs_baseline": 0.0,
              "extra": {"error": repr(e)[:300]}}
    print(json.dumps(pb))
    sys.stdout.flush()

    errors = [name for name, r in rungs.items() if r["unit"] == "error"]
    ratios = [r["vs_baseline"] for name, r in rungs.items()
              if r["unit"] != "error"]
    geomean = (float(np.exp(np.mean(np.log(np.maximum(ratios, 1e-9)))))
               if ratios and not errors else 0.0)
    print(json.dumps({
        # a failed rung zeroes the headline so the driver can't record a
        # full-ladder score from a partial run
        "metric": "train_ladder_vs_baseline_geomean",
        "value": round(geomean, 4),
        "unit": "x_baseline_geomean",
        "vs_baseline": round(geomean, 4),
        "errors": errors,
        "extra": {**{name: {"value": r["value"], "unit": r["unit"],
                            "vs_baseline": r["vs_baseline"],
                            "mfu": r.get("extra", {}).get("mfu"),
                            "attribution": r.get("extra", {}).get(
                                "attribution")}
                     for name, r in rungs.items()},
                  "compile_cache": {
                      "value": cc["value"], "unit": cc["unit"],
                      "cold_start_s": cc.get("extra", {}).get(
                          "cold_start_s"),
                      "warm_start_s": cc.get("extra", {}).get(
                          "warm_start_s")},
                  "serving_resilience": {
                      "value": sr["value"], "unit": sr["unit"],
                      "overload_retention": sr["vs_baseline"],
                      "curve": sr.get("extra", {}).get(
                          "goodput_vs_offered_load")},
                  "serving_router": {
                      "value": srr["value"], "unit": srr["unit"],
                      "overload_retention": srr["vs_baseline"],
                      "shed_at_router": srr.get("extra", {}).get(
                          "shed_at_router_total"),
                      "replica_side_shed": srr.get("extra", {}).get(
                          "replica_side_shed_total"),
                      "int8_kv_parity": srr.get("extra", {}).get(
                          "int8_kv_parity"),
                      "speculative_parity": srr.get("extra", {}).get(
                          "speculative_parity"),
                      "spec_acceptance_rate": srr.get("extra", {}).get(
                          "spec_acceptance_rate"),
                      "resident_batch_multiplier": srr.get(
                          "extra", {}).get("resident_batch_multiplier")},
                  "spmd_auto": {
                      "value": sa["value"], "unit": sa["unit"],
                      "loss_parity": sa.get("extra", {}).get(
                          "loss_parity"),
                      "auto_step_s": sa.get("extra", {}).get(
                          "auto_step_s"),
                      "fleet_tp_step_s": sa.get("extra", {}).get(
                          "fleet_tp_step_s"),
                      "attribution": sa.get("extra", {}).get(
                          "attribution")},
                  "planner_vs_manual": {
                      "value": pv["value"], "unit": pv["unit"],
                      "loss_parity": pv.get("extra", {}).get(
                          "loss_parity"),
                      "planner_winner": pv.get("extra", {}).get(
                          "planner_winner"),
                      "planner_step_s": pv.get("extra", {}).get(
                          "planner_step_s"),
                      "planner_fallbacks": pv.get("extra", {}).get(
                          "planner_fallbacks")},
                  "fusion": {
                      "value": fu["value"], "unit": fu["unit"],
                      "vs_baseline": fu["vs_baseline"],
                      "patterns": fu.get("extra", {}).get("patterns"),
                      "train_ratio": fu.get("extra", {}).get(
                          "train_ratio"),
                      "eager_ratio": fu.get("extra", {}).get(
                          "eager_ratio")},
                  "fleet_observability": {
                      "value": fo["value"], "unit": fo["unit"],
                      "overhead_pct": fo.get("extra", {}).get(
                          "overhead_pct"),
                      "within_budget": fo.get("extra", {}).get(
                          "within_budget")},
                  "goodput_overhead": {
                      "value": go["value"], "unit": go["unit"],
                      "overhead_pct": go.get("extra", {}).get(
                          "overhead_pct"),
                      "within_budget": go.get("extra", {}).get(
                          "within_budget")},
                  "fault_recovery": {
                      "value": fr["value"], "unit": fr["unit"],
                      "overhead_pct": fr.get("extra", {}).get(
                          "overhead_pct"),
                      "within_budget": fr.get("extra", {}).get(
                          "within_budget"),
                      "mttr_s": fr.get("extra", {}).get("mttr_s"),
                      "mttr_recovered": fr.get("extra", {}).get(
                          "mttr_recovered")},
                  "serving_reqtrace": {
                      "value": rt["value"], "unit": rt["unit"],
                      "overhead_pct": rt.get("extra", {}).get(
                          "overhead_pct"),
                      "within_budget": rt.get("extra", {}).get(
                          "within_budget")},
                  "verifier_overhead": {
                      "value": vo["value"], "unit": vo["unit"],
                      "overhead_pct": vo.get("extra", {}).get(
                          "overhead_pct"),
                      "within_budget": vo.get("extra", {}).get(
                          "within_budget")},
                  "async_overlap": {
                      "value": ao["value"], "unit": ao["unit"],
                      "loss_parity": ao.get("extra", {}).get(
                          "loss_parity"),
                      "idle_host_shrinks": ao.get("extra", {}).get(
                          "idle_host_shrinks"),
                      "attribution_off": ao.get("extra", {}).get(
                          "attribution_off"),
                      "attribution_on": ao.get("extra", {}).get(
                          "attribution_on")},
                  "async_batch_sweep": {
                      "value": ab["value"], "unit": ab["unit"],
                      "donated_peak_below_undonated": ab.get(
                          "extra", {}).get(
                              "donated_peak_below_undonated"),
                      "sweep": ab.get("extra", {}).get("sweep")}},
    }))


if __name__ == "__main__":
    main()
