"""paddle.sparse — COO/CSR sparse tensors and ops.

Capability parity with the reference sparse stack (reference:
paddle/phi/core/sparse_coo_tensor.h, sparse_csr_tensor.h;
python/paddle/sparse/ — sparse_coo_tensor, sparse_csr_tensor, matmul, add,
relu, to_dense). TPU-native: storage is jax.experimental.sparse BCOO
(XLA-compiled scatter/gather kernels); CSR inputs convert to BCOO
internally (crow decompression is a one-shot row expansion). A
SparseCooTensor IS a Tensor whose payload is the values array, so the
autograd tape flows through sparse ops exactly like dense ones — the
indices are static structure, the values carry the gradient.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..core import dispatch
from ..core.tensor import Tensor, as_tensor


def _arr(x):
    if isinstance(x, Tensor):
        return x._data
    return jnp.asarray(x)


class SparseCooTensor(Tensor):
    """A Tensor whose payload is the nnz values array plus static COO
    indices. Passing ``values_tensor`` adopts its tape lineage so sparse
    ops stay differentiable end to end."""

    def __init__(self, indices, values_or_tensor, shape,
                 stop_gradient=True):
        vt = values_or_tensor if isinstance(values_or_tensor, Tensor) \
            else None
        data = vt._data if vt is not None else jnp.asarray(values_or_tensor)
        if vt is not None:
            stop_gradient = vt.stop_gradient
        super().__init__(data, stop_gradient=stop_gradient)
        if vt is not None:
            self.grad_node = vt.grad_node
            self.output_index = vt.output_index
            if vt.grad_node is None and not vt.stop_gradient:
                # leaf values: share the accumulation identity so grads
                # land in the USER's tensor (vt.grad), not this facade
                from ..autograd.engine import AccumulationNode
                if getattr(vt, "_accum_node", None) is None:
                    vt._accum_node = AccumulationNode(vt)
                self._accum_node = vt._accum_node
        # indices are HOST structure (numpy, [nnz, ndim]): the pattern
        # never carries gradient and every structure op (merge, sort,
        # equality) is host work — keeping it off-device removes the
        # device->host syncs the structure ops used to pay per call
        self._coo_indices = np.asarray(indices)       # [nnz, ndim]
        self._coo_shape = tuple(int(s) for s in shape)

    @property
    def _bcoo(self) -> "jsparse.BCOO":
        return jsparse.BCOO((self._data, jnp.asarray(self._coo_indices)),
                            shape=self._coo_shape)

    @property
    def shape(self):
        return list(self._coo_shape)

    def indices(self) -> Tensor:
        return Tensor(self._coo_indices.T)     # [ndim, nnz] (reference)

    def values(self) -> Tensor:
        # a live view of the values ON the tape (not a detached copy)
        return dispatch.call("sparse_values", lambda v: v, [self])

    def nnz(self) -> int:
        return int(self._coo_indices.shape[0])

    def to_dense(self) -> Tensor:
        idx, shape = self._coo_indices, self._coo_shape

        def f(vals):
            return jsparse.BCOO((vals, idx), shape=shape).todense()
        return dispatch.call("sparse_to_dense", f, [self])

    def is_sparse(self):
        return True

    def is_sparse_coo(self):
        return True

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self._data.dtype})")


def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      stop_gradient=True):
    """Build a COO tensor (reference python/paddle/sparse/creation.py
    sparse_coo_tensor: indices [ndim, nnz]). Tensor ``values`` keep their
    autograd lineage."""
    idx = np.asarray(_arr(indices)).T          # -> [nnz, ndim]
    vt = values if isinstance(values, Tensor) else None
    vals = _arr(values)
    if dtype is not None:
        vals = vals.astype(dtype)
        vt = None      # cast breaks identity; fall back to raw values
    if shape is None:
        if idx.shape[0] == 0:
            raise ValueError(
                "shape is required for an empty sparse tensor (no indices "
                "to infer it from)")
        shape = tuple(int(m) + 1 for m in idx.max(axis=0))
    return SparseCooTensor(idx, vt if vt is not None else vals,
                           shape, stop_gradient=stop_gradient)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      stop_gradient=True):
    """Build from CSR triplets (reference sparse_csr_tensor); stored as
    COO after a one-shot row decompression."""
    crows_np = np.asarray(_arr(crows))
    cols_np = np.asarray(_arr(cols))
    rows = np.repeat(np.arange(len(crows_np) - 1), np.diff(crows_np))
    idx = np.stack([rows, cols_np], axis=1)
    vt = values if isinstance(values, Tensor) else None
    vals = _arr(values)
    if dtype is not None:
        vals = vals.astype(dtype)
        vt = None
    t = SparseCooTensor(idx, vt if vt is not None else vals, shape,
                        stop_gradient=stop_gradient)
    t._csr = (crows_np, cols_np)
    return t


def to_dense(x) -> Tensor:
    return x.to_dense() if isinstance(x, SparseCooTensor) else as_tensor(x)


def matmul(x, y, name=None) -> Tensor:
    """sparse @ dense (reference python/paddle/sparse/binary.py matmul)."""
    if not isinstance(x, SparseCooTensor):
        raise TypeError("sparse.matmul expects a SparseCooTensor lhs")
    idx, shape = x._coo_indices, x._coo_shape
    yt = y if isinstance(y, Tensor) else as_tensor(y)

    def f(vals, dense):
        return jsparse.BCOO((vals, idx), shape=shape) @ dense
    return dispatch.call("sparse_matmul", f, [x, yt])


def add(x, y, name=None):
    """sparse+sparse (union of patterns, grads flow to both) or
    sparse+dense."""
    if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor):
        if x._coo_shape != y._coo_shape:
            raise ValueError("shape mismatch in sparse.add")
        # result STRUCTURE (indices + per-input positions) is computed
        # eagerly; the VALUES go through the tape
        res_idx, pos_x, pos_y = _merge_patterns(x, y)
        n_out = res_idx.shape[0]

        def f(va, vb):
            out = jnp.zeros((n_out,), va.dtype)
            return out.at[pos_x].add(va).at[pos_y].add(vb)
        vals = dispatch.call("sparse_add", f, [x, y])
        return SparseCooTensor(res_idx, vals, x._coo_shape)
    return to_dense(x) + to_dense(y)


def relu(x, name=None):
    if isinstance(x, SparseCooTensor):
        out = dispatch.call("sparse_relu",
                            lambda v: jnp.maximum(v, 0), [x])
        return SparseCooTensor(x._coo_indices, out, x._coo_shape)
    from ..nn import functional as F
    return F.relu(x)


def is_sparse(x) -> bool:
    return isinstance(x, SparseCooTensor)


__all__ = ["SparseCooTensor", "sparse_coo_tensor", "sparse_csr_tensor",
           "to_dense", "matmul", "add", "relu", "is_sparse"]


# ---------------------------------------------------------------------------
# Unary value-ops: apply elementwise to stored values, keep the pattern
# (reference python/paddle/sparse/unary.py — each is a distinct phi
# sparse kernel; here one generic lowering, XLA fuses the elementwise op)
# ---------------------------------------------------------------------------
def _unary(op_name, jfn):
    def op(x, name=None):  # name: paddle API convention, display only
        if isinstance(x, SparseCooTensor):
            out = dispatch.call(f"sparse_{op_name}", jfn, [x])
            return SparseCooTensor(x._coo_indices, out, x._coo_shape)
        return dispatch.call(op_name, jfn, [as_tensor(x)])

    op.__name__ = op_name
    op.__doc__ = (f"sparse.{op_name}: elementwise {op_name} over stored "
                  f"values (reference python/paddle/sparse/unary.py "
                  f"{op_name}).")
    return op


sin = _unary("sin", jnp.sin)
tan = _unary("tan", jnp.tan)
asin = _unary("asin", jnp.arcsin)
atan = _unary("atan", jnp.arctan)
sinh = _unary("sinh", jnp.sinh)
tanh = _unary("tanh", jnp.tanh)
asinh = _unary("asinh", jnp.arcsinh)
atanh = _unary("atanh", jnp.arctanh)
sqrt = _unary("sqrt", jnp.sqrt)
square = _unary("square", jnp.square)
log1p = _unary("log1p", jnp.log1p)
abs = _unary("abs", jnp.abs)  # noqa: A001 - reference name
neg = _unary("neg", jnp.negative)
expm1 = _unary("expm1", jnp.expm1)
deg2rad = _unary("deg2rad", jnp.deg2rad)
rad2deg = _unary("rad2deg", jnp.rad2deg)
isnan = _unary("isnan", jnp.isnan)


def pow(x, factor, name=None):  # noqa: A001 - reference name
    if isinstance(x, SparseCooTensor):
        out = dispatch.call("sparse_pow",
                            lambda v: jnp.power(v, factor), [x])
        return SparseCooTensor(x._coo_indices, out, x._coo_shape)
    return dispatch.call("pow", lambda v: jnp.power(v, factor),
                         [as_tensor(x)])


def cast(x, index_dtype=None, value_dtype=None, name=None):
    if not isinstance(x, SparseCooTensor):
        raise TypeError("sparse.cast expects a sparse tensor")
    vals = (dispatch.call("sparse_cast",
                          lambda v: v.astype(value_dtype), [x])
            if value_dtype is not None else x.values())
    idx = (x._coo_indices.astype(index_dtype)
           if index_dtype is not None else x._coo_indices)
    return SparseCooTensor(idx, vals, x._coo_shape)


# ---------------------------------------------------------------------------
# Binary / structure ops
# ---------------------------------------------------------------------------
def _positions(res_idx, idx):
    """Scatter position of each row of ``idx`` inside ``res_idx``
    (pure host: both patterns are numpy structure)."""
    lookup = {tuple(r): i for i, r in enumerate(res_idx)}
    return np.asarray([lookup[tuple(r)] for r in idx])


def _merge_patterns(x, y):
    """Union pattern + per-input scatter positions — pure host numpy
    over the stored structure: ``np.unique`` sorts the union
    row-lexicographically (the same canonical order BCOO dedup uses)
    and its inverse IS each input row's scatter position. No device
    round-trip: the pattern is structure, not data."""
    both = np.concatenate([x._coo_indices, y._coo_indices], axis=0)
    res_idx, inverse = np.unique(both, axis=0, return_inverse=True)
    nx = x._coo_indices.shape[0]
    return res_idx, inverse[:nx], inverse[nx:]


def subtract(x, y, name=None):
    """sparse - sparse over the union pattern (reference binary.py)."""
    if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor):
        if x._coo_shape != y._coo_shape:
            raise ValueError("shape mismatch in sparse.subtract")
        res_idx, pos_x, pos_y = _merge_patterns(x, y)
        n_out = res_idx.shape[0]

        def f(va, vb):
            out = jnp.zeros((n_out,), va.dtype)
            return out.at[pos_x].add(va).at[pos_y].add(-vb)

        vals = dispatch.call("sparse_subtract", f, [x, y])
        return SparseCooTensor(res_idx, vals, x._coo_shape)
    return to_dense(x) - to_dense(y)


def multiply(x, y, name=None):
    """Elementwise multiply. sparse*sparse multiplies matching positions
    (intersection pattern == union with zeros elsewhere); sparse*scalar
    scales values (reference binary.py multiply)."""
    if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor):
        if x._coo_shape != y._coo_shape:
            raise ValueError("shape mismatch in sparse.multiply")
        res_idx, pos_x, pos_y = _merge_patterns(x, y)
        n_out = res_idx.shape[0]

        def f(va, vb):
            a = jnp.zeros((n_out,), va.dtype).at[pos_x].add(va)
            b = jnp.zeros((n_out,), vb.dtype).at[pos_y].add(vb)
            return a * b

        vals = dispatch.call("sparse_multiply", f, [x, y])
        return SparseCooTensor(res_idx, vals, x._coo_shape)
    if isinstance(x, SparseCooTensor):
        if isinstance(y, Tensor) and y.size == 1:
            # grad-tracked scalar: keep it on the tape
            out = dispatch.call("sparse_scale",
                                lambda v, s: v * s.reshape(()), [x, y])
            return SparseCooTensor(x._coo_indices, out, x._coo_shape)
        if np.isscalar(y):
            out = dispatch.call("sparse_scale",
                                lambda v: v * float(y), [x])
            return SparseCooTensor(x._coo_indices, out, x._coo_shape)
        return to_dense(x) * to_dense(y)
    return to_dense(x) * to_dense(y)


def divide(x, y, name=None):
    if isinstance(x, SparseCooTensor) and np.isscalar(y):
        out = dispatch.call("sparse_div", lambda v: v / float(y), [x])
        return SparseCooTensor(x._coo_indices, out, x._coo_shape)
    if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor):
        # implicit zeros make off-pattern quotients 0/0; only the
        # identical-pattern case has well-defined sparse semantics
        if np.array_equal(x._coo_indices, y._coo_indices):
            vals = dispatch.call("sparse_div_vv", lambda a, b: a / b,
                                 [x, y])
            return SparseCooTensor(x._coo_indices, vals, x._coo_shape)
        raise ValueError(
            "sparse.divide requires identical sparsity patterns "
            "(off-pattern positions would be 0/0)")
    return to_dense(x) / to_dense(y)


def mv(x, vec, name=None):
    """sparse matrix @ dense vector (reference binary.py mv)."""
    return matmul(x, vec)


def masked_matmul(x, y, mask, name=None):
    """(dense @ dense) sampled at mask's sparsity pattern — SDDMM
    (reference binary.py masked_matmul). TPU-native: gather the needed
    rows/cols and batch the row·col dot products; never materializes the
    dense product."""
    if not isinstance(mask, SparseCooTensor):
        raise TypeError("masked_matmul mask must be sparse")
    xt = x if isinstance(x, Tensor) else as_tensor(x)
    yt = y if isinstance(y, Tensor) else as_tensor(y)
    rows = jnp.asarray(mask._coo_indices[:, 0])
    cols = jnp.asarray(mask._coo_indices[:, 1])

    def f(a, b):
        return jnp.einsum("nk,nk->n", a[rows], b[:, cols].T)

    vals = dispatch.call("masked_matmul", f, [xt, yt])
    return SparseCooTensor(mask._coo_indices, vals, mask._coo_shape)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """beta*input + alpha*(x @ y) with sparse x (reference multiary.py)."""
    prod = matmul(x, y)
    inp = input if isinstance(input, Tensor) else as_tensor(input)
    return dispatch.call("sparse_addmm",
                         lambda i, p: beta * i + alpha * p, [inp, prod])


def transpose(x, perm, name=None):
    """Permute sparse dims: permute index columns + reorder (reference
    unary.py transpose)."""
    if not isinstance(x, SparseCooTensor):
        raise TypeError("sparse.transpose expects a sparse tensor")
    idx = x._coo_indices[:, list(perm)]
    shape = tuple(np.asarray(x._coo_shape)[list(perm)])
    order = np.lexsort(tuple(idx[:, d] for d in range(idx.shape[1] - 1, -1, -1)))
    vals = dispatch.call("sparse_transpose_gather",
                         lambda v: v[jnp.asarray(order)], [x])
    return SparseCooTensor(idx[order], vals, shape)


def sum(x, axis=None, dtype=None, keepdim=False, name=None):  # noqa: A001
    """Sum of stored values over axis (reference unary.py sum). Full
    reduction returns a dense scalar; axis reduction returns dense."""
    if not isinstance(x, SparseCooTensor):
        raise TypeError("sparse.sum expects a sparse tensor")
    if axis is None:
        return dispatch.call(
            "sparse_sum_all",
            lambda v: jnp.sum(v.astype(dtype) if dtype else v), [x])
    out = to_dense(x)
    if dtype is not None:
        out = out.astype(dtype)
    return out.sum(axis=axis, keepdim=keepdim)


def coalesce(x, name=None):
    """Merge duplicate coordinates (reference unary.py coalesce)."""
    if not isinstance(x, SparseCooTensor):
        raise TypeError("sparse.coalesce expects a sparse tensor")
    # duplicate merge is a host structure op: unique rows + inverse
    # scatter positions (same canonical row-lexicographic order BCOO
    # dedup produces), no device round-trip
    res_idx, pos = np.unique(x._coo_indices, axis=0, return_inverse=True)
    n_out = res_idx.shape[0]

    def f(v):
        return jnp.zeros((n_out,), v.dtype).at[pos].add(v)

    vals = dispatch.call("sparse_coalesce", f, [x])
    return SparseCooTensor(res_idx, vals, x._coo_shape)


def is_same_shape(x, y) -> bool:
    xs = x._coo_shape if isinstance(x, SparseCooTensor) else tuple(x.shape)
    ys = y._coo_shape if isinstance(y, SparseCooTensor) else tuple(y.shape)
    return tuple(xs) == tuple(ys)


def reshape(x, shape, name=None):
    """Reshape the sparse tensor by re-deriving coordinates from flat
    offsets (reference unary.py reshape)."""
    if not isinstance(x, SparseCooTensor):
        raise TypeError("sparse.reshape expects a sparse tensor")
    old = np.asarray(x._coo_shape)
    idx = x._coo_indices
    flat = np.zeros(idx.shape[0], np.int64)
    for d in range(idx.shape[1]):
        flat = flat * old[d] + idx[:, d]
    new = np.asarray(shape)
    neg = new < 0
    if neg.sum() > 1:
        raise ValueError("sparse.reshape: at most one -1 dim")
    if neg.any():
        new = new.copy()
        rest = int(np.prod(new[~neg]))
        if rest == 0 or int(np.prod(old)) % rest:
            raise ValueError(
                f"sparse.reshape: cannot infer -1 for {tuple(shape)} "
                f"from {tuple(old)}")
        new[neg] = int(np.prod(old)) // rest
    if int(np.prod(new)) != int(np.prod(old)):
        raise ValueError(
            f"sparse.reshape: size mismatch {tuple(old)} -> "
            f"{tuple(shape)}")
    coords = []
    rem = flat
    for d in range(len(new) - 1, -1, -1):
        coords.append(rem % new[d])
        rem = rem // new[d]
    new_idx = np.stack(coords[::-1], axis=1)
    return SparseCooTensor(new_idx, x.values(), tuple(int(s) for s in new))


__all__ += ["sin", "tan", "asin", "atan", "sinh", "tanh", "asinh",
            "atanh", "sqrt", "square", "log1p", "abs", "neg", "expm1",
            "deg2rad", "rad2deg", "isnan", "pow", "cast", "subtract",
            "multiply", "divide", "mv", "masked_matmul", "addmm",
            "transpose", "sum", "coalesce", "is_same_shape", "reshape"]


# ----------------------------------------------------------- surface tail
def mask_as(x, mask, name=None):
    """Select ``x``'s entries at ``mask``'s sparsity pattern (reference
    sparse/binary.py mask_as): dense x + sparse mask → sparse with
    mask's structure and x's values there."""
    dense = x.to_dense() if isinstance(x, SparseCooTensor) else as_tensor(x)
    if isinstance(mask, SparseCooTensor):
        idx = mask.indices()._data               # [ndim, nnz]
        vals = dense._data[tuple(idx)]
        # constructor stores [nnz, ndim] (raw layout), so transpose
        return SparseCooTensor(idx.T, Tensor(vals), dense.shape)
    raise TypeError("mask_as expects a sparse COO mask")


def slice(x, axes, starts, ends, name=None):
    """Slice a sparse tensor along ``axes`` (reference sparse slice):
    filters nnz entries into the window and rebases indices."""
    if not isinstance(x, SparseCooTensor):
        raise TypeError("sparse.slice expects a SparseCooTensor")
    idx = np.asarray(x.indices().numpy())
    vals = np.asarray(x.values().numpy())
    shape = list(x.shape)
    keep = np.ones(idx.shape[1], bool)
    new_shape = list(shape)
    for ax, st, en in zip(axes, starts, ends):
        ax = int(ax) % len(shape)
        st = int(st) % shape[ax] if st < 0 else min(int(st), shape[ax])
        en = int(en) % shape[ax] if en < 0 else min(int(en), shape[ax])
        keep &= (idx[ax] >= st) & (idx[ax] < en)
        new_shape[ax] = en - st
    kept = idx[:, keep].copy()
    for ax, st, en in zip(axes, starts, ends):
        ax = int(ax) % len(shape)
        st = int(st) % shape[ax] if st < 0 else min(int(st), shape[ax])
        kept[ax] -= st
    return sparse_coo_tensor(kept, vals[keep], new_shape)


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """Randomized PCA over a sparse COO tensor (reference
    sparse pca_lowrank): densify + the dense routine — the TPU has no
    sparse MXU path, and q·niter matmuls on the densified matrix ARE
    the efficient form at supported sizes."""
    from ..ops.linalg import pca_lowrank as _dense_pca
    dense = x.to_dense() if isinstance(x, SparseCooTensor) else as_tensor(x)
    return _dense_pca(dense, q=q, center=center, niter=niter)


__all__ += ["mask_as", "slice", "pca_lowrank"]
