"""paddle.sparse — COO/CSR sparse tensors and ops.

Capability parity with the reference sparse stack (reference:
paddle/phi/core/sparse_coo_tensor.h, sparse_csr_tensor.h;
python/paddle/sparse/ — sparse_coo_tensor, sparse_csr_tensor, matmul, add,
relu, to_dense). TPU-native: storage is jax.experimental.sparse BCOO
(XLA-compiled scatter/gather kernels); CSR inputs convert to BCOO
internally (crow decompression is a one-shot row expansion). A
SparseCooTensor IS a Tensor whose payload is the values array, so the
autograd tape flows through sparse ops exactly like dense ones — the
indices are static structure, the values carry the gradient.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..core import dispatch
from ..core.tensor import Tensor, as_tensor


def _arr(x):
    if isinstance(x, Tensor):
        return x._data
    return jnp.asarray(x)


class SparseCooTensor(Tensor):
    """A Tensor whose payload is the nnz values array plus static COO
    indices. Passing ``values_tensor`` adopts its tape lineage so sparse
    ops stay differentiable end to end."""

    def __init__(self, indices, values_or_tensor, shape,
                 stop_gradient=True):
        vt = values_or_tensor if isinstance(values_or_tensor, Tensor) \
            else None
        data = vt._data if vt is not None else jnp.asarray(values_or_tensor)
        if vt is not None:
            stop_gradient = vt.stop_gradient
        super().__init__(data, stop_gradient=stop_gradient)
        if vt is not None:
            self.grad_node = vt.grad_node
            self.output_index = vt.output_index
            if vt.grad_node is None and not vt.stop_gradient:
                # leaf values: share the accumulation identity so grads
                # land in the USER's tensor (vt.grad), not this facade
                from ..autograd.engine import AccumulationNode
                if getattr(vt, "_accum_node", None) is None:
                    vt._accum_node = AccumulationNode(vt)
                self._accum_node = vt._accum_node
        self._coo_indices = jnp.asarray(indices)      # [nnz, ndim]
        self._coo_shape = tuple(int(s) for s in shape)

    @property
    def _bcoo(self) -> "jsparse.BCOO":
        return jsparse.BCOO((self._data, self._coo_indices),
                            shape=self._coo_shape)

    @property
    def shape(self):
        return list(self._coo_shape)

    def indices(self) -> Tensor:
        return Tensor(self._coo_indices.T)     # [ndim, nnz] (reference)

    def values(self) -> Tensor:
        # a live view of the values ON the tape (not a detached copy)
        return dispatch.call("sparse_values", lambda v: v, [self])

    def nnz(self) -> int:
        return int(self._coo_indices.shape[0])

    def to_dense(self) -> Tensor:
        idx, shape = self._coo_indices, self._coo_shape

        def f(vals):
            return jsparse.BCOO((vals, idx), shape=shape).todense()
        return dispatch.call("sparse_to_dense", f, [self])

    def is_sparse(self):
        return True

    def is_sparse_coo(self):
        return True

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self._data.dtype})")


def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      stop_gradient=True):
    """Build a COO tensor (reference python/paddle/sparse/creation.py
    sparse_coo_tensor: indices [ndim, nnz]). Tensor ``values`` keep their
    autograd lineage."""
    idx = np.asarray(_arr(indices)).T          # -> [nnz, ndim]
    vt = values if isinstance(values, Tensor) else None
    vals = _arr(values)
    if dtype is not None:
        vals = vals.astype(dtype)
        vt = None      # cast breaks identity; fall back to raw values
    if shape is None:
        if idx.shape[0] == 0:
            raise ValueError(
                "shape is required for an empty sparse tensor (no indices "
                "to infer it from)")
        shape = tuple(int(m) + 1 for m in idx.max(axis=0))
    return SparseCooTensor(idx, vt if vt is not None else vals,
                           shape, stop_gradient=stop_gradient)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      stop_gradient=True):
    """Build from CSR triplets (reference sparse_csr_tensor); stored as
    COO after a one-shot row decompression."""
    crows_np = np.asarray(_arr(crows))
    cols_np = np.asarray(_arr(cols))
    rows = np.repeat(np.arange(len(crows_np) - 1), np.diff(crows_np))
    idx = np.stack([rows, cols_np], axis=1)
    vt = values if isinstance(values, Tensor) else None
    vals = _arr(values)
    if dtype is not None:
        vals = vals.astype(dtype)
        vt = None
    t = SparseCooTensor(idx, vt if vt is not None else vals, shape,
                        stop_gradient=stop_gradient)
    t._csr = (crows_np, cols_np)
    return t


def to_dense(x) -> Tensor:
    return x.to_dense() if isinstance(x, SparseCooTensor) else as_tensor(x)


def matmul(x, y, name=None) -> Tensor:
    """sparse @ dense (reference python/paddle/sparse/binary.py matmul)."""
    if not isinstance(x, SparseCooTensor):
        raise TypeError("sparse.matmul expects a SparseCooTensor lhs")
    idx, shape = x._coo_indices, x._coo_shape
    yt = y if isinstance(y, Tensor) else as_tensor(y)

    def f(vals, dense):
        return jsparse.BCOO((vals, idx), shape=shape) @ dense
    return dispatch.call("sparse_matmul", f, [x, yt])


def add(x, y, name=None):
    """sparse+sparse (union of patterns, grads flow to both) or
    sparse+dense."""
    if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor):
        if x._coo_shape != y._coo_shape:
            raise ValueError("shape mismatch in sparse.add")
        # result STRUCTURE (indices + per-input positions) is computed
        # eagerly; the VALUES go through the tape
        merged = jsparse.bcoo_sum_duplicates(jsparse.BCOO(
            (jnp.concatenate([jnp.zeros_like(x._data),
                              jnp.zeros_like(y._data)]),
             jnp.concatenate([x._coo_indices, y._coo_indices])),
            shape=x._coo_shape))
        res_idx = np.asarray(merged.indices)
        lookup = {tuple(r): i for i, r in enumerate(res_idx)}
        pos_x = jnp.asarray([lookup[tuple(r)]
                             for r in np.asarray(x._coo_indices)])
        pos_y = jnp.asarray([lookup[tuple(r)]
                             for r in np.asarray(y._coo_indices)])
        n_out = res_idx.shape[0]

        def f(va, vb):
            out = jnp.zeros((n_out,), va.dtype)
            return out.at[pos_x].add(va).at[pos_y].add(vb)
        vals = dispatch.call("sparse_add", f, [x, y])
        return SparseCooTensor(res_idx, vals, x._coo_shape)
    return to_dense(x) + to_dense(y)


def relu(x, name=None):
    if isinstance(x, SparseCooTensor):
        out = dispatch.call("sparse_relu",
                            lambda v: jnp.maximum(v, 0), [x])
        return SparseCooTensor(x._coo_indices, out, x._coo_shape)
    from ..nn import functional as F
    return F.relu(x)


def is_sparse(x) -> bool:
    return isinstance(x, SparseCooTensor)


__all__ = ["SparseCooTensor", "sparse_coo_tensor", "sparse_csr_tensor",
           "to_dense", "matmul", "add", "relu", "is_sparse"]
