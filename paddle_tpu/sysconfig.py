"""paddle.sysconfig — install paths for native extension builds
(reference: python/paddle/sysconfig.py get_include/get_lib)."""
import os

__all__ = ["get_include", "get_lib"]

_ROOT = os.path.dirname(os.path.abspath(__file__))


def get_include() -> str:
    """Directory of C headers shipped with the package (the native
    runtime's sources double as the public header surface)."""
    return os.path.join(_ROOT, "native", "src")


def get_lib() -> str:
    """Directory of built native libraries."""
    return os.path.join(_ROOT, "native")
