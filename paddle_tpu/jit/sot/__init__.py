"""SOT v1 — partial-frame graph breaks via deferred (lazy) execution.

Reference contract: python/paddle/jit/sot/translate.py:98 (frame-eval entry),
sot/symbolic/statement_ir.py (captured op-statement IR), and
symbolic/compile_cache.py (guarded per-site program cache): when a function
hits an untraceable construct, the reference compiles the statements BEFORE
the break, runs the break eagerly, and resumes capture after it — instead of
abandoning the whole frame.

TPU-native redesign — no bytecode simulation. Python runs the frame
normally, but ops dispatched while SOT capture is active do not execute:
they append to a **segment graph** (the StatementIR analogue) and return
``LazyArray`` placeholders carrying abstract shapes. Any concretization
point — ``Tensor.numpy()``, ``bool()``, ``item()``, a host round-trip —
**flushes** the current segment: the accumulated op list is compiled as ONE
XLA program (the pre-break subgraph), executed, and capture resumes into a
fresh segment. Function exit flushes the tail segment. A function with one
mid-frame ``numpy()`` sync therefore yields exactly two compiled subgraphs.

Guards + cache: each flushed segment is keyed by (site index, op-sequence
fingerprint, external input shapes/dtypes) — re-running the function with
the same shapes reuses the compiled programs (the compile_cache.py role).
"""
from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional, Sequence, Tuple
import weakref

import jax
import jax.numpy as jnp
import numpy as np

_tls = threading.local()

#: bound on compiled segments per (StaticFunction, signature) cache —
#: long-running shape-diverse workloads must not grow XLA executables
#: without limit (compile_cache.py's cache is similarly bounded by
#: guard invalidation in the reference)
SEGMENT_CACHE_MAX = 128

_PRIM = (int, float, bool, str, bytes, complex, type(None))


def _const_repr(v, depth: int) -> str:
    """Stable repr of a captured Python constant for guard keys."""
    if isinstance(v, _PRIM) or isinstance(v, (np.integer, np.floating,
                                              np.bool_)):
        return repr(v)
    if isinstance(v, (tuple, list)):
        if depth <= 0:
            return f"<seq:{len(v)}>"
        return "[" + ",".join(_const_repr(x, depth - 1) for x in v) + "]"
    if isinstance(v, dict):
        if depth <= 0:
            return f"<dict:{len(v)}>"
        try:
            items = sorted(v.items())
        except TypeError:
            items = list(v.items())
        return "{" + ",".join(f"{k!r}:{_const_repr(x, depth - 1)}"
                              for k, x in items) + "}"
    if hasattr(v, "shape") and hasattr(v, "dtype"):
        shape = tuple(getattr(v, "shape", ()))
        size = int(np.prod(shape)) if shape else 1
        if size <= 1:
            # scalar arrays DO value-guard: a loss scale / step counter
            # baked into a lowering must invalidate on change (the sync
            # is one host read of one element)
            try:
                return f"<arr:{shape}:{v.dtype}:{np.asarray(v).item()!r}>"
            except Exception:
                pass
        # larger payloads guard shape/dtype only (cheap); value-captured
        # big arrays should be op INPUTS, not closure constants
        return f"<arr:{shape}:{v.dtype}>"
    if callable(v):
        return fn_fingerprint(v, depth - 1)
    # plain object: guard its primitive/scalar attributes one level deep
    # (e.g. a GradScaler captured via ``self`` — its _scale must key the
    # cache, or a post-overflow segment stale-hits the old scale)
    d = getattr(v, "__dict__", None)
    if d and depth > 0:
        attrs = ",".join(
            f"{k}:{_const_repr(x, 0)}" for k, x in
            sorted(d.items())[:16]
            if isinstance(x, _PRIM + (np.integer, np.floating, np.bool_))
            or (hasattr(x, "shape") and hasattr(x, "dtype")))
        return f"<{type(v).__name__}:{attrs}>"
    return f"<{type(v).__name__}>"


def fn_fingerprint(f, depth: int = 2) -> str:
    """Guard key covering the VALUES a lowering closed over, not just its
    attrs (reference: sot/symbolic/compile_cache.py object guards over
    globals/closure cells). A non-tensor Python value baked into the
    lowering closure (e.g. a rope theta, a scale factor) changes the key,
    so the cached program recompiles instead of stale-hitting."""
    import functools
    if isinstance(f, functools.partial):
        return ("partial(" + fn_fingerprint(f.func, depth) + ","
                + _const_repr(f.args, depth) + ","
                + _const_repr(f.keywords, depth) + ")")
    code = getattr(f, "__code__", None)
    if code is None:
        return f"<callable:{type(f).__name__}>"
    parts = [code.co_filename, str(code.co_firstlineno)]
    if depth > 0:
        for cell in getattr(f, "__closure__", None) or ():
            try:
                v = cell.cell_contents
            except ValueError:
                parts.append("<empty>")
                continue
            parts.append(_const_repr(v, depth))
        for d in getattr(f, "__defaults__", None) or ():
            parts.append(_const_repr(d, depth))
    return "|".join(parts)


def active() -> bool:
    return getattr(_tls, "capture", None) is not None


def current_segment() -> Optional["Segment"]:
    cap = getattr(_tls, "capture", None)
    return cap.segment if cap is not None else None


class LazyArray:
    """Placeholder payload for a Tensor whose value is a pending segment
    node. Carries the abstract shape/dtype; concretizing (``__array__`` /
    ``__jax_array__``) flushes the owning segment."""

    __slots__ = ("segment", "node_id", "out_idx", "aval", "_value",
                 "__weakref__")

    def __init__(self, segment, node_id, out_idx, aval):
        self.segment = segment
        self.node_id = node_id
        self.out_idx = out_idx
        self.aval = aval
        self._value = None

    # ---- abstract metadata (Tensor.shape/.dtype/.ndim read these)
    @property
    def shape(self):
        return tuple(self.aval.shape)

    @property
    def ndim(self):
        return len(self.aval.shape)

    @property
    def dtype(self):
        return self.aval.dtype

    @property
    def size(self):
        return int(np.prod(self.aval.shape)) if self.aval.shape else 1

    # ---- concretization = graph break boundary
    def concrete(self):
        if self._value is None:
            self.segment.flush()
        if self._value is None:  # pragma: no cover - defensive
            raise RuntimeError("segment flush did not materialize node")
        return self._value

    def __array__(self, dtype=None, copy=None):
        arr = np.asarray(self.concrete())
        return arr.astype(dtype) if dtype is not None else arr

    def __jax_array__(self):
        return self.concrete()

    def astype(self, dtype):
        seg = current_segment()
        if self._value is None and seg is self.segment:
            return seg.add("astype",
                           lambda x, _d=dtype: x.astype(_d), [self],
                           attr_key=str(dtype))[0]
        return self.concrete().astype(dtype)

    def __repr__(self):
        state = "pending" if self._value is None else "materialized"
        return (f"LazyArray(shape={self.shape}, dtype={self.dtype}, "
                f"{state})")


def _aval_of(x) -> jax.ShapeDtypeStruct:
    if isinstance(x, LazyArray):
        return x.aval
    if isinstance(x, jax.ShapeDtypeStruct):
        return x
    a = jnp.asarray(x) if not hasattr(x, "dtype") else x
    return jax.ShapeDtypeStruct(tuple(getattr(a, "shape", ())), a.dtype)


class Segment:
    """One pre-break subgraph under construction (StatementIR analogue)."""

    def __init__(self, owner: "capture"):
        self.owner = owner
        self.nodes: List[Tuple[str, Callable, tuple, int, str]] = []
        self.ext_arrays: List[Any] = []
        self._ext_ids: dict = {}
        self._lazy: List[weakref.ref] = []
        self._flushed = False

    # ------------------------------------------------------------- inputs
    def _ext(self, arr) -> int:
        key = id(arr)
        idx = self._ext_ids.get(key)
        if idx is None:
            idx = len(self.ext_arrays)
            self.ext_arrays.append(arr)
            self._ext_ids[key] = idx
        return idx

    def _ref_of(self, a):
        if isinstance(a, LazyArray) and a._value is None \
                and a.segment is self:
            return ("n", a.node_id, a.out_idx)
        if isinstance(a, LazyArray):
            return ("x", self._ext(a.concrete()))
        return ("x", self._ext(a))

    # ------------------------------------------------------------ capture
    def add(self, op_name: str, f: Callable, arrays: Sequence,
            attr_key: str = "") -> List[LazyArray]:
        """Append one op; returns LazyArrays for its outputs. Raises if the
        op cannot be shape-inferred (caller falls back to concrete)."""
        lazies, _multi = self.add_with_structure(op_name, f, arrays,
                                                attr_key)
        return lazies

    def add_with_structure(self, op_name: str, f: Callable,
                           arrays: Sequence, attr_key: str = ""):
        in_refs = tuple(self._ref_of(a) for a in arrays)
        avals = [a.aval if isinstance(a, LazyArray) and a._value is None
                 else _aval_of(a) for a in arrays]
        out = jax.eval_shape(f, *avals)
        multi = isinstance(out, (tuple, list))
        out_avals = list(out) if multi else [out]
        node_id = len(self.nodes)
        self.nodes.append((op_name, f, in_refs, len(out_avals), attr_key))
        lazies = [LazyArray(self, node_id, i, av)
                  for i, av in enumerate(out_avals)]
        self._lazy.extend(weakref.ref(l) for l in lazies)
        return lazies, multi

    # -------------------------------------------------------------- flush
    def fingerprint(self, out_refs) -> tuple:
        return (
            tuple((op, attr_key, in_refs, n_out)
                  for op, _f, in_refs, n_out, attr_key in self.nodes),
            tuple((tuple(_aval_of(a).shape), str(_aval_of(a).dtype))
                  for a in self.ext_arrays),
            tuple(out_refs),
        )

    def flush(self) -> None:
        """Compile + execute the accumulated subgraph, materialize every
        live LazyArray, and hand the capture a fresh segment."""
        if self._flushed:
            return
        self._flushed = True
        self.owner._segment_closed(self)
        if not self.nodes:
            return
        live = [l for l in (r() for r in self._lazy)
                if l is not None and l._value is None]
        out_refs = sorted({(l.node_id, l.out_idx) for l in live})
        key = (self.owner.site_idx, self.fingerprint(out_refs))
        jitted = self.owner.cache.get(key)
        if jitted is not None:
            # LRU touch: FIFO eviction would throw out the steady-state
            # hot segment first and thrash recompiles
            self.owner.cache.pop(key)
            self.owner.cache[key] = jitted
        if jitted is None:
            nodes = self.nodes

            def seg_fn(ext):
                env: List[List[Any]] = []
                for _op, f, in_refs, _n, _ak in nodes:
                    ins = [env[r[1]][r[2]] if r[0] == "n" else ext[r[1]]
                           for r in in_refs]
                    o = f(*ins)
                    env.append(list(o) if isinstance(o, (tuple, list))
                               else [o])
                return [env[i][j] for i, j in out_refs]

            jitted = jax.jit(seg_fn)
            if len(self.owner.cache) >= SEGMENT_CACHE_MAX:
                self.owner.cache.pop(next(iter(self.owner.cache)))
            self.owner.cache[key] = jitted
            self.owner.stats["compiled"] += 1
        results = jitted(self.ext_arrays)
        value_of = dict(zip(out_refs, results))
        for l in live:
            l._value = value_of[(l.node_id, l.out_idx)]
        self.owner.stats["segments"] += 1
        self.owner.site_idx += 1


class capture:
    """Context manager activating SOT lazy capture on this thread.

    ``cache`` persists across invocations (per StaticFunction+signature);
    ``stats`` counts segments flushed / programs compiled for this run.
    """

    def __init__(self, cache: Optional[dict] = None):
        self.cache = cache if cache is not None else {}
        self.stats = {"segments": 0, "compiled": 0}
        self.segment = Segment(self)
        self.site_idx = 0

    def _segment_closed(self, seg: Segment):
        if seg is self.segment:
            self.segment = Segment(self)

    def __enter__(self):
        if getattr(_tls, "capture", None) is not None:
            raise RuntimeError("SOT capture is not reentrant")
        _tls.capture = self
        return self

    def __exit__(self, exc_type, exc, tb):
        _tls.capture = None
        if exc_type is None:
            self.segment.flush()
        return False


def record_or_none(op_name: str, f: Callable, arrays: Sequence,
                   attrs: Optional[dict]):
    """Dispatch hook: append the op to the active segment. Returns
    ``(lazy_outputs, is_multi_output)``, or None when SOT is inactive /
    the op cannot be deferred (shape inference failed → caller executes
    concretely after we flush, an implicit break)."""
    seg = current_segment()
    if seg is None:
        return None
    try:
        attr_key = repr(sorted((attrs or {}).items()))
    except Exception:
        attr_key = f"<unrepr:{op_name}>"
    # value-guard the lowering's closure: constants captured OUTSIDE the
    # attrs dict must invalidate the cached segment when they change
    attr_key += "#" + fn_fingerprint(f)
    try:
        return seg.add_with_structure(op_name, f, arrays,
                                      attr_key=attr_key)
    except Exception:
        # data-dependent output shape (nonzero, unique, …): break here —
        # flush the prefix and let the op run on concrete values
        seg.flush()
        return None
