"""SOT v1 — partial-frame graph breaks via deferred (lazy) execution.

Reference contract: python/paddle/jit/sot/translate.py:98 (frame-eval entry),
sot/symbolic/statement_ir.py (captured op-statement IR), and
symbolic/compile_cache.py (guarded per-site program cache): when a function
hits an untraceable construct, the reference compiles the statements BEFORE
the break, runs the break eagerly, and resumes capture after it — instead of
abandoning the whole frame.

TPU-native redesign — no bytecode simulation. Python runs the frame
normally, but ops dispatched while SOT capture is active do not execute:
they append to a **segment graph** (the StatementIR analogue) and return
``LazyArray`` placeholders carrying abstract shapes. Any concretization
point — ``Tensor.numpy()``, ``bool()``, ``item()``, a host round-trip —
**flushes** the current segment: the accumulated op list is compiled as ONE
XLA program (the pre-break subgraph), executed, and capture resumes into a
fresh segment. Function exit flushes the tail segment. A function with one
mid-frame ``numpy()`` sync therefore yields exactly two compiled subgraphs.

Guards + cache: each flushed segment is keyed by (site index, op-sequence
fingerprint, external input shapes/dtypes) — re-running the function with
the same shapes reuses the compiled programs (the compile_cache.py role).

Steady-state bypass (the compile_cache.py guard-hit fast path): while the
frame replays, a ``FrameJournal`` records the segment DAG — each segment's
cache key, where its external arrays came from (frame input / parameter /
an earlier segment's output / captured constant), the scalar values Python
read at the breaks, and how the frame's return value maps onto segment
outputs. Two consecutive runs with the identical journal mark the frame
STABLE; later calls skip Python entirely: one frame-level guard check
(function closure fingerprint + input signature), then the stitched
compiled segments execute directly with parameters re-read live and every
break scalar value-guarded against the recording. Any guard miss falls
back to Python replay and re-records. Frames whose outputs carry autograd
nodes, whose break values are non-scalar and consumed by glue code, or
whose glue mutates parameters mid-frame are ineligible (replay keeps full
semantics there).
"""
from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional, Sequence, Tuple
import weakref

import time

import jax
import jax.numpy as jnp
import numpy as np

from ...observability import metrics as _metrics
from ...observability import trace as _trace

_tls = threading.local()

# SOT segment-cache telemetry (gated by FLAGS_enable_metrics)
_m_segment_cache = _metrics.counter(
    "paddle_tpu_sot_segment_cache_total",
    "SOT compiled-segment cache events at flush: hit = cached XLA "
    "program reused, miss = segment compiled fresh.",
    labelnames=("event",))
_m_segment_compile_time = _metrics.histogram(
    "paddle_tpu_sot_segment_compile_seconds",
    "Wall time to compile + first-run one flushed SOT segment.")

#: bound on compiled segments per (StaticFunction, signature) cache —
#: long-running shape-diverse workloads must not grow XLA executables
#: without limit (compile_cache.py's cache is similarly bounded by
#: guard invalidation in the reference)
SEGMENT_CACHE_MAX = 128

_PRIM = (int, float, bool, str, bytes, complex, type(None))


def _const_repr(v, depth: int) -> str:
    """Stable repr of a captured Python constant for guard keys."""
    if isinstance(v, _PRIM) or isinstance(v, (np.integer, np.floating,
                                              np.bool_)):
        return repr(v)
    if isinstance(v, (tuple, list)):
        if depth <= 0:
            return f"<seq:{len(v)}>"
        return "[" + ",".join(_const_repr(x, depth - 1) for x in v) + "]"
    if isinstance(v, dict):
        if depth <= 0:
            return f"<dict:{len(v)}>"
        try:
            items = sorted(v.items())
        except TypeError:
            items = list(v.items())
        return "{" + ",".join(f"{k!r}:{_const_repr(x, depth - 1)}"
                              for k, x in items) + "}"
    if hasattr(v, "shape") and hasattr(v, "dtype"):
        try:
            shape = tuple(v.shape)
        except TypeError:
            # ".shape" is a method, not array metadata (duck-type miss)
            return f"<{type(v).__name__}>"
        size = int(np.prod(shape)) if shape else 1
        payload = getattr(v, "_data", v)
        if isinstance(payload, LazyArray) and payload._value is None:
            # pending segment node captured in a lowering closure (the
            # control-flow ops close over branch Tensors): reading its
            # value here would flush the segment MID-RECORD. Its value
            # dependence flows through op inputs, so shape/dtype guard.
            return f"<arr:{shape}:{v.dtype}:lazy>"
        if size <= 1:
            # scalar arrays DO value-guard: a loss scale / step counter
            # baked into a lowering must invalidate on change (the sync
            # is one host read of one element)
            try:
                return f"<arr:{shape}:{v.dtype}:{np.asarray(v).item()!r}>"
            except Exception:
                pass
        # larger payloads guard shape/dtype only (cheap); value-captured
        # big arrays should be op INPUTS, not closure constants
        return f"<arr:{shape}:{v.dtype}>"
    import functools
    if hasattr(v, "__code__") or isinstance(v, functools.partial):
        return fn_fingerprint(v, depth - 1)
    # plain object (incl. callable objects like Layers): guard its
    # primitive/scalar attributes one level deep (e.g. a GradScaler
    # captured via ``self`` — its _scale must key the cache, or a
    # post-overflow segment stale-hits the old scale)
    d = getattr(v, "__dict__", None)
    if d and depth > 0:
        guardable = [(k, x) for k, x in sorted(d.items())
                     if isinstance(x, _PRIM + (np.integer, np.floating,
                                               np.bool_))
                     or (hasattr(x, "shape") and hasattr(x, "dtype"))]
        attrs = ",".join(f"{k}:{_const_repr(x, 0)}"
                         for k, x in guardable[:16])
        return f"<{type(v).__name__}:{attrs}>"
    return f"<{type(v).__name__}>"


def fn_fingerprint(f, depth: int = 2) -> str:
    """Guard key covering the VALUES a lowering closed over, not just its
    attrs (reference: sot/symbolic/compile_cache.py object guards over
    globals/closure cells). A non-tensor Python value baked into the
    lowering closure (e.g. a rope theta, a scale factor) changes the key,
    so the cached program recompiles instead of stale-hitting."""
    import functools
    if isinstance(f, functools.partial):
        return ("partial(" + fn_fingerprint(f.func, depth) + ","
                + _const_repr(f.args, depth) + ","
                + _const_repr(f.keywords, depth) + ")")
    code = getattr(f, "__code__", None)
    if code is None:
        return f"<callable:{type(f).__name__}>"
    parts = [code.co_filename, str(code.co_firstlineno)]
    if depth > 0:
        for cell in getattr(f, "__closure__", None) or ():
            try:
                v = cell.cell_contents
            except ValueError:
                parts.append("<empty>")
                continue
            parts.append(_const_repr(v, depth))
        for d in getattr(f, "__defaults__", None) or ():
            parts.append(_const_repr(d, depth))
    return "|".join(parts)


def active() -> bool:
    return getattr(_tls, "capture", None) is not None


def current_segment() -> Optional["Segment"]:
    cap = getattr(_tls, "capture", None)
    return cap.segment if cap is not None else None


class LazyArray:
    """Placeholder payload for a Tensor whose value is a pending segment
    node. Carries the abstract shape/dtype; concretizing (``__array__`` /
    ``__jax_array__``) flushes the owning segment."""

    __slots__ = ("segment", "node_id", "out_idx", "aval", "_value",
                 "__weakref__")

    def __init__(self, segment, node_id, out_idx, aval):
        self.segment = segment
        self.node_id = node_id
        self.out_idx = out_idx
        self.aval = aval
        self._value = None

    # ---- abstract metadata (Tensor.shape/.dtype/.ndim read these)
    @property
    def shape(self):
        return tuple(self.aval.shape)

    @property
    def ndim(self):
        return len(self.aval.shape)

    @property
    def dtype(self):
        return self.aval.dtype

    @property
    def size(self):
        return int(np.prod(self.aval.shape)) if self.aval.shape else 1

    # ---- concretization = graph break boundary
    def concrete(self):
        if self._value is None:
            self.segment.flush()
        if self._value is None:  # pragma: no cover - defensive
            raise RuntimeError("segment flush did not materialize node")
        return self._value

    def __array__(self, dtype=None, copy=None):
        arr = np.asarray(self.concrete())
        return arr.astype(dtype) if dtype is not None else arr

    def __jax_array__(self):
        return self.concrete()

    def astype(self, dtype):
        seg = current_segment()
        if self._value is None and seg is self.segment:
            return seg.add("astype",
                           lambda x, _d=dtype: x.astype(_d), [self],
                           attr_key=str(dtype))[0]
        return self.concrete().astype(dtype)

    def __repr__(self):
        state = "pending" if self._value is None else "materialized"
        return (f"LazyArray(shape={self.shape}, dtype={self.dtype}, "
                f"{state})")


def _aval_of(x) -> jax.ShapeDtypeStruct:
    if isinstance(x, LazyArray):
        return x.aval
    if isinstance(x, jax.ShapeDtypeStruct):
        return x
    a = jnp.asarray(x) if not hasattr(x, "dtype") else x
    return jax.ShapeDtypeStruct(tuple(getattr(a, "shape", ())), a.dtype)


class Segment:
    """One pre-break subgraph under construction (StatementIR analogue)."""

    def __init__(self, owner: "capture"):
        self.owner = owner
        self.nodes: List[Tuple[str, Callable, tuple, int, str]] = []
        self.ext_arrays: List[Any] = []
        self._ext_ids: dict = {}
        self._lazy: List[weakref.ref] = []
        self._flushed = False

    # ------------------------------------------------------------- inputs
    def _ext(self, arr) -> int:
        key = id(arr)
        idx = self._ext_ids.get(key)
        if idx is None:
            idx = len(self.ext_arrays)
            self.ext_arrays.append(arr)
            self._ext_ids[key] = idx
        return idx

    def _ref_of(self, a):
        if isinstance(a, LazyArray) and a._value is None \
                and a.segment is self:
            return ("n", a.node_id, a.out_idx)
        if isinstance(a, LazyArray):
            return ("x", self._ext(a.concrete()))
        return ("x", self._ext(a))

    # ------------------------------------------------------------ capture
    def add(self, op_name: str, f: Callable, arrays: Sequence,
            attr_key: str = "") -> List[LazyArray]:
        """Append one op; returns LazyArrays for its outputs. Raises if the
        op cannot be shape-inferred (caller falls back to concrete)."""
        lazies, _multi = self.add_with_structure(op_name, f, arrays,
                                                attr_key)
        return lazies

    def add_with_structure(self, op_name: str, f: Callable,
                           arrays: Sequence, attr_key: str = "",
                           attrs=None):
        in_refs = tuple(self._ref_of(a) for a in arrays)
        avals = [a.aval if isinstance(a, LazyArray) and a._value is None
                 else _aval_of(a) for a in arrays]
        out = jax.eval_shape(f, *avals)
        multi = isinstance(out, (tuple, list))
        out_avals = list(out) if multi else [out]
        node_id = len(self.nodes)
        # semantic attrs + shapes ride the node so the graph-fusion pass
        # (compile/fusion.fuse_sot_nodes) can pattern-match the segment
        io_shapes = (tuple(tuple(a.shape) for a in avals),
                     tuple(tuple(a.shape) for a in out_avals))
        self.nodes.append((op_name, f, in_refs, len(out_avals), attr_key,
                           dict(attrs or {}), io_shapes))
        lazies = [LazyArray(self, node_id, i, av)
                  for i, av in enumerate(out_avals)]
        self._lazy.extend(weakref.ref(l) for l in lazies)
        return lazies, multi

    # -------------------------------------------------------------- flush
    def fingerprint(self, out_refs) -> tuple:
        return (
            tuple((op, attr_key, in_refs, n_out)
                  for op, _f, in_refs, n_out, attr_key, *_ in self.nodes),
            tuple((tuple(_aval_of(a).shape), str(_aval_of(a).dtype))
                  for a in self.ext_arrays),
            tuple(out_refs),
        )

    def flush(self) -> None:
        """Compile + execute the accumulated subgraph, materialize every
        live LazyArray, and hand the capture a fresh segment."""
        if self._flushed:
            return
        self._flushed = True
        self.owner._segment_closed(self)
        if not self.nodes:
            return
        live = [l for l in (r() for r in self._lazy)
                if l is not None and l._value is None]
        out_refs = sorted({(l.node_id, l.out_idx) for l in live})
        key = (self.owner.site_idx, self.fingerprint(out_refs))
        from ...compile import fusion as _fusion
        if _fusion.enabled():
            # fused and unfused compiles of one segment must never share
            # a cache entry (in-memory or persistent)
            key = key + (_fusion.fingerprint(),)
        jitted = self.owner.cache.get(key)
        if jitted is not None:
            # LRU touch: FIFO eviction would throw out the steady-state
            # hot segment first and thrash recompiles
            self.owner.cache.pop(key)
            self.owner.cache[key] = jitted
            if _metrics.enabled():
                _m_segment_cache.inc(event="hit")
        if jitted is None:
            if _metrics.enabled():
                _m_segment_cache.inc(event="miss")
            nodes = self.nodes

            # program verification on a cache MISS (FLAGS_verify_programs)
            # — the segment node graph is an op-list IR like any other;
            # strict raises before the segment ever compiles
            from ...static import verifier as _verifier
            if _verifier.mode() != "off":
                recs = [
                    _verifier.Record(
                        name=op, fn=f,
                        in_ids=tuple(tuple(r) for r in in_refs),
                        out_ids=tuple(("n", nid, k)
                                      for k in range(n_out)),
                        attrs=attrs, in_shapes=io_shapes[0],
                        out_shapes=io_shapes[1])
                    for nid, (op, f, in_refs, n_out, _ak, attrs,
                              io_shapes) in enumerate(self.nodes)]
                _verifier.enforce(_verifier.check(
                    recs,
                    label=f"sot segment (site {self.owner.site_idx})"))

            # pattern matching only on a cache MISS: a hit replays the
            # already-fused compile, and the rewritten/matched counters
            # stay per-compile (not per-execution)
            fuse_plan = None
            if _fusion.enabled():
                fuse_plan, fstats = _fusion.fuse_sot_nodes(self.nodes,
                                                           out_refs)
                if fstats and fstats.get("rewritten"):
                    self.owner.stats["fusion_rewritten"] = (
                        self.owner.stats.get("fusion_rewritten", 0)
                        + sum(fstats["rewritten"].values()))

            if fuse_plan is not None:
                def seg_fn(ext, _plan=fuse_plan):
                    # fused replay: env keyed by the ORIGINAL ("n",
                    # node, out) slots, so out_refs stay valid; values
                    # interior to a fused chain are simply never written
                    env: dict = {}
                    for st in _plan:
                        ins = [env[r] if r[0] == "n" else ext[r[1]]
                               for r in st.in_ids]
                        o = st.fn(*ins)
                        outs = (list(o) if isinstance(o, (tuple, list))
                                else [o])
                        for oid, v in zip(st.out_ids, outs):
                            env[oid] = v
                    return [env[("n", i, j)] for i, j in out_refs]
            else:
                def seg_fn(ext):
                    env: List[List[Any]] = []
                    for _op, f, in_refs, _n, _ak, *_ in nodes:
                        ins = [env[r[1]][r[2]] if r[0] == "n"
                               else ext[r[1]] for r in in_refs]
                        o = f(*ins)
                        env.append(list(o) if isinstance(o, (tuple, list))
                                   else [o])
                    return [env[i][j] for i, j in out_refs]

            # persistent compilation cache: a segment already compiled by
            # another process (same ops/shapes/toolchain) deserializes
            # instead of recompiling
            jitted = _pcc_lookup(key)
            if jitted is not None:
                if len(self.owner.cache) >= SEGMENT_CACHE_MAX:
                    self.owner.cache.pop(next(iter(self.owner.cache)))
                self.owner.cache[key] = jitted
                results = jitted(self.ext_arrays)
            else:
                jitted, publish = _pcc_compile(
                    seg_fn, self.ext_arrays,
                    label=f"site{self.owner.site_idx}"
                          f"_ops{len(self.nodes)}")
                if len(self.owner.cache) >= SEGMENT_CACHE_MAX:
                    self.owner.cache.pop(next(iter(self.owner.cache)))
                self.owner.cache[key] = jitted
                self.owner.stats["compiled"] += 1
                # XLA compiles on the first execution — time it as the
                # segment's compile cost
                from ...observability import goodput as _goodput
                with _trace.span(
                        f"sot_segment_compile:site{self.owner.site_idx}",
                        "compile", {"ops": len(self.nodes)}), \
                        _goodput.bill("compile"):
                    c0 = time.perf_counter()
                    results = jitted(self.ext_arrays)
                seg_seconds = time.perf_counter() - c0
                if _metrics.enabled():
                    _m_segment_compile_time.observe(seg_seconds)
                if publish is not None:
                    publish(key, seg_seconds)
        else:
            results = jitted(self.ext_arrays)
        value_of = dict(zip(out_refs, results))
        for l in live:
            l._value = value_of[(l.node_id, l.out_idx)]
        if self.owner.journal is not None:
            self.owner._journal_segment(self, key, out_refs, results)
        self.owner.stats["segments"] += 1
        self.owner.site_idx += 1


class FrameJournal:
    """Record of one SOT replay: the frame's segment DAG + data flow.

    ``segments``: list of dicts with
      key        — the segment's compile-cache key
      ext_srcs   — per ext array: ("in", i) frame tensor input,
                   ("param", i) live parameter (re-read at bypass time),
                   ("seg", s, (node, out)) earlier segment's output,
                   ("const", array) value captured from glue code
      out_refs   — the (node, out) pairs the segment materialized
      guards     — {(node, out): float} scalar values Python read at the
                   break (bypass re-checks them; a flip = control flow
                   would differ = fall back to replay)
    ``out_map``  — frame return value as (treedef, leaf descriptors)
    ``eligible`` — False when bypass would be unsound for this frame
    """

    def __init__(self):
        self.segments: List[dict] = []
        self.out_map = None
        self.eligible = True
        self.reason = ""

    def mark_ineligible(self, why: str):
        self.eligible = False
        self.reason = why

    def structure_key(self):
        return tuple(s["key"] for s in self.segments)


class capture:
    """Context manager activating SOT lazy capture on this thread.

    ``cache`` persists across invocations (per StaticFunction+signature);
    ``stats`` counts segments flushed / programs compiled for this run.
    ``journal``: pass a FrameJournal plus the frame's input arrays and
    parameters to record the segment DAG for the steady-state bypass.
    """

    def __init__(self, cache: Optional[dict] = None,
                 journal: Optional[FrameJournal] = None,
                 input_arrays: Sequence = (), params: Sequence = ()):
        self.cache = cache if cache is not None else {}
        self.stats = {"segments": 0, "compiled": 0}
        self.segment = Segment(self)
        self.site_idx = 0
        self.journal = journal
        if journal is not None:
            self._src_of = {}
            for i, a in enumerate(input_arrays):
                self._src_of[id(a)] = ("in", i)
            self._param_ids = {}
            for i, p in enumerate(params):
                d = getattr(p, "_data", None)
                if d is not None:
                    self._param_ids[id(d)] = i
            self._params = list(params)
            self._param_data0 = [getattr(p, "_data", None) for p in params]

    def _segment_closed(self, seg: Segment):
        if seg is self.segment:
            self.segment = Segment(self)

    # ------------------------------------------------- journal recording
    def _journal_segment(self, seg: "Segment", key, out_refs, results):
        j = self.journal
        if j is None or not j.eligible:
            return
        srcs = []
        for a in seg.ext_arrays:
            src = self._src_of.get(id(a))
            if src is None:
                pi = self._param_ids.get(id(a))
                src = ("param", pi) if pi is not None else ("const", a)
            srcs.append(src)
        s_idx = len(j.segments)
        j.segments.append({"key": key, "ext_srcs": srcs,
                           "out_refs": list(out_refs), "guards": {}})
        if not hasattr(self, "_out_values"):
            self._out_values = {}
        for ref, val in zip(out_refs, results):
            # later segments that consume this output find it by id
            self._src_of[id(val)] = ("seg", s_idx, tuple(ref))
            self._out_values[(s_idx, tuple(ref))] = val

    def finalize_journal(self, out_leaves: Sequence, treedef) -> None:
        """Classify the frame's return leaves + decide break guards."""
        j = self.journal
        if j is None or not j.eligible:
            return
        # any parameter mutated mid-frame -> glue has side effects the
        # bypass would not reproduce
        for p, d0 in zip(self._params, self._param_data0):
            if getattr(p, "_data", None) is not d0:
                j.mark_ineligible("parameter mutated during frame")
                return
        leaf_descrs = []
        for leaf in out_leaves:
            if getattr(leaf, "grad_node", None) is not None:
                j.mark_ineligible("output carries autograd state")
                return
            is_tensor = hasattr(leaf, "_data")
            wrap = ("tensor", bool(getattr(leaf, "stop_gradient", True))) \
                if is_tensor else None
            payload = leaf._data if is_tensor else leaf
            if type(payload) is LazyArray:
                payload = payload.concrete()
            src = self._src_of.get(id(payload))
            if src is None:
                leaf_descrs.append(("const", payload, wrap))
            elif src[0] == "seg":
                leaf_descrs.append(("seg", src[1], src[2], wrap))
            else:
                leaf_descrs.append((src[0], src[1], wrap))
        j.out_map = (treedef, leaf_descrs)
        # break guards: outputs of non-final segments that glue code read
        # (i.e. NOT consumed as a later segment's ext input nor returned).
        # Scalars are value-guarded; a non-scalar glue read is opaque to
        # guarding, so the frame stays on Python replay.
        consumed_by_later = set()
        for srec in j.segments:
            for src in srec["ext_srcs"]:
                if src[0] == "seg":
                    consumed_by_later.add((src[1], tuple(src[2])))
        returned_refs = {(d[1], tuple(d[2])) for d in leaf_descrs
                         if d[0] == "seg"}
        out_values = getattr(self, "_out_values", {})
        # EVERY segment's glue-read outputs need guards — including the
        # final one: a frame can break, read a scalar, branch on it, and
        # return without recording further ops
        for s_idx, srec in enumerate(j.segments):
            for ref in srec["out_refs"]:
                r = (s_idx, tuple(ref))
                if r in consumed_by_later or r in returned_refs:
                    continue
                val = out_values.get(r)
                if val is None:
                    continue
                if getattr(val, "size", 0) != 1:
                    j.mark_ineligible(
                        "non-scalar break value read by glue code")
                    return
                srec["guards"][tuple(ref)] = float(np.asarray(val))

    def __enter__(self):
        if getattr(_tls, "capture", None) is not None:
            raise RuntimeError("SOT capture is not reentrant")
        _tls.capture = self
        return self

    def __exit__(self, exc_type, exc, tb):
        _tls.capture = None
        if exc_type is None:
            self.segment.flush()
        return False


def replay_frame(journal: FrameJournal, cache: dict, input_arrays: Sequence,
                 params: Sequence):
    """Steady-state fast path: execute the journal's stitched compiled
    segments directly — no Python frame, no per-op recording, no
    re-fingerprinting. Returns (ok, (treedef, leaves), why); ``ok=False``
    means a guard missed or state moved and the caller must fall back to
    a recording Python replay."""
    env: dict = {}
    for s_idx, srec in enumerate(journal.segments):
        jitted = cache.get(srec["key"])
        if jitted is None:
            return False, None, "compiled segment evicted"
        ext = []
        for src in srec["ext_srcs"]:
            kind = src[0]
            if kind == "in":
                ext.append(input_arrays[src[1]])
            elif kind == "param":
                if src[1] >= len(params):
                    return False, None, "parameter list changed"
                d = getattr(params[src[1]], "_data", None)
                if d is None:
                    return False, None, "parameter gone"
                ext.append(d)
            elif kind == "seg":
                ext.append(env[(src[1], tuple(src[2]))])
            else:  # const
                ext.append(src[1])
        results = jitted(ext)
        for ref, val in zip(srec["out_refs"], results):
            env[(s_idx, tuple(ref))] = val
        for ref, expected in srec["guards"].items():
            got = float(np.asarray(env[(s_idx, tuple(ref))]))
            if got != expected:
                # the scalar Python branched on at record time changed —
                # glue control flow could differ; replay honestly
                return False, None, "break value guard miss"
    treedef, descrs = journal.out_map
    leaves = []
    for d in descrs:
        kind = d[0]
        if kind == "seg":
            leaves.append((env[(d[1], tuple(d[2]))], d[3]))
        elif kind == "in":
            leaves.append((input_arrays[d[1]], d[2]))
        elif kind == "param":
            if d[1] >= len(params):
                return False, None, "parameter list changed"
            arr = getattr(params[d[1]], "_data", None)
            if arr is None:
                return False, None, "parameter gone"
            leaves.append((arr, d[2]))
        else:
            leaves.append((d[1], d[2]))
    return True, (treedef, leaves), ""


def _pcc_key(key) -> str:
    """Persistent-cache key for one segment: the in-memory cache key
    (site index + op-sequence fingerprint + ext shapes/dtypes + out
    refs) is already a stable, content-describing tuple of strings and
    ints — fold its repr with the toolchain/topology fingerprint."""
    from ... import compile as pcc
    return pcc.key_of("sot", repr(key))


def _pcc_lookup(key):
    """Deserialize a persistently-cached segment program, or None. The
    runner takes the ext-array list like the jitted seg_fn. Failures of
    any kind are a miss — the segment simply recompiles."""
    try:
        from ... import compile as pcc
        if not pcc.enabled():
            return None
        got = pcc.get_cache().get(_pcc_key(key), site="sot")
        if got is None:
            return None
        meta, payload = got
        runner = pcc.aot.load_runner(meta.get("tier", ""), payload)
        if runner is None:
            return None
        pcc.record_time_saved(meta.get("compile_seconds", 0.0))
        return lambda ext, _r=runner: _r([jnp.asarray(e) for e in ext])
    except Exception:
        return None


def _pcc_compile(seg_fn, ext_arrays, label: str = "segment"):
    """Build the segment's compiled program. With the persistent cache
    off: plain ``jax.jit`` (zero behavior change). With it on: AOT
    lower+compile so the executable handle can be serialized; returns
    ``(runner, publish)`` where ``publish(key, seconds)`` writes the
    entry once the caller has timed the compile."""
    from ...observability import perf as _perf

    try:
        from ... import compile as pcc
        use_pcc = pcc.enabled()
    except Exception:
        use_pcc = False
    perf_capture = _perf.capture_enabled()
    if not use_pcc and not perf_capture:
        return jax.jit(seg_fn), None
    try:
        # normalize ext leaves exactly as the runners do at call time, so
        # the compiled avals (incl. weak types) match on every call
        conv = [jnp.asarray(e) for e in ext_arrays]
        compiled = jax.jit(seg_fn).lower(conv).compile()
    except Exception:
        return jax.jit(seg_fn), None
    if perf_capture:
        _perf.record_compiled("sot", label, compiled)

    def runner(ext, _c=compiled):
        return _c([jnp.asarray(e) for e in ext])

    if not use_pcc:
        # perf-capture-only AOT: nothing to publish without the cache
        return runner, None

    def publish(key, seconds, _c=compiled):
        try:
            ser = pcc.aot.serialize_compiled(_c)
            if ser is not None:
                tier, payload = ser
                pcc.get_cache().put(
                    _pcc_key(key), payload,
                    {"site": "sot", "tier": tier,
                     "compile_seconds": float(seconds)})
        except Exception:
            pass

    return runner, publish


def record_or_none(op_name: str, f: Callable, arrays: Sequence,
                   attrs: Optional[dict]):
    """Dispatch hook: append the op to the active segment. Returns
    ``(lazy_outputs, is_multi_output)``, or None when SOT is inactive /
    the op cannot be deferred (shape inference failed → caller executes
    concretely after we flush, an implicit break)."""
    seg = current_segment()
    if seg is None:
        return None
    try:
        attr_key = repr(sorted((attrs or {}).items()))
    except Exception:
        attr_key = f"<unrepr:{op_name}>"
    # value-guard the lowering's closure: constants captured OUTSIDE the
    # attrs dict must invalidate the cached segment when they change
    attr_key += "#" + fn_fingerprint(f)
    try:
        return seg.add_with_structure(op_name, f, arrays,
                                      attr_key=attr_key, attrs=attrs)
    except Exception:
        # data-dependent output shape (nonzero, unique, …): break here —
        # flush the prefix and let the op run on concrete values
        seg.flush()
        return None
