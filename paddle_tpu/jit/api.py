"""Program capture (to_static) — trace-based v0.

Reference: python/paddle/jit/api.py to_static:173 + dy2static/sot capture
frontends. TPU-native design: instead of transpiling Python to a Program IR,
`to_static` jits the wrapped callable with jax — the dispatcher runs under
tracing (payloads become tracers), the autograd tape records as usual, and
XLA compiles the whole step. Guards = jax's shape/dtype dispatch cache.

This v0 supports function capture with static control flow. Graph-break
fallback and bytecode-level capture (SOT) land on top of this API.
"""
from __future__ import annotations

import functools
import threading
from typing import Any, Callable, Optional

import jax
import numpy as np

from ..core.tensor import Tensor

_capture = threading.local()


def in_capture_mode() -> bool:
    return getattr(_capture, "active", 0) > 0


class _CaptureScope:
    def __enter__(self):
        _capture.active = getattr(_capture, "active", 0) + 1
        return self

    def __exit__(self, *exc):
        _capture.active -= 1
        return False


def _unwrap(obj):
    if isinstance(obj, Tensor):
        return obj._data
    if isinstance(obj, (list, tuple)):
        return type(obj)(_unwrap(o) for o in obj)
    if isinstance(obj, dict):
        return {k: _unwrap(v) for k, v in obj.items()}
    return obj


def _wrap(obj):
    if isinstance(obj, jax.Array):
        return Tensor(obj)
    if isinstance(obj, (list, tuple)):
        return type(obj)(_wrap(o) for o in obj)
    if isinstance(obj, dict):
        return {k: _wrap(v) for k, v in obj.items()}
    return obj


class StaticFunction:
    """Callable wrapper holding the jit cache (reference:
    dy2static/program_translator.py:329 StaticFunction)."""

    def __init__(self, fn: Callable, input_spec=None, build_strategy=None,
                 backend=None, full_graph=True):
        self._dygraph_fn = fn
        self._input_spec = input_spec
        functools.update_wrapper(self, fn)

        def traced(params_data, args_data, kwargs_data):
            with _CaptureScope():
                # rebind parameter payloads to tracers for the trace
                originals = []
                for p, d in params_data:
                    originals.append((p, p._data))
                    p._data = d
                try:
                    args_t = _wrap(args_data)
                    kwargs_t = _wrap(kwargs_data)
                    out = fn(*args_t, **kwargs_t)
                    return _unwrap(out)
                finally:
                    for p, d in originals:
                        p._data = d

        self._jitted = None
        self._traced = traced

    def _collect_params(self, args):
        """Find Layer instances bound to the function (self for methods)."""
        params = []
        owner = getattr(self._dygraph_fn, "__self__", None)
        if owner is not None and hasattr(owner, "parameters"):
            params.extend(owner.parameters())
            params.extend(b for _, b in owner.named_buffers())
        for a in args:
            if hasattr(a, "parameters") and hasattr(a, "named_buffers"):
                params.extend(a.parameters())
        return params

    def __call__(self, *args, **kwargs):
        if in_capture_mode():
            return self._dygraph_fn(*args, **kwargs)
        params = self._collect_params(args)
        pairs = [(p, p._data) for p in params]
        if self._jitted is None:
            def jit_target(param_arrays, args_data, kwargs_data):
                return self._traced(
                    list(zip(params, param_arrays)), args_data, kwargs_data)
            self._jitted = jax.jit(jit_target)
        out = self._jitted([d for _, d in pairs], _unwrap(args),
                           _unwrap(kwargs))
        return _wrap(out)

    @property
    def code(self):
        import inspect
        return inspect.getsource(self._dygraph_fn)

    def concrete_program(self):
        return None


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, full_graph=True):
    def decorate(fn):
        if hasattr(fn, "forward") and callable(getattr(fn, "forward")):
            # Layer instance: wrap its forward
            layer = fn
            layer.forward = StaticFunction(layer.forward, input_spec,
                                           build_strategy, backend, full_graph)
            return layer
        return StaticFunction(fn, input_spec, build_strategy, backend,
                              full_graph)
    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(fn):
    fn._not_to_static = True
    return fn


def ignore_module(modules):
    return None


def save(layer, path, input_spec=None, **configs):
    """Save params + (optionally) the traced program (reference:
    python/paddle/jit/api.py save). v0 persists the state_dict; exported
    StableHLO lands with the inference-export milestone."""
    from ..framework.io import save as _save
    state = layer.state_dict() if hasattr(layer, "state_dict") else layer
    _save(state, path + ".pdparams")


def load(path, **configs):
    from ..framework.io import load as _load
    return _load(path + ".pdparams")
