"""Program capture (to_static) — trace-based v0.

Reference: python/paddle/jit/api.py to_static:173 + dy2static/sot capture
frontends. TPU-native design: instead of transpiling Python to a Program IR,
`to_static` jits the wrapped callable with jax — the dispatcher runs under
tracing (payloads become tracers), the autograd tape records as usual, and
XLA compiles the whole step. Guards = jax's shape/dtype dispatch cache.

Supports function capture with static control flow, plus SOT-style
graph-break fallback (reference sot/translate.py): with full_graph=False
(the default, matching the reference's SOT mode), data-dependent Python
control flow falls back to eager with a warning and a recorded
``graph_break_reason`` instead of erroring; full_graph=True makes breaks
hard errors. Bytecode-level partial-frame capture is intentionally not
replicated — the capture unit here is the function, with jax's shape/dtype
dispatch cache playing the role of SOT guards.
"""
from __future__ import annotations

import functools
import threading
import time
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..observability import goodput as _goodput
from ..observability import metrics as _metrics
from ..observability import sentinel as _sentinel
from ..observability import trace as _trace

_capture = threading.local()

# to_static compile telemetry (collection gated by FLAGS_enable_metrics)
_m_compile = _metrics.counter(
    "paddle_tpu_to_static_compile_total",
    "to_static program builds: initial = first signature of a "
    "StaticFunction, retrace = additional signature.",
    labelnames=("kind",))
_m_compile_time = _metrics.histogram(
    "paddle_tpu_to_static_compile_seconds",
    "Wall time of the first call for a new to_static signature (trace + "
    "XLA compile + first run).", labelnames=("kind",))
_m_retrace_reason = _metrics.counter(
    "paddle_tpu_to_static_retrace_total",
    "Why a new signature retraced: new_input_shapes, new_static_args, or "
    "new_structure.", labelnames=("reason",))
_m_graph_break = _metrics.counter(
    "paddle_tpu_graph_break_total",
    "to_static full-graph trace failures that fell back to SOT "
    "partial-frame capture, labeled by the tracer error class.",
    labelnames=("reason",))
_m_sot_frame = _metrics.counter(
    "paddle_tpu_sot_frame_total",
    "SOT frame executions: bypass = stitched compiled segments (no "
    "Python), replay = recording Python replay.", labelnames=("mode",))


def in_capture_mode() -> bool:
    return getattr(_capture, "active", 0) > 0


class _CaptureScope:
    def __enter__(self):
        _capture.active = getattr(_capture, "active", 0) + 1
        return self

    def __exit__(self, *exc):
        _capture.active -= 1
        return False


def _unwrap(obj):
    if isinstance(obj, Tensor):
        return obj._data
    if isinstance(obj, (list, tuple)):
        return type(obj)(_unwrap(o) for o in obj)
    if isinstance(obj, dict):
        return {k: _unwrap(v) for k, v in obj.items()}
    return obj


def _wrap(obj):
    if isinstance(obj, jax.Array):
        return Tensor(obj)
    if isinstance(obj, (list, tuple)):
        return type(obj)(_wrap(o) for o in obj)
    if isinstance(obj, dict):
        return {k: _wrap(v) for k, v in obj.items()}
    return obj


def _is_traced_leaf(x):
    return isinstance(x, (Tensor, jax.Array, np.ndarray))


class StaticFunction:
    """Callable wrapper holding the jit cache (reference:
    dy2static/program_translator.py:329 StaticFunction).

    Arguments are partitioned per call: Tensor/array leaves are traced, any
    other leaf (a Layer, a python scalar, a string attr) is static and keys
    the jit cache — the guard role of the reference's SOT guards."""

    def __init__(self, fn: Callable, input_spec=None, build_strategy=None,
                 backend=None, full_graph=True, mesh=None, in_specs=None,
                 param_specs=None, donate=False):
        self._dygraph_fn = fn
        self._input_spec = input_spec
        functools.update_wrapper(self, fn)
        self._jitted = None
        self._params = None
        # Buffer donation (async runtime): with donate=True the param /
        # buffer arrays are donated to the compiled program — XLA reuses
        # their HBM for the updated outputs (the bigger-batch headroom).
        # jit_target then returns EVERY param so the caller can rebind
        # the Tensors onto live buffers; the old buffers are registered
        # with core.donation so stale reads raise the framework's error.
        self._donate = bool(donate)
        # SPMD auto-sharding (distributed.spmd): when a mesh is given,
        # the trace runs under a propagation scope — inputs seed from
        # in_specs, params from their shard_params/_spmd_spec stamps
        # (or the param_specs callable), and every dispatched op's rule
        # annotates its outputs, so ONE fully-sharded XLA program comes
        # out of jit.
        if mesh is not None and hasattr(mesh, "jax_mesh"):
            mesh = mesh.jax_mesh()  # ProcessMesh -> jax Mesh
        self._spmd_mesh = mesh
        self._spmd_in_specs = in_specs
        self._spmd_param_specs = param_specs
        #: propagation stats of the most recent traced signature
        self.spmd_stats: Optional[dict] = None
        #: fusion-pass stats of the most recent traced signature
        #: (compile.fusion.rewrite_traced; None = fusion off / no trace)
        self.fusion_stats: Optional[dict] = None
        #: per-signature AOT runners — deserialized persistent-cache hits
        #: and locally AOT-compiled programs (persistent cache path)
        self._aot_sigs: dict = {}
        # SOT-style graph-break state (reference sot/translate.py: on
        # untraceable code, fall back and record why). full_graph=True
        # makes a break an error, like the reference's full_graph flag.
        self._full_graph = full_graph
        # break reasons keyed per dispatch signature (statics + array
        # shapes/dtypes) — one breaking signature must not disable jit for
        # signatures that trace fine (the reference SOT falls back
        # per-guard, not per-function). Bounded: a transient error must
        # not grow this without limit across many distinct shapes.
        self._graph_breaks: dict = {}
        self._graph_breaks_max = 256
        # SOT partial-frame capture state: per-signature compiled-segment
        # caches + stats of the most recent SOT run (see jit/sot).
        self._sot_caches: dict = {}
        #: per-signature frame journals for the steady-state bypass
        self._sot_frames: dict = {}
        self.sot_stats: Optional[dict] = None
        #: signatures already dispatched — a new one means trace+compile
        #: (telemetry only; jax's jit cache is the source of truth)
        self._seen_sigs: set = set()

    @property
    def graph_break_reason(self):
        """Why the most recent breaking signature fell back to eager
        (None = no signature has broken)."""
        if not self._graph_breaks:
            return None
        return next(reversed(self._graph_breaks.values()))

    def _collect_params(self, args):
        """Find Layer instances bound to the function (self for methods),
        including buffers (BN running stats) so trace-time set_value on them
        is threaded back out instead of leaking a tracer."""
        params = []
        owner = getattr(self._dygraph_fn, "__self__", None)
        if owner is not None and hasattr(owner, "parameters"):
            params.extend(owner.parameters())
            params.extend(b for _, b in owner.named_buffers())
        for a in args:
            if hasattr(a, "parameters") and hasattr(a, "named_buffers"):
                params.extend(a.parameters())
                params.extend(b for _, b in a.named_buffers())
        return params

    def _check_input_spec(self, args):
        """Validate Tensor args against the declared InputSpec list
        (reference: program_translator input_spec guard) — shape (-1 =
        any) and dtype must match."""
        if not self._input_spec:
            return
        tensors = [a for a in args if isinstance(a, Tensor)]
        for spec, t in zip(self._input_spec, tensors):
            shape = getattr(spec, "shape", None)
            if shape is None:
                continue
            if len(shape) != len(t.shape) or any(
                    s not in (-1, d) for s, d in zip(shape, t.shape)):
                raise ValueError(
                    f"input shape {t.shape} does not match input_spec "
                    f"{tuple(shape)}")
            sdt = str(getattr(spec, "dtype", ""))
            if sdt and sdt != str(t.dtype):
                raise ValueError(
                    f"input dtype {t.dtype} does not match input_spec "
                    f"{sdt}")

    def __call__(self, *args, **kwargs):
        if in_capture_mode():
            return self._dygraph_fn(*args, **kwargs)
        from . import sot as sot_mod
        if sot_mod.active():
            # called inside an outer SOT capture: the outer segment graph
            # records these ops; a fresh jit here would choke on the
            # LazyArray payloads
            return self._dygraph_fn(*args, **kwargs)
        self._check_input_spec(args)
        params = self._collect_params(args)
        fn = self._dygraph_fn
        if self._spmd_mesh is not None \
                and self._spmd_param_specs == "auto":
            self._auto_plan(args, kwargs)

        leaves, treedef = jax.tree_util.tree_flatten(
            (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor))
        arrays = [l._data if isinstance(l, Tensor) else jnp.asarray(l)
                  for l in leaves if _is_traced_leaf(l)]  # tpulint: disable=TPU105 — filters on leaf TYPE (isinstance), never a tensor value
        statics = tuple((i, l) for i, l in enumerate(leaves)
                        if not _is_traced_leaf(l))  # tpulint: disable=TPU105 — same type-level partition
        # graph fusion: the pass fingerprint rides the statics tuple, so
        # (a) jax.jit retraces when FLAGS_enable_fusion flips and (b) the
        # persistent-cache key (built over statics) separates fused from
        # unfused programs. Slot -1 is unreachable by the leaf rebuild in
        # jit_target (it iterates range(num_leaves)).
        from ..compile import fusion as _fusion
        if _fusion.enabled():
            statics = statics + ((-1, ("__fusion__",
                                       _fusion.fingerprint())),)


        # The live param binding: jit_target reads this at trace time, so a
        # call with a different layer (new static leaf -> retrace) rebinds
        # tracers onto THAT call's params rather than the first call's.
        self._params = params
        self._build_jitted(fn)
        donated_prev = None
        if self._donate:
            from ..core import donation as _donation
            site = f"to_static({self.__name__!r}, donate=True)"
            _donation.ensure_live((p._data for p in params),
                                  f"{site} entry")
            _donation.ensure_distinct(
                ((p.name, p._data) for p in params), site)
            donated_prev = [p._data for p in params]
        sig = (treedef, statics,
               tuple((tuple(a.shape), str(a.dtype)) for a in arrays))
        if sig in self._graph_breaks:  # tpulint: disable=TPU105 — sig holds treedef/statics/SHAPES (dispatch key), no tensor values
            return self._run_sot(sig, fn, args, kwargs)
        is_new_sig = sig not in self._seen_sigs
        runner = self._aot_sigs.get(sig)
        if runner is None and is_new_sig:  # tpulint: disable=TPU105 — branches on input SHAPES (the dispatch signature), not tensor values
            # persistent compilation cache: an already-seen signature
            # (this machine or a warmed fleet peer) skips trace+compile.
            # The goodput ledger bills the load wall as compile — a pcc
            # hit therefore bills near-zero vs a real compile
            with _goodput.bill("compile"):
                runner = self._pcc_load(sig, params)
            self._pcc_record_manifest(arrays)
        if runner is not None:
            self._seen_sigs.add(sig)   # known signature, nothing compiled
            out, mutated = runner([p._data for p in params], arrays)
            for i, arr in mutated.items():
                params[int(i)]._swap_payload(arr)  # tpulint: disable=TPU103 — i is the mutated-dict's STRING key (param index), not tensor data
            self._mark_donated(donated_prev)
            return _wrap(out)
        if is_new_sig:  # tpulint: disable=TPU105 — same shape-only branch
            self._record_new_sig(sig)
        try:
            if is_new_sig:  # tpulint: disable=TPU105 — same shape-only branch
                # first call of a new signature pays trace + XLA compile;
                # time it as the compile cost (per-subsystem span + metric)
                kind = "initial" if len(self._seen_sigs) == 1 else "retrace"
                with _trace.span(f"to_static_compile:{self.__name__}",
                                 "compile"), _goodput.bill("compile"):
                    c0 = time.perf_counter()
                    out, mutated = self._dispatch_new_sig(
                        sig, params, arrays, treedef, statics)
                c1 = time.perf_counter() - c0
                # retrace bursts are the sentinel's compile-storm signal
                _sentinel.get().note_compile(kind=kind, seconds=c1)
                if _metrics.enabled():
                    _m_compile_time.observe(c1, kind=kind)
            else:
                out, mutated = self._jitted(
                    [p._data for p in params], arrays, treedef, statics)
        except (jax.errors.TracerBoolConversionError,
                jax.errors.TracerArrayConversionError,
                jax.errors.TracerIntegerConversionError,
                jax.errors.ConcretizationTypeError,
                jax.errors.NonConcreteBooleanIndexError) as e:
            # graph break: data-dependent Python control flow (or a host
            # round-trip) inside the traced region. The reference SOT
            # falls back to eager for the breaking frame; our capture unit
            # is the whole function, so this SIGNATURE runs eagerly —
            # other signatures keep their compiled programs.
            reason = f"{type(e).__name__}: {str(e).splitlines()[0]}"
            if _metrics.enabled():
                _m_graph_break.inc(reason=type(e).__name__)
            # donation-hazard verdict (static.verifier): the break may
            # BE a host read of a donated param mid-step — the stale
            # read the runtime registry would only catch when the SOT
            # fallback executes it. strict raises here, before any
            # segment of the donated program compiles or runs.
            vsc = getattr(self, "_verifier_scope", None)
            if vsc is not None:
                vrep = vsc.donation_report()
                if vrep is not None:
                    from ..static import verifier as _verifier
                    _verifier.enforce(vrep)
            if self._full_graph:
                raise
            if len(self._graph_breaks) >= self._graph_breaks_max:
                evicted = next(iter(self._graph_breaks))
                self._graph_breaks.pop(evicted)
                # drop the compiled segments with the signature — they
                # hold XLA executables, far heavier than reason strings
                self._sot_caches.pop(evicted, None)
            self._graph_breaks[sig] = reason
            import warnings
            warnings.warn(
                f"to_static graph break in {self.__name__!r} — switching "
                f"to SOT partial-frame capture ({reason}): the op "
                f"sequences between breaks still compile as XLA "
                f"subgraphs. Use lax-style control flow (paddle.where / "
                f"static shapes) to capture the whole function.",
                stacklevel=2)
            return self._run_sot(sig, fn, args, kwargs)
        for i, arr in mutated.items():
            params[int(i)]._swap_payload(arr)  # tpulint: disable=TPU103 — same string-key int() as the runner path
        self._mark_donated(donated_prev)
        return _wrap(out)

    def _mark_donated(self, donated_prev):
        """Register the buffers a donating call just invalidated so a
        stale read raises core.donation.DonatedBufferError (the clear
        framework error), not XLA's opaque deleted-array failure."""
        if donated_prev is not None:
            from ..core import donation as _donation
            _donation.mark_donated(
                donated_prev, f"to_static({self.__name__!r}, donate=True)")

    def _build_jitted(self, fn):
        if self._jitted is not None:
            return
        outer = self

        def jit_target(param_arrays, array_leaves, treedef, statics):
            params = outer._params
            static_map = dict(statics)
            it = iter(array_leaves)
            full = [static_map[i] if i in static_map else next(it)
                    for i in range(treedef.num_leaves)]
            a, k = jax.tree_util.tree_unflatten(treedef, full)
            with _CaptureScope():
                originals = []
                for p, d in zip(params, param_arrays):
                    originals.append((p, p._data))
                    p._data = d
                vsc = getattr(outer, "_verifier_scope", None)
                if vsc is not None:
                    # params now hold the trace's argument tracers: a
                    # host read of one of THESE payloads during the
                    # trace is a donated-then-read hazard (TPU601);
                    # begin_trace also resets the record stream so a
                    # jax retrace of this target starts clean
                    vsc.begin_trace(params)
                try:
                    args_t = _wrap(a)
                    kwargs_t = _wrap(k)
                    if outer._spmd_mesh is not None:
                        out = outer._spmd_traced_call(fn, args_t,
                                                      kwargs_t, params)
                    else:
                        from ..compile import fusion as _fusion
                        out, outer.fusion_stats = _fusion.rewrite_traced(
                            lambda: fn(*args_t, **kwargs_t))
                    # Thread in-place updates (BatchNorm running stats
                    # via set_value) out of the trace so the caller can
                    # write them back. String keys: the mutated dict
                    # crosses jax.export serialization, which only
                    # accepts string dict keys in pytrees. Under
                    # donation EVERY param comes back — the input
                    # buffers are invalid after the call, so the caller
                    # must rebind all of them (unchanged params alias
                    # their donated input buffer: free).
                    mutated = {str(i): p._data
                               for i, (p, d) in enumerate(
                                   zip(params, param_arrays))
                               if outer._donate or p._data is not d}
                    if vsc is not None:
                        # verify the recorded op stream HERE — the
                        # trace is complete but nothing has lowered or
                        # compiled yet, so strict mode raises before
                        # XLA ever sees the program
                        vsc.finish()
                    return _unwrap(out), mutated
                finally:
                    for p, d in originals:
                        p._data = d

        self._jitted = jax.jit(
            jit_target, static_argnums=(2, 3),
            donate_argnums=(0,) if self._donate else ())

    def _auto_plan(self, args, kwargs):
        """param_specs="auto": run the auto-parallel planner
        (distributed.planner) on the first call's arguments — the
        function runs once eagerly to record its program, candidates
        are searched and cost-scored, and the winner's placements
        replace the "auto" marker before the first jit trace."""
        from ..distributed import planner as planner_mod

        owner = getattr(self._dygraph_fn, "__self__", None)
        model = owner if hasattr(owner, "named_parameters") else None
        res = planner_mod.plan(
            self._dygraph_fn, self._spmd_mesh,
            in_specs=self._spmd_in_specs,
            example_inputs=args, kwargs=dict(kwargs),
            model=model)
        #: PlanResult of the auto placement (report(), ranked table)
        self.placement_plan = res
        self._spmd_param_specs = res.param_specs
        if self._spmd_in_specs is None:
            self._spmd_in_specs = res.in_specs

    def _spmd_traced_call(self, fn, args_t, kwargs_t, params):
        """Run the traced body under a sharding-propagation scope
        (distributed.spmd.trace_scope): seed params + inputs, let every
        op's spmd_rule annotate its outputs inside the jaxpr."""
        from ..distributed import spmd as spmd_mod

        sc = spmd_mod.trace_scope(self._spmd_mesh)
        with sc:
            for p in params:
                spec = spmd_mod.param_spec_of(p, self._spmd_param_specs)
                if spec is not None:
                    # constrain=False: the param arrays are jit ARGUMENTS
                    # whose committed sharding already tells GSPMD the
                    # placement, and replacing p._data here would make
                    # jit_target's mutated-baseline comparison flag every
                    # sharded param as mutated (returned + swapped per
                    # call). Only the propagation env needs the spec.
                    sc.seed(p, spec, constrain=False)
            sc.seed_tree((args_t, kwargs_t), self._spmd_in_specs)
            # fusion runs INSIDE the propagation scope: the re-emitted
            # fused ops dispatch through the scope's recorder hook, so
            # their spmd_rules annotate the fused program's tracers
            from ..compile import fusion as _fusion
            out, self.fusion_stats = _fusion.rewrite_traced(
                lambda: fn(*args_t, **kwargs_t))
        self.spmd_stats = dict(sc.stats)
        return out

    def _spmd_fingerprint(self, params=()):
        """Persistent-cache key component: a program compiled under one
        mesh/spec configuration must never be served for another —
        including the PARAM placements (shard_params stamps /
        param_specs), which change the compiled executable's input
        shardings without touching mesh or in_specs."""
        if self._spmd_mesh is None:
            return []
        from ..distributed import spmd as spmd_mod
        mesh = self._spmd_mesh
        return [list(mesh.axis_names),
                [int(mesh.shape[a]) for a in mesh.axis_names],
                repr(self._spmd_in_specs),
                [repr(spmd_mod.param_spec_of(p, self._spmd_param_specs))
                 for p in params]]

    # ------------------------------------------------ persistent cache
    def _pcc_key(self, sig, params):
        """Cache key for one dispatch signature: function identity +
        closure/owner guards + the full signature + param avals, folded
        with the toolchain/topology/FLAGS fingerprint (compile/)."""
        from .. import compile as pcc
        from . import sot as sot_mod
        treedef, statics, shapes = sig
        fn = self._dygraph_fn
        return pcc.key_of(
            "to_static",
            f"{getattr(fn, '__module__', '')}:"
            f"{getattr(fn, '__qualname__', '')}",
            # code CONTENT, not file:line — editing the body in place
            # must invalidate the entry, not stale-hit it
            pcc.code_fingerprint(fn),
            self._frame_guard(fn),
            repr(treedef),
            [[i, sot_mod._const_repr(v, 2)] for i, v in statics],
            [list(map(list, shapes))],
            # spmd fingerprint only when a mesh is set: appending the
            # empty list for plain functions would re-key (and so
            # invalidate) every previously persisted cache entry
            *([self._spmd_fingerprint(params)]
              if self._spmd_mesh is not None else []),
            # donation re-keys the same way: a donated executable's
            # input-output aliasing is part of the compiled artifact, so
            # donated and undonated programs must never cross-hit
            *([["__donate__"]] if self._donate else []))

    def _pcc_load(self, sig, params):
        """Look the signature up in the persistent cache; a hit returns a
        runner (params, arrays) -> (out, mutated) and skips trace+compile
        entirely. Any cache-layer problem is a miss, never an error."""
        try:
            from .. import compile as pcc
            if not pcc.enabled():
                return None
            got = pcc.get_cache().get(self._pcc_key(sig, params),
                                      site="to_static")
            if got is None:
                return None
            meta, payload = got
            # the donate fingerprint in the key already separates the
            # programs; the meta check is belt-and-braces — an executable
            # whose aliasing disagrees with this wrapper must not run
            if bool(meta.get("donate", False)) != self._donate:
                return None
            runner = pcc.aot.load_runner(meta.get("tier", ""), payload)
            if runner is None:
                return None
            pcc.record_time_saved(meta.get("compile_seconds", 0.0))
            self._aot_sigs[sig] = runner
            return runner
        except Exception:
            return None

    def _pcc_record_manifest(self, arrays):
        try:
            from .. import compile as pcc
            pcc.record_to_static(self._dygraph_fn, arrays)
        except Exception:
            pass

    def _aot_compile(self, sig, param_arrays, arrays, treedef, statics):
        """Shared AOT path: lower+compile one signature, capture its
        cost/memory analysis when FLAGS_perf_capture is on, install the
        per-signature runner. Returns (runner, compiled, seconds)."""
        from ..observability import perf as _perf

        c0 = time.perf_counter()
        compiled = self._jitted.lower(param_arrays, arrays, treedef,
                                      statics).compile()
        compile_seconds = time.perf_counter() - c0
        if _perf.capture_enabled():
            _perf.record_compiled(
                "to_static", getattr(self, "__name__", "<fn>"), compiled)

        def runner(pa, ar, _c=compiled):
            return _c(pa, ar)

        self._aot_sigs[sig] = runner
        return runner, compiled, compile_seconds

    def _dispatch_new_sig(self, sig, params, arrays, treedef, statics):
        """First dispatch of a signature. With the persistent cache off,
        the plain jit path; with it on, AOT lower+compile so the
        executable can be serialized and published for other processes.
        With FLAGS_perf_capture on, the AOT route is taken either way so
        the compiled program's cost/memory analysis can be captured.

        The program verifier rides the first-compile trace: a
        static.verifier.trace_scope records the dispatched op stream
        (and, under donation, host reads of donated params) and the
        contract/collective passes run before any result is returned —
        FLAGS_verify_programs=strict raises the framework's error
        naming the op + source line before XLA sees the program."""
        from ..observability import perf as _perf
        from ..static import verifier as _verifier

        self._verifier_scope = None
        if _verifier.mode() != "off":
            self._verifier_scope = _verifier.trace_scope(
                label=f"to_static({getattr(self, '__name__', '<fn>')!r})",
                donate=self._donate)

        def _inner():
            param_arrays = [p._data for p in params]
            try:
                from .. import compile as pcc
                use_pcc = pcc.enabled()
            except Exception:
                use_pcc = False
            if not use_pcc:
                if _perf.capture_enabled():
                    runner, _c, _s = self._aot_compile(
                        sig, param_arrays, arrays, treedef, statics)
                    return runner(param_arrays, arrays)
                return self._jitted(param_arrays, arrays, treedef,
                                    statics)
            runner = self._pcc_store(sig, params, arrays, treedef,
                                     statics)
            return runner(param_arrays, arrays)

        if self._verifier_scope is None:
            return _inner()
        # the scope only registers/unregisters the recorder hook here;
        # jit_target itself calls begin_trace/finish so the verdict
        # lands at end-of-trace, BEFORE lowering + XLA compile
        with self._verifier_scope:
            return _inner()

    def _pcc_store(self, sig, params, arrays, treedef, statics):
        """AOT-compile one signature, publish it, return its runner.
        ``arrays`` may be abstract (ShapeDtypeStructs) — the warmup path
        compiles and publishes without executing anything."""
        from .. import compile as pcc
        param_arrays = [p._data for p in params]
        runner, compiled, compile_seconds = self._aot_compile(
            sig, param_arrays, arrays, treedef, statics)
        try:
            ser = pcc.aot.serialize_compiled(compiled)
            if ser is None:
                # backend cannot serialize executables: fall back to the
                # exported-StableHLO tier (a hit still skips trace+lower)
                from jax import export as jax_export
                p_avals = [jax.ShapeDtypeStruct(tuple(a.shape), a.dtype)
                           for a in param_arrays]
                a_avals = [jax.ShapeDtypeStruct(tuple(a.shape), a.dtype)
                           for a in arrays]
                exported = jax_export.export(self._jitted)(
                    p_avals, a_avals, treedef, statics)
                ser = pcc.aot.serialize_exported(exported)
            if ser is not None:
                tier, payload = ser
                pcc.get_cache().put(
                    self._pcc_key(sig, params), payload,
                    {"site": "to_static", "tier": tier,
                     "label": getattr(self, "__name__", ""),
                     "donate": self._donate,
                     "compile_seconds": compile_seconds})
        except Exception:
            pass
        return runner

    def precompile(self, input_spec=None):
        """AOT warmup: compile (and publish to the persistent cache) the
        signature described by ``input_spec`` — a list of InputSpec /
        Tensors / (shape, dtype)-shaped arrays — WITHOUT executing it.
        All entries must have concrete shapes; serving warmup runs over
        the recorded shape manifest, not symbolic dims."""
        specs = list(input_spec if input_spec is not None
                     else self._input_spec or [])
        if not specs:
            raise ValueError(
                "precompile needs input_spec (InputSpec/Tensor/array "
                "examples) to describe the signature")
        avals = _example_arrays(specs)
        if any(not all(isinstance(d, int) for d in a.shape)
               for a in avals):
            raise ValueError(
                "precompile needs concrete shapes (no -1 dims) — warm "
                "from a recorded shape-signature manifest")
        params = self._collect_params(())
        self._params = params
        self._build_jitted(self._dygraph_fn)
        leaves_tree = jax.tree_util.tree_structure(
            (tuple(avals), {}))
        sig = (leaves_tree, (),
               tuple((tuple(a.shape), str(a.dtype)) for a in avals))
        if sig in self._aot_sigs:  # tpulint: disable=TPU105 — precompile sig is (treedef, shapes) over ABSTRACT avals
            return
        if self._pcc_load(sig, params) is not None:
            self._seen_sigs.add(sig)
            return
        self._pcc_store(sig, params, avals, leaves_tree, ())
        self._seen_sigs.add(sig)

    def _record_new_sig(self, sig):
        """Telemetry for a signature's first dispatch: initial build vs
        retrace, with the retrace classified against prior signatures."""
        treedef, statics, shapes = sig
        if _metrics.enabled():
            if not self._seen_sigs:
                _m_compile.inc(kind="initial")
            else:
                _m_compile.inc(kind="retrace")
                reason = "new_structure"
                for ptd, pst, _psh in self._seen_sigs:
                    if ptd == treedef and pst == statics:
                        reason = "new_input_shapes"
                        break
                    if ptd == treedef:
                        reason = "new_static_args"
                _m_retrace_reason.inc(reason=reason)
        self._seen_sigs.add(sig)

    def _frame_guard(self, fn):
        """Frame-level guard string: the closure/default values the frame
        itself can reach (the op-level fingerprints that the bypass skips
        are derived from this state plus the journaled attrs)."""
        from . import sot as sot_mod
        g = sot_mod.fn_fingerprint(fn, depth=2)
        owner = getattr(fn, "__self__", None)
        if owner is not None:
            g += "#" + sot_mod._const_repr(owner, 1)
        return g

    def _run_sot(self, sig, fn, args, kwargs):
        """Partial-frame capture for a signature that cannot full-graph
        trace (reference jit/sot/translate.py contract): ops before each
        concretization point compile as one cached XLA subgraph, the break
        runs eagerly, capture resumes after.

        Steady state (reference symbolic/compile_cache.py guard-hit path):
        once two consecutive replays journal the SAME segment DAG, later
        calls check one frame-level guard and execute the stitched
        compiled segments directly — zero per-op Python work. Any guard
        miss or journal mismatch drops back to a recording replay.
        """
        import jax as _jax

        from ..core.tensor import Tensor as _T
        from . import sot as sot_mod
        if sot_mod.active():
            # nested break inside an outer SOT capture: the outer segment
            # machinery already records these ops — just run the frame
            return fn(*args, **kwargs)
        cache = self._sot_caches.setdefault(sig, {})
        state = self._sot_frames.setdefault(
            sig, {"journal": None, "stable": False, "guard": None})

        leaves, _ = _jax.tree_util.tree_flatten(
            (args, kwargs), is_leaf=lambda x: isinstance(x, _T))
        input_arrays = [l._data for l in leaves if isinstance(l, _T)]
        # raw ndarray / jax.Array args are re-materialized per call, so
        # the journal cannot track their provenance (they would be frozen
        # as first-call constants) — such frames stay on Python replay
        trackable = all(isinstance(l, _T) or not _is_traced_leaf(l)
                        for l in leaves)
        params = self._params or []
        guard = self._frame_guard(fn)

        journal = state["journal"]
        if (state["stable"] and journal is not None and journal.eligible
                and state["guard"] == guard):
            ok, packed, why = sot_mod.replay_frame(
                journal, cache, input_arrays, params)
            if ok:  # tpulint: disable=TPU105 — ok is replay_frame's python bool (guard-hit status), not a tensor
                treedef, out_leaves = packed
                rebuilt = [
                    _T(arr, stop_gradient=wrap[1]) if wrap is not None
                    else arr
                    for arr, wrap in out_leaves]
                self.sot_stats = {"segments": len(journal.segments),
                                  "compiled": 0, "bypassed": True}
                if _metrics.enabled():
                    _m_sot_frame.inc(mode="bypass")
                return _jax.tree_util.tree_unflatten(treedef, rebuilt)
            # guard missed: demote to recording replay
            state["stable"] = False
            state["journal"] = None

        new_journal = sot_mod.FrameJournal()
        if not trackable:  # tpulint: disable=TPU105 — trackable comes from isinstance checks over leaf types
            new_journal.mark_ineligible("non-Tensor array input")
        cap = sot_mod.capture(cache, journal=new_journal,
                              input_arrays=input_arrays, params=params)
        with cap:
            out = fn(*args, **kwargs)
        out_leaves, out_treedef = _jax.tree_util.tree_flatten(
            out, is_leaf=lambda x: isinstance(x, _T))
        cap.finalize_journal(out_leaves, out_treedef)
        prev = state["journal"]
        state["stable"] = bool(
            new_journal.eligible and prev is not None and prev.eligible
            and state["guard"] == guard
            and prev.structure_key() == new_journal.structure_key()
            and new_journal.segments)
        state["journal"] = new_journal if new_journal.eligible else None
        state["guard"] = guard
        self.sot_stats = dict(cap.stats)
        self.sot_stats["bypassed"] = False
        if _metrics.enabled():
            _m_sot_frame.inc(mode="replay")
        return out

    @property
    def code(self):
        import inspect
        return inspect.getsource(self._dygraph_fn)

    def concrete_program(self):
        return None


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, full_graph=False, mesh=None, in_specs=None,
              param_specs=None, donate=False):
    """Program capture; with ``mesh=`` the capture auto-shards — see
    distributed.spmd (``in_specs``: PartitionSpec pytree for the Tensor
    arguments; ``param_specs``: optional ``fn(param) -> spec``,
    defaulting to each param's spmd.shard_params placement — or the
    string ``"auto"`` to let the auto-parallel planner
    (distributed.planner) search and emit the placement on the first
    call). ``donate=True`` donates the param/buffer arrays to the
    compiled program (XLA reuses their HBM for the updated outputs, the
    train-step memory win); the wrapper rebinds every Parameter onto the
    returned buffers, and stale references to pre-call buffers raise
    ``core.donation.DonatedBufferError``."""
    def decorate(fn):
        if hasattr(fn, "forward") and callable(getattr(fn, "forward")):
            # Layer instance: wrap its forward
            layer = fn
            layer.forward = StaticFunction(layer.forward, input_spec,
                                           build_strategy, backend,
                                           full_graph, mesh=mesh,
                                           in_specs=in_specs,
                                           param_specs=param_specs,
                                           donate=donate)
            return layer
        return StaticFunction(fn, input_spec, build_strategy, backend,
                              full_graph, mesh=mesh, in_specs=in_specs,
                              param_specs=param_specs, donate=donate)
    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(fn):
    fn._not_to_static = True
    return fn


def ignore_module(modules):
    return None


def donating_jit(fn, donate_argnums=(), context="donating_jit"):
    """``jax.jit`` with buffer donation plus host-side bookkeeping.

    The pipeline runtime's per-stage backward consumes its saved
    activations and incoming gradients exactly once — donating them
    lets XLA reuse the buffers in place (double buffering without a
    second allocation). After each call the donated argument leaves are
    registered with ``core.donation`` so a stale host read raises the
    framework's ``DonatedBufferError`` instead of XLA's opaque
    deleted-array failure (same contract as ``to_static(donate=True)``).
    On backends where donation is unimplemented (CPU) the call still
    works; XLA's "donated buffers were not usable" noise is filtered.
    """
    import warnings

    dn = tuple(int(i) for i in donate_argnums)
    jitted = jax.jit(fn, donate_argnums=dn) if dn else jax.jit(fn)

    @functools.wraps(fn)
    def call(*args):
        with warnings.catch_warnings():
            warnings.filterwarnings(
                "ignore", message=".*donated buffers were not usable.*")
            out = jitted(*args)
        if dn:
            from ..core import donation as _donation
            leaves = []
            for i in dn:
                if i < len(args):
                    leaves.extend(jax.tree_util.tree_leaves(args[i]))
            _donation.mark_donated(leaves, context)
        return out

    call._jitted = jitted
    return call


def _example_arrays(input_spec):
    """InputSpec / Tensor / ndarray entries -> jax abstract values. A -1
    dim becomes a symbolic dimension so the saved program serves any size
    on that axis."""
    from jax import export as jax_export

    avals = []
    # ONE symbolic scope shared by every spec (jax.export rejects mixed
    # scopes). A -1 at axis i is named d<i> in that scope, so the same
    # axis of different inputs shares one symbol — inputs with dynamic
    # batch dims stay broadcast-compatible (the reference's -1 contract).
    scope = jax_export.SymbolicScope()
    for spec in input_spec:
        if isinstance(spec, Tensor):
            avals.append(jax.ShapeDtypeStruct(tuple(spec.shape),
                                              spec._data.dtype))
            continue
        if isinstance(spec, (np.ndarray, jax.Array)):
            avals.append(jax.ShapeDtypeStruct(spec.shape, spec.dtype))
            continue
        shape = tuple(spec.shape)
        if any(s == -1 for s in shape):
            parts = [f"d{i}" if s == -1 else str(s)
                     for i, s in enumerate(shape)]
            shape = jax_export.symbolic_shape(f"({','.join(parts)})",
                                              scope=scope)
        dtype = jnp.bfloat16 if str(spec.dtype) == "bfloat16" \
            else np.dtype(spec.dtype)
        avals.append(jax.ShapeDtypeStruct(shape, dtype))
    return avals


def save(layer, path, input_spec=None, **configs):
    """Serialize the traced program (StableHLO via jax.export) + params
    (reference: python/paddle/jit/api.py save → .pdmodel/.pdiparams;
    jit.load returns a TranslatedLayer that executes WITHOUT the Python
    model class). Artifacts: ``path.pdmodel`` (program + calling
    convention) and ``path.pdparams`` (weights)."""
    import pickle

    from jax import export as jax_export

    from ..framework.io import save as _save

    fn = layer.forward if hasattr(layer, "forward") else layer
    if isinstance(fn, StaticFunction):
        if input_spec is None:
            input_spec = fn._input_spec
        fn = fn._dygraph_fn
    if input_spec is None:
        raise ValueError(
            "jit.save needs input_spec (list of InputSpec / example "
            "tensors) to trace the program")

    state = layer.state_dict() if hasattr(layer, "state_dict") else {}
    param_arrays = {k: (v._data if isinstance(v, Tensor) else jnp.asarray(v))
                    for k, v in state.items()}
    name_to_param = {}
    if hasattr(layer, "named_parameters"):
        name_to_param.update(dict(layer.named_parameters()))
    if hasattr(layer, "named_buffers"):
        name_to_param.update(dict(layer.named_buffers()))

    was_training = getattr(layer, "training", False)
    if hasattr(layer, "eval"):
        layer.eval()
    try:
        def pure(params, *xs):
            originals = []
            for k, t in name_to_param.items():
                originals.append((t, t._data))
                if k in params:
                    t._data = params[k]
            try:
                out = fn(*_wrap(list(xs)))
                return _unwrap(out)
            finally:
                for t, d in originals:
                    t._data = d

        avals = _example_arrays(list(input_spec))
        exported = jax_export.export(jax.jit(pure))(param_arrays, *avals)
    finally:
        if was_training and hasattr(layer, "train"):
            layer.train()

    import jaxlib

    with open(path + ".pdmodel", "wb") as f:
        # version-stamped v2 blob: load() turns a deserialize failure on
        # a version-skewed artifact into a clear ArtifactVersionError
        pickle.dump({"format": "paddle_tpu.jit/2",
                     "n_inputs": len(list(input_spec)),
                     "stablehlo": exported.serialize(),
                     "jax_version": jax.__version__,
                     "jaxlib_version": jaxlib.__version__,
                     "platform": jax.devices()[0].platform}, f)
    _save(state, path + ".pdparams")


class ArtifactVersionError(RuntimeError):
    """A ``jit.save`` artifact was produced by an incompatible toolchain
    (jax/jaxlib/backend skew). Raised by ``jit.load`` instead of an
    opaque deserialize failure; the fix is re-exporting the artifact
    with the current toolchain."""


class TranslatedLayer:
    """A loaded program: callable without the original model class
    (reference: python/paddle/jit/translated_layer.py TranslatedLayer).

    With ``FLAGS_compile_cache=1`` each input-shape signature is AOT
    compiled once and the executable published to the persistent cache
    keyed by the artifact's serialized-StableHLO digest — a warmed
    serving replica's first request deserializes instead of compiling."""

    def __init__(self, exported, state, n_inputs: int = 1,
                 program_digest: Optional[str] = None,
                 artifact_path: Optional[str] = None):
        self._exported = exported
        self._state = state
        self.n_inputs = n_inputs
        self._param_arrays = {
            k: (v._data if isinstance(v, Tensor) else jnp.asarray(v))
            for k, v in state.items()}
        self.training = False
        self._program_digest = program_digest
        self._artifact_path = artifact_path
        self._aot: dict = {}

    def __call__(self, *inputs):
        arrays = [i._data if isinstance(i, Tensor) else jnp.asarray(i)
                  for i in inputs]
        # only the CACHE machinery is guarded — once a runner exists it
        # executes unguarded, so a genuine runtime failure (OOM, shape
        # error) surfaces once instead of being swallowed and re-run
        runner = None
        try:
            from .. import compile as pcc
            if self._artifact_path:
                pcc.record_artifact(self._artifact_path, arrays)
            if pcc.enabled() and self._program_digest:
                runner = self._runner_for(arrays, pcc)
        except Exception:
            runner = None
        if runner is not None:
            return _wrap(runner(self._param_arrays, *arrays))
        out = self._exported.call(self._param_arrays, *arrays)
        return _wrap(out)

    forward = __call__

    # ------------------------------------------------ persistent cache
    def _runner_for(self, arrays, pcc):
        """Per-shape-signature compiled program: persistent-cache hit or
        AOT compile + publish (content-addressed by the artifact's
        StableHLO digest + input avals + toolchain/topology)."""
        avsig = tuple((tuple(a.shape), str(a.dtype)) for a in arrays)
        runner = self._aot.get(avsig)
        if runner is not None:
            return runner
        key = pcc.key_of("artifact", self._program_digest,
                         [list(map(list, avsig))])
        got = pcc.get_cache().get(key, site="artifact")
        if got is not None:
            meta, payload = got
            runner = pcc.aot.load_runner(meta.get("tier", ""), payload)
            if runner is not None:
                pcc.record_time_saved(meta.get("compile_seconds", 0.0))
                self._aot[avsig] = runner
                return runner
        c0 = time.perf_counter()
        compiled = jax.jit(self._exported.call).lower(
            self._param_arrays, *arrays).compile()
        compile_seconds = time.perf_counter() - c0

        def runner(pa, *ar, _c=compiled):
            return _c(pa, *ar)

        self._aot[avsig] = runner
        ser = pcc.aot.serialize_compiled(compiled)
        if ser is not None:
            tier, payload = ser
            pcc.get_cache().put(
                key, payload,
                {"site": "artifact", "tier": tier,
                 "label": self._artifact_path or "",
                 "compile_seconds": compile_seconds})
        return runner

    def precompile(self, input_spec):
        """AOT warmup: compile + publish this artifact's program for the
        given input shapes without executing it."""
        from .. import compile as pcc
        avals = _example_arrays(list(input_spec))
        self._runner_for(avals, pcc)

    def state_dict(self):
        return dict(self._state)

    def set_state_dict(self, state):
        for k, v in state.items():
            if k in self._state:
                self._state[k] = v if isinstance(v, Tensor) else Tensor(
                    jnp.asarray(v))
        self._param_arrays = {
            k: (v._data if isinstance(v, Tensor) else jnp.asarray(v))
            for k, v in self._state.items()}

    def eval(self):
        self.training = False
        return self

    def train(self):
        raise RuntimeError(
            "TranslatedLayer holds an inference program; retraining "
            "requires the original model class (reference parity)")


def load(path, **configs):
    """Load a saved program as a TranslatedLayer; falls back to a raw
    state-dict when only params were saved."""
    import os
    import pickle

    from jax import export as jax_export

    from ..framework.io import load as _load

    state = _load(path + ".pdparams")
    model_file = path + ".pdmodel"
    if not os.path.exists(model_file):
        return state
    with open(model_file, "rb") as f:
        blob = pickle.load(f)
    fmt = str(blob.get("format", ""))
    if not fmt.startswith("paddle_tpu.jit/"):
        raise ArtifactVersionError(
            f"{model_file!r} is not a paddle_tpu.jit artifact "
            f"(format={fmt!r}) — re-export it with jit.save")
    try:
        exported = jax_export.deserialize(blob["stablehlo"])
    except Exception as e:
        import jaxlib
        saved_jax = blob.get("jax_version")
        saved_jaxlib = blob.get("jaxlib_version")
        if (saved_jax, saved_jaxlib) != (jax.__version__,
                                         jaxlib.__version__):
            raise ArtifactVersionError(
                f"cannot load {model_file!r}: artifact was exported with "
                f"jax {saved_jax or '<unstamped v1 artifact>'} / jaxlib "
                f"{saved_jaxlib or '?'} on "
                f"{blob.get('platform', '?')}, this runtime is jax "
                f"{jax.__version__} / jaxlib {jaxlib.__version__}. "
                f"Re-export the artifact with jit.save on the current "
                f"toolchain.") from e
        raise
    try:
        import hashlib
        digest = hashlib.sha256(bytes(blob["stablehlo"])).hexdigest()
    except Exception:
        digest = None
    return TranslatedLayer(exported, state,
                           n_inputs=int(blob.get("n_inputs", 1)),
                           program_digest=digest, artifact_path=path)
