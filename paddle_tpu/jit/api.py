"""Program capture (to_static) — trace-based v0.

Reference: python/paddle/jit/api.py to_static:173 + dy2static/sot capture
frontends. TPU-native design: instead of transpiling Python to a Program IR,
`to_static` jits the wrapped callable with jax — the dispatcher runs under
tracing (payloads become tracers), the autograd tape records as usual, and
XLA compiles the whole step. Guards = jax's shape/dtype dispatch cache.

This v0 supports function capture with static control flow. Graph-break
fallback and bytecode-level capture (SOT) land on top of this API.
"""
from __future__ import annotations

import functools
import threading
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor

_capture = threading.local()


def in_capture_mode() -> bool:
    return getattr(_capture, "active", 0) > 0


class _CaptureScope:
    def __enter__(self):
        _capture.active = getattr(_capture, "active", 0) + 1
        return self

    def __exit__(self, *exc):
        _capture.active -= 1
        return False


def _unwrap(obj):
    if isinstance(obj, Tensor):
        return obj._data
    if isinstance(obj, (list, tuple)):
        return type(obj)(_unwrap(o) for o in obj)
    if isinstance(obj, dict):
        return {k: _unwrap(v) for k, v in obj.items()}
    return obj


def _wrap(obj):
    if isinstance(obj, jax.Array):
        return Tensor(obj)
    if isinstance(obj, (list, tuple)):
        return type(obj)(_wrap(o) for o in obj)
    if isinstance(obj, dict):
        return {k: _wrap(v) for k, v in obj.items()}
    return obj


def _is_traced_leaf(x):
    return isinstance(x, (Tensor, jax.Array, np.ndarray))


class StaticFunction:
    """Callable wrapper holding the jit cache (reference:
    dy2static/program_translator.py:329 StaticFunction).

    Arguments are partitioned per call: Tensor/array leaves are traced, any
    other leaf (a Layer, a python scalar, a string attr) is static and keys
    the jit cache — the guard role of the reference's SOT guards."""

    def __init__(self, fn: Callable, input_spec=None, build_strategy=None,
                 backend=None, full_graph=True):
        self._dygraph_fn = fn
        self._input_spec = input_spec
        functools.update_wrapper(self, fn)
        self._jitted = None
        self._params = None

    def _collect_params(self, args):
        """Find Layer instances bound to the function (self for methods),
        including buffers (BN running stats) so trace-time set_value on them
        is threaded back out instead of leaking a tracer."""
        params = []
        owner = getattr(self._dygraph_fn, "__self__", None)
        if owner is not None and hasattr(owner, "parameters"):
            params.extend(owner.parameters())
            params.extend(b for _, b in owner.named_buffers())
        for a in args:
            if hasattr(a, "parameters") and hasattr(a, "named_buffers"):
                params.extend(a.parameters())
                params.extend(b for _, b in a.named_buffers())
        return params

    def __call__(self, *args, **kwargs):
        if in_capture_mode():
            return self._dygraph_fn(*args, **kwargs)
        params = self._collect_params(args)
        fn = self._dygraph_fn

        leaves, treedef = jax.tree_util.tree_flatten(
            (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor))
        arrays = [l._data if isinstance(l, Tensor) else jnp.asarray(l)
                  for l in leaves if _is_traced_leaf(l)]
        statics = tuple((i, l) for i, l in enumerate(leaves)
                        if not _is_traced_leaf(l))

        # The live param binding: jit_target reads this at trace time, so a
        # call with a different layer (new static leaf -> retrace) rebinds
        # tracers onto THAT call's params rather than the first call's.
        self._params = params
        if self._jitted is None:
            outer = self

            def jit_target(param_arrays, array_leaves, treedef, statics):
                params = outer._params
                static_map = dict(statics)
                it = iter(array_leaves)
                full = [static_map[i] if i in static_map else next(it)
                        for i in range(treedef.num_leaves)]
                a, k = jax.tree_util.tree_unflatten(treedef, full)
                with _CaptureScope():
                    originals = []
                    for p, d in zip(params, param_arrays):
                        originals.append((p, p._data))
                        p._data = d
                    try:
                        args_t = _wrap(a)
                        kwargs_t = _wrap(k)
                        out = fn(*args_t, **kwargs_t)
                        # Thread in-place updates (BatchNorm running stats
                        # via set_value) out of the trace so the caller can
                        # write them back.
                        mutated = {i: p._data
                                   for i, (p, d) in enumerate(
                                       zip(params, param_arrays))
                                   if p._data is not d}
                        return _unwrap(out), mutated
                    finally:
                        for p, d in originals:
                            p._data = d

            self._jitted = jax.jit(jit_target,
                                   static_argnums=(2, 3))
        out, mutated = self._jitted([p._data for p in params], arrays,
                                    treedef, statics)
        for i, arr in mutated.items():
            params[i]._swap_payload(arr)
        return _wrap(out)

    @property
    def code(self):
        import inspect
        return inspect.getsource(self._dygraph_fn)

    def concrete_program(self):
        return None


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, full_graph=True):
    def decorate(fn):
        if hasattr(fn, "forward") and callable(getattr(fn, "forward")):
            # Layer instance: wrap its forward
            layer = fn
            layer.forward = StaticFunction(layer.forward, input_spec,
                                           build_strategy, backend, full_graph)
            return layer
        return StaticFunction(fn, input_spec, build_strategy, backend,
                              full_graph)
    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(fn):
    fn._not_to_static = True
    return fn


def ignore_module(modules):
    return None


def save(layer, path, input_spec=None, **configs):
    """Save params + (optionally) the traced program (reference:
    python/paddle/jit/api.py save). v0 persists the state_dict; exported
    StableHLO lands with the inference-export milestone."""
    from ..framework.io import save as _save
    state = layer.state_dict() if hasattr(layer, "state_dict") else layer
    _save(state, path + ".pdparams")


def load(path, **configs):
    from ..framework.io import load as _load
    return _load(path + ".pdparams")
