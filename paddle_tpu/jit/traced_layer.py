"""TracedLayer — legacy trace-then-run API.

Reference: ``python/paddle/jit/dy2static/program_translator.py`` /
``python/paddle/base/dygraph/jit.py`` ``TracedLayer``:
``TracedLayer.trace(layer, inputs)`` returns the eager outputs plus a
traced module that replays the captured program;
``save_inference_model`` exports the deployable artifact.

TPU-native: the trace IS ``jit.to_static`` capture — one jitted XLA
program specialized to the example shapes; ``save_inference_model``
routes to ``jit.save`` (StableHLO + params), loadable by the Predictor
and ``jit.load``.
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

__all__ = ["TracedLayer"]


class TracedLayer:
    def __init__(self, static_fn, layer, example_inputs):
        self._fn = static_fn
        self._layer = layer
        self._example = list(example_inputs)

    @staticmethod
    def trace(layer, inputs: Sequence) -> Tuple[object, "TracedLayer"]:
        """Run ``layer`` on ``inputs`` eagerly (the returned outputs) and
        capture a compiled replay specialized to their shapes."""
        from .api import to_static

        inputs = list(inputs)
        dygraph_out = layer(*inputs)
        static_fn = to_static(lambda *xs: layer(*xs))
        return dygraph_out, TracedLayer(static_fn, layer, inputs)

    def __call__(self, inputs: Sequence):
        return self._fn(*inputs)

    def set_strategy(self, build_strategy=None, exec_strategy=None):
        """Accepted for parity; XLA owns build/exec strategy here."""

    def save_inference_model(self, path: str, feed: List[int] = None,
                             fetch: List[int] = None, **kwargs):
        """Export the traced program (reference save_inference_model).
        ``fetch`` selects output indices of a multi-output trace;
        ``feed`` index filtering (constant-folding dropped inputs) has
        no XLA-artifact equivalent and is rejected rather than ignored.
        """
        from .api import save
        from ..static import InputSpec

        if feed is not None:
            raise NotImplementedError(
                "save_inference_model(feed=...): input filtering is not "
                "supported for StableHLO artifacts — export with the "
                "full input list")
        spec = [InputSpec.from_tensor(t) if hasattr(t, "shape") else t
                for t in self._example]
        layer = self._layer
        if fetch is not None:
            layer = _FetchFilter(layer, list(fetch))
        save(layer, path, input_spec=spec, **kwargs)
        return path


class _FetchFilter:
    """Output-index selection wrapper for multi-output traces."""

    def __init__(self, layer, fetch):
        self._layer = layer
        self._fetch = fetch

    def __getattr__(self, name):
        return getattr(self._layer, name)

    def forward(self, *xs, **kw):
        # explicit (not delegated): jit.save captures layer.forward
        out = self._layer(*xs, **kw)
        if not isinstance(out, (tuple, list)):
            out = (out,)
        picked = [out[i] for i in self._fetch]
        return picked[0] if len(picked) == 1 else tuple(picked)

    __call__ = forward
