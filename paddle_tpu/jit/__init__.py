"""paddle_tpu.jit — program capture & compiled execution.

Reference: python/paddle/jit/ (to_static, save/load, SOT). The trace-based
capture engine lands in api.py; SOT-style bytecode capture is tracked in
sot/ (reference python/paddle/jit/sot/).
"""
from .api import to_static, not_to_static, in_capture_mode, ignore_module
from .api import donating_jit
from .api import save, load, TranslatedLayer, ArtifactVersionError
from .traced_layer import TracedLayer
