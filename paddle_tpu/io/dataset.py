"""Datasets (reference: python/paddle/io/dataloader/dataset.py)."""
from __future__ import annotations

import bisect

import numpy as np


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        lens = {t.shape[0] for t in tensors}
        if len(lens) != 1:
            raise ValueError("all tensors must have the same first dim")
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        if not self.datasets:
            raise ValueError("datasets must not be empty")
        n = len(self.datasets[0])
        for d in self.datasets:
            if len(d) != n:
                raise ValueError("all datasets must have the same length")

    def __len__(self):
        return len(self.datasets[0])

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            sample = d[idx]
            if isinstance(sample, (list, tuple)):
                out.extend(sample)
            else:
                out.append(sample)
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cumulative_sizes = np.cumsum([len(d) for d in self.datasets]).tolist()

    def __len__(self):
        return self.cumulative_sizes[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        ds_idx = bisect.bisect_right(self.cumulative_sizes, idx)
        offset = idx - (self.cumulative_sizes[ds_idx - 1] if ds_idx else 0)
        return self.datasets[ds_idx][offset]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    if sum(lengths) != len(dataset):
        raise ValueError("sum of lengths must equal dataset length")
    perm = np.random.permutation(len(dataset)).tolist()
    out, offset = [], 0
    for n in lengths:
        out.append(Subset(dataset, perm[offset:offset + n]))
        offset += n
    return out
