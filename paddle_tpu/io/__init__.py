"""paddle_tpu.io — Dataset/DataLoader.

Reference: python/paddle/io/ (dataloader with multiprocess prefetch,
dataloader_iter.py:365). TPU-native notes: the loader yields host numpy
batches; device transfer happens at first op use (or explicitly via
to_tensor), so input pipelines overlap with device compute naturally under
JAX's async dispatch. Multiprocess workers use the same
``multiprocessing.Process`` + queue design as the reference.
"""
from .dataset import (ChainDataset, ComposeDataset, ConcatDataset, Dataset,
                      IterableDataset, Subset, TensorDataset, random_split)
from .dataloader import DataLoader, get_worker_info
from .prefetch import DevicePrefetcher
from .sampler import (BatchSampler, DistributedBatchSampler, RandomSampler,
                      Sampler, SequenceSampler, SubsetRandomSampler,
                      WeightedRandomSampler)
