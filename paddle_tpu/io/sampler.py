"""Samplers (reference: python/paddle/io/dataloader/sampler.py,
batch_sampler.py)."""
from __future__ import annotations

import math

import numpy as np


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))

    def __len__(self):
        return len(self.data_source)


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples
        self.generator = generator

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class SubsetRandomSampler(Sampler):
    def __init__(self, indices):
        super().__init__()
        self.indices = list(indices)

    def __iter__(self):
        return iter(np.random.permutation(self.indices).tolist())

    def __len__(self):
        return len(self.indices)


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        super().__init__()
        self.weights = np.asarray(weights, dtype=np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(self.weights), self.num_samples,
                               replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False, batch_size=1,
                 drop_last=False):
        super().__init__()
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)
        self.batch_size = batch_size
        self.drop_last = drop_last

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Rank-sharded batches (reference:
    python/paddle/io/dataloader/batch_sampler.py DistributedBatchSampler)."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        if num_replicas is None or rank is None:
            from ..distributed import get_rank, get_world_size
            num_replicas = num_replicas or get_world_size()
            rank = rank if rank is not None else get_rank()
        self.nranks = num_replicas
        self.local_rank = rank
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            indices = rng.permutation(n).tolist()
        else:
            indices = list(range(n))
        indices += indices[:(self.total_size - len(indices))]
        indices = indices[self.local_rank::self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch
