"""Double-buffered device prefetch.

The round-12 step attribution shows synchronous input pipelines as
host+idle time at the top of every step: the consumer fetches a batch,
pays the host→device transfer, and only then dispatches compute. The
:class:`DevicePrefetcher` moves that work onto a background thread — it
pulls the NEXT batch from any iterator and issues its ``device_put``
(sharding-aware via a caller-supplied placement function) while the
current step computes, keeping up to ``depth`` batches in flight. jax
dispatch being async, the transfer overlaps device execution; the
consumer's ``next()`` becomes a queue pop.

This is the input half of the async runtime (the reference runs a
multi-stream actor runtime — ``fleet_executor`` — for the same reason);
``Engine.fit`` and ``hapi.Model.fit`` wrap their loaders in one by
default (``FLAGS_prefetch``).

Telemetry: ``paddle_tpu_prefetch_depth`` (configured depth),
``paddle_tpu_prefetch_hits_total`` (batch was already transferred when
the consumer asked), ``paddle_tpu_prefetch_stall_seconds_total`` (time
the consumer waited on the producer), and ``io.prefetch`` spans on the
producer thread — on the merged timeline they visibly overlap the
``device`` spans of the step (``tools/fleet_trace.py --overlap``).

Shutdown discipline: the producer thread and the WRAPPED iterator are
torn down together — explicitly via :meth:`close`/``with``, at iterator
exhaustion, and via ``weakref.finalize`` when the consumer abandons a
prefetching iterator mid-epoch. A wrapped multiprocess DataLoader
iterator propagates that teardown to its worker processes (no orphans).
"""
from __future__ import annotations

import queue as queue_mod
import threading
import time
import weakref
from typing import Callable, Iterator, Optional

from ..core import flags
from ..observability import goodput as _goodput
from ..observability import metrics as _metrics
from ..observability import trace as _trace

__all__ = ["DevicePrefetcher", "default_place_fn"]

_m_depth = _metrics.gauge(
    "paddle_tpu_prefetch_depth",
    "Configured DevicePrefetcher depth (batches kept in flight).")
_m_hits = _metrics.counter(
    "paddle_tpu_prefetch_hits_total",
    "Batches already transferred when the consumer asked (no wait).")
_m_stall = _metrics.counter(
    "paddle_tpu_prefetch_stall_seconds_total",
    "Seconds the consumer waited because the producer was behind.")

_DONE = object()


def default_place_fn(batch):
    """Default placement: move every array/Tensor leaf to the device
    (committed ``jnp.asarray``); structure is preserved. Callers with a
    mesh pass their own placement (e.g. the Engine's ``_shard_batch``)."""
    import jax.numpy as jnp
    import numpy as np

    from ..core.tensor import Tensor

    if isinstance(batch, Tensor):
        return Tensor(jnp.asarray(batch._data),
                      stop_gradient=batch.stop_gradient)
    if isinstance(batch, np.ndarray):
        return jnp.asarray(batch)
    if isinstance(batch, (list, tuple)):
        return type(batch)(default_place_fn(b) for b in batch)
    if isinstance(batch, dict):
        return {k: default_place_fn(v) for k, v in batch.items()}
    return batch


def _teardown_inner(it):
    """Propagate shutdown to the wrapped iterator: a multiprocess
    DataLoader iterator must reap its worker processes the moment the
    prefetcher dies, not at interpreter exit."""
    for name in ("close", "_teardown"):
        fn = getattr(it, name, None)
        if callable(fn):
            try:
                fn()
            except Exception:
                pass
            return


def _producer_loop(it, q, stop, place_fn):
    """Producer thread: fetch + place the next batch, park it in the
    bounded queue. Holds NO reference to the prefetcher object, so the
    consumer-side wrapper stays collectable (its finalize is the
    mid-epoch abandonment path)."""
    try:
        while not stop.is_set():
            try:
                with _trace.span("io.prefetch", "io"):
                    batch = next(it)
                    placed = place_fn(batch)
            except StopIteration:
                _offer(q, (_DONE, None), stop)
                return
            except BaseException as e:  # surface in the consumer
                _offer(q, ("error", e), stop)
                return
            if not _offer(q, ("ok", placed), stop):
                return
    finally:
        if stop.is_set():
            # abandoned mid-epoch: reap the wrapped iterator from here —
            # the finalize thread already signalled and moved on
            _teardown_inner(it)


def _offer(q, item, stop) -> bool:
    """put() that never deadlocks shutdown: re-checks the stop event
    while the queue is full."""
    while not stop.is_set():
        try:
            q.put(item, timeout=0.1)
            return True
        except queue_mod.Full:
            continue
    return False


def _shutdown(stop, thread, it):
    """finalize/close target (module-level: must not re-reference the
    prefetcher). Signals the producer, waits briefly, and guarantees the
    wrapped iterator's teardown even if the producer is parked."""
    stop.set()
    thread.join(timeout=5.0)
    _teardown_inner(it)


class DevicePrefetcher:
    """Wrap ``it`` so batches are fetched, placed, and transferred
    ``depth`` steps ahead of the consumer.

    ``place_fn(batch)`` runs on the producer thread and should return
    the device-resident (and, under a mesh, sharded) batch; defaults to
    :func:`default_place_fn`. ``depth`` defaults to
    ``FLAGS_prefetch_depth``.
    """

    def __init__(self, it: Iterator, depth: Optional[int] = None,
                 place_fn: Optional[Callable] = None):
        if depth is None:
            depth = int(flags.get_flag("prefetch_depth"))
        self.depth = max(1, int(depth))
        self._queue: queue_mod.Queue = queue_mod.Queue(maxsize=self.depth)
        self._stop = threading.Event()
        self._done = False
        self.hits = 0
        self.stall_seconds = 0.0
        if _metrics.enabled():
            _m_depth.set(self.depth)
        inner = iter(it)
        self._thread = threading.Thread(
            target=_producer_loop,
            args=(inner, self._queue, self._stop,
                  place_fn or default_place_fn),
            name="paddle_tpu-prefetch", daemon=True)
        self._finalizer = weakref.finalize(
            self, _shutdown, self._stop, self._thread, inner)
        self._thread.start()

    def __iter__(self):
        return self

    def __next__(self):
        if self._done:
            raise StopIteration
        waited = False
        try:
            kind, payload = self._queue.get_nowait()
        except queue_mod.Empty:
            waited = True
            t0 = time.perf_counter()
            while True:
                try:
                    kind, payload = self._queue.get(timeout=1.0)
                    break
                except queue_mod.Empty:
                    # a closed prefetcher (or a dead producer that never
                    # parked a sentinel) must not hang the consumer
                    if self._stop.is_set() or not self._thread.is_alive():
                        self._done = True
                        raise StopIteration
            stalled = time.perf_counter() - t0
            self.stall_seconds += stalled
            if _metrics.enabled():
                _m_stall.inc(stalled)
            # input starvation is badput the data plane owns: bill the
            # stall window to the goodput ledger's data_stall bucket
            _goodput.bill_interval("data_stall", t0, t0 + stalled)
        if kind is _DONE:
            self._done = True
            self.close()
            raise StopIteration
        if kind == "error":
            self._done = True
            self.close()
            raise payload
        if not waited:
            # a hit = a real BATCH that was ready when asked — sentinels
            # must not inflate the documented hit-rate metric
            self.hits += 1
            if _metrics.enabled():
                _m_hits.inc()
        return payload

    def close(self):
        """Stop the producer and tear down the wrapped iterator
        (idempotent; also runs at GC / interpreter exit)."""
        self._finalizer()

    @property
    def closed(self) -> bool:
        return not self._finalizer.alive

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
