"""DataLoader with multiprocess prefetch.

Reference: python/paddle/io/dataloader/dataloader_iter.py:365
(_DataLoaderIterMultiProcess — worker Process pool, index queues, data
queue). This implementation keeps the same architecture: a round-robin
index-queue per worker, a shared result queue, and an in-order reorder
buffer; numpy arrays cross process boundaries (device transfer happens in
the consumer, keeping workers device-free, which is mandatory on TPU where
only one process may own the chip).
"""
from __future__ import annotations

import itertools
import multiprocessing as mp
import queue as queue_mod
import threading
import weakref
from dataclasses import dataclass
from typing import Optional

import numpy as np

from ..core.tensor import Tensor
from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler, RandomSampler, SequenceSampler

_worker_info = None


@dataclass
class WorkerInfo:
    id: int
    num_workers: int
    dataset: object
    seed: int = 0


def get_worker_info():
    return _worker_info


def default_collate_fn(batch):
    """Stack samples into batched numpy/Tensor structures (reference:
    python/paddle/io/dataloader/collate.py)."""
    sample = batch[0]
    if isinstance(sample, Tensor):
        import jax.numpy as jnp
        return Tensor(jnp.stack([b._data for b in batch]))
    if isinstance(sample, np.ndarray):
        # native parallel memcpy when available (paddle_tpu/native —
        # reference data_feed.cc batch assembly role)
        try:
            from .. import native
            if native.AVAILABLE and sample.nbytes * len(batch) > 1 << 20:
                return native.collate_stack(batch)
        except Exception:
            pass
        return np.stack(batch)
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, dtype=np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, dtype=np.float32)
    if isinstance(sample, (list, tuple)):
        return type(sample)(default_collate_fn(list(items))
                            for items in zip(*batch))
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    return batch


def _worker_loop(dataset, index_queue, data_queue, collate_fn, worker_id,
                 num_workers, init_fn, use_shared_memory):
    global _worker_info
    _worker_info = WorkerInfo(worker_id, num_workers, dataset)
    if init_fn is not None:
        init_fn(worker_id)
    while True:
        item = index_queue.get()
        if item is None:
            break
        batch_idx, indices = item
        try:
            if isinstance(dataset, IterableDataset):
                data = indices  # pre-fetched by iterator path
            else:
                samples = [dataset[i] for i in indices]
                data = collate_fn(samples)
            data_queue.put((batch_idx, data, None))
        except Exception as e:  # propagate worker errors to the consumer
            import traceback
            data_queue.put((batch_idx, None, f"{e}\n{traceback.format_exc()}"))


class _SingleProcessIter:
    def __init__(self, loader):
        self._loader = loader
        self._sampler_iter = iter(loader.batch_sampler)
        self._dataset = loader.dataset
        self._collate = loader.collate_fn

    def __iter__(self):
        return self

    def __next__(self):
        indices = next(self._sampler_iter)
        samples = [self._dataset[i] for i in indices]
        out = self._collate(samples)
        return self._loader._to_output(out)


class _IterableDatasetIter:
    def __init__(self, loader):
        self._loader = loader
        self._it = iter(loader.dataset)
        self._batch_size = loader.batch_size
        self._drop_last = loader.drop_last
        self._collate = loader.collate_fn

    def __iter__(self):
        return self

    def __next__(self):
        batch = list(itertools.islice(self._it, self._batch_size))
        if not batch or (self._drop_last and len(batch) < self._batch_size):
            raise StopIteration
        return self._loader._to_output(self._collate(batch))


def _shutdown_workers(workers, index_queues):
    """Join/terminate worker processes (idempotent). Module-level so a
    ``weakref.finalize`` can run it at iterator GC AND interpreter exit
    without keeping the iterator alive — an exception in the consumer
    loop must not leave orphaned worker processes behind."""
    for q in index_queues:
        try:
            q.put_nowait(None)
        except Exception:
            pass
    for w in workers:
        try:
            w.join(timeout=2)
            if w.is_alive():
                w.terminate()
                w.join(timeout=2)
        except Exception:
            pass


class _MultiProcessIter:
    def __init__(self, loader):
        self._loader = loader
        self._num_workers = loader.num_workers
        self._sampler_iter = iter(loader.batch_sampler)
        ctx = mp.get_context("fork")
        self._index_queues = [ctx.Queue() for _ in range(self._num_workers)]
        self._data_queue = ctx.Queue()
        self._workers = []
        for wid in range(self._num_workers):
            w = ctx.Process(
                target=_worker_loop,
                args=(loader.dataset, self._index_queues[wid],
                      self._data_queue, loader.collate_fn, wid,
                      self._num_workers, loader.worker_init_fn,
                      loader.use_shared_memory),
                daemon=True)
            w.start()
            self._workers.append(w)
        # guaranteed cleanup: fires when the iterator is garbage
        # collected (incl. after a consumer-loop exception dropped the
        # last reference) and, via finalize's atexit hook, at interpreter
        # exit — whichever comes first
        self._finalizer = weakref.finalize(
            self, _shutdown_workers, self._workers, self._index_queues)
        self._send_idx = 0
        self._rcvd_idx = 0
        self._reorder = {}
        self._outstanding = 0
        self._exhausted = False
        self._shutdown = False
        # prime the pipeline: 2 batches in flight per worker
        for _ in range(2 * self._num_workers):
            self._dispatch()

    def _dispatch(self):
        if self._exhausted:
            return
        try:
            indices = next(self._sampler_iter)
        except StopIteration:
            self._exhausted = True
            return
        self._index_queues[self._send_idx % self._num_workers].put(
            (self._send_idx, indices))
        self._send_idx += 1
        self._outstanding += 1

    def __iter__(self):
        return self

    def __next__(self):
        if self._outstanding == 0:
            self._teardown()
            raise StopIteration
        while self._rcvd_idx not in self._reorder:
            # Bounded get + liveness check: a died worker (e.g. fork of the
            # multithreaded JAX parent wedging) must not hang the consumer.
            try:
                batch_idx, data, err = self._data_queue.get(timeout=5.0)
            except queue_mod.Empty:
                dead = [w.pid for w in self._workers if not w.is_alive()]
                if dead:
                    self._teardown()
                    raise RuntimeError(
                        f"DataLoader worker(s) {dead} exited unexpectedly")
                continue
            if err is not None:
                self._teardown()
                raise RuntimeError(f"DataLoader worker failed:\n{err}")
            self._reorder[batch_idx] = data
        data = self._reorder.pop(self._rcvd_idx)
        self._rcvd_idx += 1
        self._outstanding -= 1
        self._dispatch()
        return self._loader._to_output(data)

    def _teardown(self):
        if self._shutdown:
            return
        self._shutdown = True
        self._finalizer()

    #: public shutdown hook — a wrapping DevicePrefetcher (io/prefetch.py)
    #: propagates its own teardown here so abandoning a prefetching
    #: iterator mid-epoch reaps the worker processes immediately
    close = _teardown

    def __del__(self):
        self._teardown()


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.worker_init_fn = worker_init_fn
        self.use_shared_memory = use_shared_memory
        self.return_list = return_list
        if batch_sampler is not None:
            self.batch_sampler = batch_sampler
            self.batch_size = getattr(batch_sampler, "batch_size", batch_size)
        elif not isinstance(dataset, IterableDataset):
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last)
        else:
            self.batch_sampler = None

    def _to_output(self, data):
        """numpy → Tensor conversion at the consumer edge."""
        if isinstance(data, np.ndarray):
            import jax.numpy as jnp
            return Tensor(jnp.asarray(data))
        if isinstance(data, (list, tuple)):
            return type(data)(self._to_output(d) for d in data)
        if isinstance(data, dict):
            return {k: self._to_output(v) for k, v in data.items()}
        return data

    def __iter__(self):
        if isinstance(self.dataset, IterableDataset):
            return _IterableDatasetIter(self)
        if self.num_workers == 0:
            return _SingleProcessIter(self)
        return _MultiProcessIter(self)

    def __len__(self):
        if self.batch_sampler is None:
            raise TypeError("IterableDataset has no len()")
        return len(self.batch_sampler)
