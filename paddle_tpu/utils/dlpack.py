"""paddle.utils.dlpack — zero-copy tensor interchange.

Reference: ``python/paddle/utils/dlpack.py`` (``to_dlpack`` /
``from_dlpack`` over the DLPack capsule protocol). TPU-native: jax
arrays implement ``__dlpack__``, so exchange is direct — framework ↔
numpy/torch/cupy without a host copy where the backing buffer allows it
(device buffers export on-device; consumers that can't see the device
get a host copy via numpy()).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["to_dlpack", "from_dlpack"]


def to_dlpack(x):
    """Export a Tensor as a DLPack capsule (reference to_dlpack)."""
    if not isinstance(x, Tensor):
        raise TypeError(f"to_dlpack expects a paddle Tensor, got {type(x)}")
    return x._data.__dlpack__()


def from_dlpack(dlpack) -> Tensor:
    """Import from a DLPack capsule OR any object with ``__dlpack__``
    (torch/cupy/numpy arrays), reference from_dlpack."""
    if hasattr(dlpack, "__dlpack__") or hasattr(dlpack, "shape"):
        try:
            arr = jnp.from_dlpack(dlpack)
        except BufferError:
            # readonly buffers (e.g. numpy views) can't signal readonly
            # through DLPack — fall back to a copy
            import numpy as np
            arr = jnp.asarray(np.array(dlpack))
    else:
        # raw capsule: jax.dlpack consumes legacy capsules
        from jax import dlpack as jdl
        arr = jdl.from_dlpack(dlpack)
    return Tensor(arr)
