from . import cpp_extension, custom_op, dlpack
from .custom_op import register_custom_op

__all__ = ["cpp_extension", "custom_op", "register_custom_op", "dlpack"]
