from . import custom_op
from .custom_op import register_custom_op

__all__ = ["custom_op", "register_custom_op"]
