"""Custom op registration.

Capability parity with the reference custom-op ABI (reference:
paddle/phi/capi/ + python/paddle/utils/cpp_extension/ — user kernels with
optional hand-written grads registered into the op registry and callable
like builtins). TPU-native: a "kernel" is a jax-traceable function (jnp, or
a Pallas kernel for hand-tiled TPU code); the optional backward installs a
jax.custom_vjp, and the op lands in paddle_tpu.ops.registry + the autograd
tape exactly like built-in ops — no C ABI needed, and the custom op fuses
with its neighbors under jit.
"""
from __future__ import annotations

import functools
from typing import Callable, Optional

import jax

from ..core import dispatch
from ..core.tensor import Tensor, as_tensor
from ..ops.registry import OPS, OpDef


def register_custom_op(name: str, forward: Callable,
                       backward: Optional[Callable] = None,
                       num_inputs: Optional[int] = None,
                       category: str = "custom"):
    """Register ``name`` as a framework op.

    forward(*arrays, **attrs) -> array | tuple — jax-traceable lowering
    (jnp ops or a Pallas kernel).
    backward(residuals, *out_grads) -> tuple(in_grads) with residuals =
    (inputs, outputs); omit to use jax autodiff of ``forward``.

    Returns the user-facing function taking/returning Tensors.
    """
    if name in OPS:
        raise ValueError(f"op {name!r} already registered")

    if backward is not None:
        # one custom_vjp per distinct attrs (attrs are static config and
        # must reach BOTH the primal and the residual-producing fwd rule)
        _cores = {}

        def _get_core(attrs):
            key = tuple(sorted(attrs.items()))
            core = _cores.get(key)
            if core is not None:
                return core

            @jax.custom_vjp
            def core(*arrays):
                return forward(*arrays, **attrs)

            def fwd_rule(*arrays):
                out = forward(*arrays, **attrs)
                return out, (arrays, out)

            def bwd_rule(res, g):
                grads = backward(res,
                                 *(g if isinstance(g, tuple) else (g,)))
                if not isinstance(grads, (tuple, list)):
                    grads = (grads,)
                return tuple(grads)

            core.defvjp(fwd_rule, bwd_rule)
            _cores[key] = core
            return core
    else:
        _get_core = None

    def user_fn(*inputs, **attrs):
        tensors = [i if isinstance(i, Tensor) else as_tensor(i)
                   for i in inputs]
        if _get_core is not None:
            fn = _get_core(attrs)
        elif attrs:
            fn = lambda *xs: forward(*xs, **attrs)
        else:
            fn = forward
        return dispatch.call(name, fn, tensors)

    user_fn.__name__ = name
    OPS[name] = OpDef(name=name, category=category, lowering=user_fn,
                      doc=forward.__doc__ or "")
    return user_fn


from .cpp_extension import CppExtension  # noqa: E402  (real impl)

__all__ = ["register_custom_op", "CppExtension"]
