"""C++ extension loader: compile user C++ into host custom ops.

Reference: python/paddle/utils/cpp_extension/ (setup/load building
pybind+CUDA ops; paddle/phi/capi C ABI). TPU-native split: DEVICE custom
kernels are jax/Pallas code (``register_custom_op``); this module covers
the HOST side — user C++ compiled with g++ into a shared library, bound
through ctypes, and exposed as framework ops that work both eagerly and
under ``jit`` (via ``jax.pure_callback``, which XLA schedules as a host
callback). The exported C ABI is flat-buffer style, like the native
runtime's collation library:

    extern "C" void my_op(const float* x, float* out, int64_t n);

(same-shape float32 transform — the common "custom activation /
data-side transform in C++" case; reductions/shape changes belong in
jax/Pallas device code).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
from types import SimpleNamespace
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dispatch
from ..core.tensor import Tensor, as_tensor

__all__ = ["load", "CppExtension"]


def _compile(name: str, sources: Sequence[str], extra_cflags, build_dir,
             verbose: bool) -> str:
    build_dir = build_dir or os.path.join(
        tempfile.gettempdir(), "paddle_tpu_extensions")
    os.makedirs(build_dir, exist_ok=True)
    # content-hashed filename: dlopen caches by path, so rebuilding edited
    # sources to the SAME path would silently keep running the old code
    import hashlib
    h = hashlib.sha256()
    for src in sources:
        with open(src, "rb") as fh:
            h.update(fh.read())
    h.update(" ".join(extra_cflags or []).encode())  # flags change codegen
    out = os.path.join(build_dir, f"lib{name}_{h.hexdigest()[:12]}.so")
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-o", out, *sources,
           *(extra_cflags or [])]
    if verbose:
        print("[cpp_extension]", " ".join(cmd))
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(
            f"cpp_extension build failed:\n{proc.stderr}")
    return out


def _bind(lib_path: str, fn_name: str):
    lib = ctypes.CDLL(lib_path)
    try:
        cfn = getattr(lib, fn_name)
    except AttributeError:
        raise RuntimeError(
            f"{lib_path} does not export {fn_name!r} "
            f"(declare it extern \"C\")")
    cfn.restype = None
    cfn.argtypes = [ctypes.POINTER(ctypes.c_float),
                    ctypes.POINTER(ctypes.c_float), ctypes.c_int64]

    def host_impl(arr: np.ndarray) -> np.ndarray:
        arr = np.ascontiguousarray(arr, dtype=np.float32)
        out = np.empty_like(arr)
        cfn(arr.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            ctypes.c_int64(arr.size))
        return out

    return host_impl


def load(name: str, sources: Sequence[str],
         functions: Optional[List[str]] = None, extra_cflags=None,
         build_directory: Optional[str] = None, verbose: bool = False):
    """Compile ``sources`` and return a namespace of framework ops, one
    per exported function (reference cpp_extension.load contract).

    Each op takes/returns a float32 Tensor of unchanged shape. It runs
    the C++ code on host — eagerly via ctypes, under jit via
    ``jax.pure_callback`` (a host callback op inside the XLA program).
    """
    if not functions:
        raise ValueError("pass functions=[...] naming the extern \"C\" "
                         "symbols to bind")
    lib_path = _compile(name, sources, extra_cflags, build_directory,
                        verbose)
    ns = {}
    for fn_name in functions:
        host_impl = _bind(lib_path, fn_name)

        def lowering(a, _impl=host_impl):
            spec = jax.ShapeDtypeStruct(a.shape, jnp.float32)
            return jax.pure_callback(
                lambda arr: _impl(np.asarray(arr)), spec,
                a.astype(jnp.float32))

        def op(x, _lowering=lowering, _name=fn_name):
            t = x if isinstance(x, Tensor) else as_tensor(x)
            return dispatch.call(f"{name}.{_name}", _lowering, [t],
                                 differentiable_mask=[False])

        op.__name__ = fn_name
        ns[fn_name] = op
    module = SimpleNamespace(**ns)
    module.__file__ = lib_path
    return module


class CppExtension:
    """Build-spec record for setup()-style builds (reference
    cpp_extension.CppExtension). ``load`` is the JIT path; for packaged
    builds, instantiate with sources and call .build()."""

    def __init__(self, sources: Sequence[str], name: str = "custom_ext",
                 extra_compile_args=None, **kwargs):
        self.name = name
        self.sources = list(sources)
        self.extra_compile_args = extra_compile_args or []

    def build(self, functions: List[str], build_directory=None,
              verbose: bool = False):
        return load(self.name, self.sources, functions=functions,
                    extra_cflags=self.extra_compile_args,
                    build_directory=build_directory, verbose=verbose)
