"""paddle.flops — per-layer FLOPs accounting via forward hooks.

Reference: ``python/paddle/hapi/dynamic_flops.py`` (``flops`` :28 /
``dynamic_flops`` — leaf layers get a type-matched count function
attached as a forward-post hook, unknown types count zero with a
notice, ``custom_ops`` overrides; multiply-accumulate counted as one
op, matching the reference's numbers).
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np

__all__ = ["flops"]


def _numel(t):
    return int(np.prod(t.shape)) if t.shape else 1


def _count_convnd(m, x, y):
    # output elements × (in_ch/groups × prod(kernel)) MACs (+bias)
    bias_ops = 1 if getattr(m, "bias", None) is not None else 0
    macs_per_out = int(np.prod(m.weight.shape[1:]))
    m._flops_ops += _numel(y) * (macs_per_out + bias_ops)


def _count_linear(m, x, y):
    in_features = m.weight.shape[0]
    m._flops_ops += _numel(y) * in_features


def _count_bn(m, x, y):
    m._flops_ops += 2 * _numel(x[0] if isinstance(x, tuple) else x)


def _count_relu(m, x, y):
    m._flops_ops += _numel(x[0] if isinstance(x, tuple) else x)


def _count_avgpool(m, x, y):
    m._flops_ops += _numel(y)


def _count_adap_avgpool(m, x, y):
    xin = x[0] if isinstance(x, tuple) else x
    kern = max(_numel(xin) // max(_numel(y), 1), 1)
    m._flops_ops += (kern + 1) * _numel(y)


def _count_zero(m, x, y):
    pass


def _register_hooks() -> Dict[type, callable]:
    from .. import nn
    table = {
        nn.Conv1D: _count_convnd, nn.Conv2D: _count_convnd,
        nn.Conv3D: _count_convnd,
        nn.Linear: _count_linear,
        nn.BatchNorm1D: _count_bn, nn.BatchNorm2D: _count_bn,
        nn.BatchNorm3D: _count_bn, nn.BatchNorm: _count_bn,
        nn.SyncBatchNorm: _count_bn,
        nn.ReLU: _count_relu, nn.ReLU6: _count_relu,
        nn.Sigmoid: _count_relu,
        nn.AvgPool1D: _count_avgpool, nn.AvgPool2D: _count_avgpool,
        nn.AvgPool3D: _count_avgpool,
        nn.AdaptiveAvgPool1D: _count_adap_avgpool,
        nn.AdaptiveAvgPool2D: _count_adap_avgpool,
        nn.AdaptiveAvgPool3D: _count_adap_avgpool,
        nn.Dropout: _count_zero,
    }
    for name in ("Conv1DTranspose", "Conv2DTranspose", "Conv3DTranspose"):
        cls = getattr(nn, name, None)
        if cls is not None:
            table[cls] = _count_convnd
    return table


def flops(net, input_size=None, custom_ops: Optional[dict] = None,
          print_detail: bool = False, inputs=None):
    """Total FLOPs of one forward pass (reference hapi flops :28).

    ``input_size`` builds a zeros input of that shape; alternatively
    pass ``inputs`` (a Tensor) directly.
    """
    from .. import to_tensor
    from ..core import dispatch

    if inputs is None:
        if input_size is None:
            raise ValueError("flops needs input_size or inputs")
        inputs = to_tensor(np.zeros(input_size, np.float32))

    custom_ops = custom_ops or {}
    table = _register_hooks()
    handles = []
    seen_types = set()
    leaves = [m for m in net.sublayers(include_self=True)
              if not list(m.children())]
    for m in leaves:
        m._flops_ops = 0
        m._flops_params = sum(_numel(p) for p in m.parameters())
        mt = type(m)
        fn = custom_ops.get(mt, table.get(mt))
        if fn is None:
            if mt not in seen_types:
                print(f"Cannot find suitable count function for {mt}. "
                      f"Treat it as zero FLOPs.")
            fn = _count_zero
        elif mt not in seen_types:
            src = "Customize Function" if mt in custom_ops else str(mt)
            print(f"{src}'s flops has been counted")
        seen_types.add(mt)
        handles.append(m.register_forward_post_hook(fn))

    was_training = net.training
    net.eval()
    try:
        with dispatch.no_grad():
            net(inputs)
    finally:
        for h in handles:
            h.remove()
        if was_training:
            net.train()

    total_ops = sum(m._flops_ops for m in leaves)
    total_params = sum(m._flops_params for m in leaves)
    if print_detail:
        print(f"{'Layer':<40}{'FLOPs':>16}{'Params':>12}")
        for m in leaves:
            print(f"{type(m).__name__:<40}{m._flops_ops:>16}"
                  f"{m._flops_params:>12}")
    print(f"Total Flops: {total_ops}     Total Params: {total_params}")
    return int(total_ops)
