"""paddle.summary — layer/param table (reference: python/paddle/hapi/
model_summary.py summary())."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor


def summary(net, input_size=None, dtypes=None, input=None):
    """Run a forward pass with hooks to collect per-layer output shapes and
    parameter counts; returns {'total_params': N, 'trainable_params': N}."""
    import paddle_tpu as paddle

    rows = []
    hooks = []

    def make_hook(name):
        def hook(layer, inputs, outputs):
            out = outputs[0] if isinstance(outputs, (list, tuple)) \
                else outputs
            shape = list(out.shape) if isinstance(out, Tensor) else None
            n_params = sum(int(np.prod(p.shape))
                           for p in layer.parameters(
                               include_sublayers=False))
            rows.append((name, type(layer).__name__, shape, n_params))
        return hook

    for name, layer in net.named_sublayers():
        hooks.append(layer.register_forward_post_hook(make_hook(name)))

    was_training = net.training
    try:
        if input is not None:
            x = input
        else:
            if input_size is None:
                raise ValueError("summary needs input_size or input")
            sizes = input_size if isinstance(input_size, (list, tuple)) and \
                isinstance(input_size[0], (list, tuple)) else [input_size]
            dts = dtypes if dtypes else ["float32"] * len(sizes)
            x = [paddle.to_tensor(
                np.zeros([d if d and d > 0 else 1 for d in s],
                         np.dtype(dt) if dt != "bfloat16" else np.float32))
                for s, dt in zip(sizes, dts)]
            x = x[0] if len(x) == 1 else x
        net.eval()
        net(*x) if isinstance(x, list) else net(x)
    finally:
        if was_training:
            net.train()
        for h in hooks:
            h.remove()

    total = sum(int(np.prod(p.shape)) for p in net.parameters())
    trainable = sum(int(np.prod(p.shape)) for p in net.parameters()
                    if not p.stop_gradient)
    line = "-" * 72
    print(line)
    print(f"{'Layer (type)':<34}{'Output Shape':<24}{'Param #':<12}")
    print(line)
    for name, cls, shape, n in rows:
        print(f"{name + ' (' + cls + ')':<34}{str(shape):<24}{n:<12}")
    print(line)
    print(f"Total params: {total:,}")
    print(f"Trainable params: {trainable:,}")
    print(line)
    return {"total_params": total, "trainable_params": trainable}
