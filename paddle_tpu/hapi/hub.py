"""paddle.hub — hubconf-based model loading.

Reference: ``python/paddle/hapi/hub.py`` (``list`` :180, ``help`` :230,
``load`` :278 over a repo's ``hubconf.py``; ``_load_entry_from_hubconf``
:144, dependency check via ``dependencies`` :167).

Local sources are fully supported (the hubconf protocol is just module
loading). Remote sources (github/gitee) require network egress, which a
TPU training pod typically does not have — they raise a clear error
pointing at the local-path workflow instead of failing mid-download.
"""
from __future__ import annotations

import importlib.util
import os
import sys
from typing import List, Optional

__all__ = ["list", "help", "load"]

MODULE_HUBCONF = "hubconf.py"
_ALLOWED = ("github", "gitee", "local")


def _import_hubconf(repo_dir: str):
    path = os.path.join(repo_dir, MODULE_HUBCONF)
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"no {MODULE_HUBCONF} in {repo_dir!r} — a hub repo must "
            f"define one (reference hub contract)")
    sys.path.insert(0, repo_dir)
    try:
        spec = importlib.util.spec_from_file_location("hubconf", path)
        m = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(m)
    finally:
        sys.path.remove(repo_dir)
    _check_dependencies(m)
    return m


def _check_dependencies(m) -> None:
    deps = getattr(m, "dependencies", None)
    if not deps:
        return
    missing = [p for p in deps
               if importlib.util.find_spec(p) is None]
    if missing:
        raise RuntimeError(
            f"Missing dependencies: {missing}")


def _resolve(repo_dir: str, source: str) -> str:
    if source not in _ALLOWED:
        raise ValueError(
            f'Unknown source: "{source}". Allowed values: '
            f'"github" | "gitee" | "local".')
    if source != "local":
        raise RuntimeError(
            f"source={source!r} needs network egress to fetch "
            f"{repo_dir!r}; this environment is isolated — clone the "
            f"repo yourself and call with source='local'")
    return repo_dir


def _load_entry_from_hubconf(m, name: str):
    if not isinstance(name, str):
        raise ValueError(
            "Invalid input: model should be a str of function name")
    func = getattr(m, name, None)
    if func is None or not callable(func):
        raise RuntimeError(f"Cannot find callable {name} in hubconf")
    return func


def list(repo_dir: str, source: str = "github",
         force_reload: bool = False) -> List[str]:
    """All entrypoint names exported by the repo's hubconf."""
    m = _import_hubconf(_resolve(repo_dir, source))
    return [f for f in dir(m)
            if callable(getattr(m, f)) and not f.startswith("_")]


def help(repo_dir: str, model: str, source: str = "github",
         force_reload: bool = False) -> Optional[str]:
    """Docstring of one entrypoint."""
    m = _import_hubconf(_resolve(repo_dir, source))
    return _load_entry_from_hubconf(m, model).__doc__


def load(repo_dir: str, model: str, source: str = "github",
         force_reload: bool = False, **kwargs):
    """Instantiate an entrypoint: ``hubconf.<model>(**kwargs)``."""
    m = _import_hubconf(_resolve(repo_dir, source))
    return _load_entry_from_hubconf(m, model)(**kwargs)
