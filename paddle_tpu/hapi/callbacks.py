"""hapi training callbacks.

Reference: python/paddle/hapi/callbacks.py — Callback base with
on_{train,eval}_{begin,end} / on_epoch_{begin,end} /
on_{train,eval}_batch_{begin,end} hooks, plus ModelCheckpoint,
EarlyStopping, LRScheduler, ReduceLROnPlateau built-ins, driven by
Model.fit/evaluate.
"""
from __future__ import annotations

import math
import numbers
from typing import List, Optional

import numpy as np

__all__ = ["Callback", "CallbackList", "ModelCheckpoint", "EarlyStopping",
           "LRScheduler", "ReduceLROnPlateau"]


def _scalar(logs, key):
    """Pull a numeric metric out of a logs dict (values may be scalars or
    one-element lists, e.g. evaluate()'s {"loss": [v]})."""
    value = (logs or {}).get(key)
    if isinstance(value, (list, tuple, np.ndarray)):
        arr = np.asarray(value).ravel()
        if arr.size != 1:
            return None
        value = float(arr[0])
    return value if isinstance(value, numbers.Number) else None


class Callback:
    """Base callback (reference callbacks.py Callback)."""

    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = dict(params or {})

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks: Optional[List[Callback]] = None):
        self.callbacks = list(callbacks or [])

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def __getattr__(self, name):
        if not name.startswith("on_"):
            raise AttributeError(name)

        def fan_out(*args, **kwargs):
            for c in self.callbacks:
                getattr(c, name)(*args, **kwargs)

        return fan_out


class ModelCheckpoint(Callback):
    """Save params every ``save_freq`` epochs + final (reference
    callbacks.py ModelCheckpoint).

    **Manager mode** (fault tolerance): pass ``manager`` (a
    :class:`paddle_tpu.fault.CheckpointManager`) to save the FULL train
    state — model, optimizer, optional GradScaler, epoch/step counters —
    atomically with rotation, every ``save_freq`` epochs and (with
    ``save_steps=N``) every N global steps, so ``Model.fit(resume=...)``
    restarts step-granularly after preemption. With
    ``restore_on_nonfinite=True`` a diverged step (non-finite loss) rolls
    model+optimizer back to the last verifiable checkpoint instead of
    training on."""

    def __init__(self, save_freq=1, save_dir=None, manager=None,
                 save_steps=None, scaler=None,
                 restore_on_nonfinite=False):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir
        self.manager = manager
        self.save_steps = save_steps
        self.scaler = scaler
        self.restore_on_nonfinite = restore_on_nonfinite
        self.restored_nonfinite = 0
        self._epoch = 0
        self._epoch_began = False
        if restore_on_nonfinite and manager is None:
            raise ValueError("restore_on_nonfinite requires manager=")
        if save_steps is not None and manager is None:
            raise ValueError("save_steps requires manager=")

    def _save_state(self, epoch, step_in_epoch=None):
        from ..fault import capture_train_state
        state = capture_train_state(network=self.model.network,
                                    optimizer=self.model._optimizer,
                                    scaler=self.scaler)
        meta = {"epoch_complete": step_in_epoch is None}
        if step_in_epoch is not None:
            meta["step_in_epoch"] = int(step_in_epoch)
        self.manager.save(state, step=self.model._global_step,
                          epoch=int(epoch), meta=meta)

    def on_train_begin(self, logs=None):
        # a reused callback instance must not carry a previous fit's
        # epoch counter into this run's on_train_end guard
        self._epoch = 0
        self._epoch_began = False

    def on_epoch_begin(self, epoch, logs=None):
        self._epoch = epoch
        self._epoch_began = True

    def on_train_batch_end(self, step, logs=None):
        if self.manager is None:
            return
        if self.restore_on_nonfinite:
            loss = _scalar(logs, "loss")
            if loss is not None and not math.isfinite(loss):
                from ..fault import restore_train_state
                out = self.manager.restore()
                if out is not None:
                    restore_train_state(
                        out[0], network=self.model.network,
                        optimizer=self.model._optimizer,
                        scaler=self.scaler)
                    self.restored_nonfinite += 1
                return
        if self.save_steps and \
                self.model._global_step % self.save_steps == 0:
            self._save_state(self._epoch, step_in_epoch=step)

    def on_epoch_end(self, epoch, logs=None):
        if (epoch + 1) % max(self.save_freq, 1) != 0:
            return
        if self.manager is not None:
            self._save_state(epoch)
        elif self.save_dir:
            self.model.save(f"{self.save_dir}/{epoch}")

    def on_train_end(self, logs=None):
        if self.manager is not None:
            # only if this fit actually trained: a fully-resumed run
            # (start_epoch == epochs) must not overwrite the newest
            # checkpoint's meta with a stale epoch counter
            if self._epoch_began:
                self._save_state(self._epoch)   # idempotent if epoch-saved
        elif self.save_dir:
            self.model.save(f"{self.save_dir}/final")


class EarlyStopping(Callback):
    """Stop when ``monitor`` stops improving (reference callbacks.py
    EarlyStopping). Sets model.stop_training, honored by Model.fit."""

    def __init__(self, monitor="loss", mode="auto", patience=0,
                 verbose=1, min_delta=0, baseline=None,
                 save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        if mode not in ("auto", "min", "max"):
            mode = "auto"
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self.wait = 0
        self.best = None
        self.stopped_epoch = -1

    def _improved(self, value):
        if self.best is None:
            return True
        if self.mode == "min":
            return value < self.best - self.min_delta
        return value > self.best + self.min_delta

    def on_train_begin(self, logs=None):
        self.wait = 0
        self.best = self.baseline

    def on_eval_end(self, logs=None):
        value = _scalar(logs, self.monitor)
        if value is None:
            return
        if self._improved(value):
            self.best = value
            self.wait = 0
            save_dir = self.params.get("save_dir")
            if self.save_best_model and save_dir:
                self.model.save(f"{save_dir}/best_model")
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True
                if self.verbose:
                    print(f"[EarlyStopping] no {self.monitor} improvement "
                          f"for {self.wait} evals; stopping")

    def on_epoch_end(self, epoch, logs=None):
        if getattr(self.model, "stop_training", False) \
                and self.stopped_epoch < 0:
            self.stopped_epoch = epoch


class LRScheduler(Callback):
    """Step the optimizer's LR scheduler (reference callbacks.py
    LRScheduler: by_step or by_epoch)."""

    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        if by_step and by_epoch:
            raise ValueError("by_step and by_epoch are mutually exclusive")
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if hasattr(lr, "step") else None

    def on_train_batch_end(self, step, logs=None):
        if self.by_step:
            s = self._sched()
            if s is not None:
                s.step()

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch:
            s = self._sched()
            if s is not None:
                s.step()


class ReduceLROnPlateau(Callback):
    """Hook the ReduceOnPlateau scheduler to eval metrics (reference
    callbacks.py ReduceLROnPlateau-style behavior via the optimizer's
    scheduler)."""

    def __init__(self, monitor="loss"):
        super().__init__()
        self.monitor = monitor

    def on_eval_end(self, logs=None):
        value = _scalar(logs, self.monitor)
        if value is None:
            return
        opt = getattr(self.model, "_optimizer", None)
        sched = getattr(opt, "_learning_rate", None)
        from ..optimizer.lr import ReduceOnPlateau as _ROP
        if isinstance(sched, _ROP):
            sched.step(value)  # plateau scheduler consumes the metric
        # any other scheduler: do nothing — passing the metric as an
        # epoch number would silently corrupt its schedule
