from .model import Model
from .summary import summary

__all__ = ["Model", "summary"]
