from . import callbacks
from .callbacks import (Callback, EarlyStopping, LRScheduler,
                        ModelCheckpoint, ReduceLROnPlateau)
from .model import Model
from .summary import summary

__all__ = ["Model", "summary", "callbacks", "Callback", "EarlyStopping",
           "LRScheduler", "ModelCheckpoint", "ReduceLROnPlateau"]
