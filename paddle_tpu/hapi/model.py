"""hapi Model — the high-level train/eval/predict facade.

Capability parity with the reference high-level API (reference:
python/paddle/hapi/model.py Model:1000 region — prepare/fit/evaluate/
predict/save/load over a Layer + optimizer + loss + metrics). TPU-native:
train_batch is plain eager dispatch (each op an XLA call); the whole-step
jit path comes from wrapping the network with paddle.jit.to_static before
constructing the Model, exactly like the reference's prepare(amp_configs)
composition.
"""
from __future__ import annotations

import math
from typing import List, Optional, Sequence

import numpy as np

from ..core.tensor import Tensor
from ..fault import inject as _inject
from ..observability import metrics as _metrics

_m_skipped = _metrics.counter(
    "paddle_tpu_train_nonfinite_skipped_total",
    "Optimizer steps skipped because the loss went non-finite "
    "(graceful degradation instead of poisoning the weights).")


def _to_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics: List = []
        self.stop_training = False
        #: monotonically increasing train-batch counter; persisted by
        #: manager-mode ModelCheckpoint and restored by fit(resume=...)
        self._global_step = 0
        #: count of steps skipped on non-finite loss (this run)
        self._nonfinite_steps = 0

    # ------------------------------------------------------------- prepare
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        self._metrics = _to_list(metrics)
        return self

    # ------------------------------------------------------- batch methods
    def _compute_loss(self, outputs, labels):
        outs = _to_list(outputs)
        labs = _to_list(labels)
        if self._loss is None:
            raise RuntimeError("call prepare(loss=...) first")
        return self._loss(*outs, *labs)

    def train_batch(self, inputs, labels=None, update=True):
        self.network.train()
        outputs = self.network(*_to_list(inputs))
        loss = self._compute_loss(outputs, labels)
        if _inject.fire("grads.nan_at_step",
                        step=self._global_step) is not None:
            loss = loss * float("nan")   # deterministic divergence for tests
        loss.backward()
        # the loss is MATERIALIZED here, before the skip-step check:
        # under the async input pipeline (fit wraps its loader in a
        # DevicePrefetcher) everything else in the step stays in flight,
        # but graceful degradation needs a concrete value — a lazy/NaN
        # loss must never reach the optimizer step undetected
        loss_val = float(loss.numpy())
        if update and self._optimizer is not None:
            if math.isfinite(loss_val):
                self._optimizer.step()
            else:
                # graceful degradation: a non-finite loss means the grads
                # are poison — drop them and keep the weights intact
                # rather than stepping the run into NaN
                self._nonfinite_steps += 1
                _m_skipped.inc()
            self._optimizer.clear_grad()
        self._global_step += 1
        metrics = self._update_metrics(outputs, labels)
        return ([loss_val], metrics) if metrics else [loss_val]

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        outputs = self.network(*_to_list(inputs))
        loss = self._compute_loss(outputs, labels)
        metrics = self._update_metrics(outputs, labels)
        return ([float(loss.numpy())], metrics) if metrics else \
            [float(loss.numpy())]

    def predict_batch(self, inputs):
        self.network.eval()
        outputs = self.network(*_to_list(inputs))
        return [o.numpy() if isinstance(o, Tensor) else o
                for o in _to_list(outputs)]

    def _update_metrics(self, outputs, labels):
        res = []
        for m in self._metrics:
            correct = m.compute(*_to_list(outputs), *_to_list(labels))
            m.update(*[np.asarray(c.numpy() if isinstance(c, Tensor) else c)
                       for c in _to_list(correct)])
            res.append(m.accumulate())
        return res

    # ------------------------------------------------------------ fit loop
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1,
            verbose=2, drop_last=False, shuffle=True, num_workers=0,
            callbacks=None, resume=None):
        """``resume``: a :class:`paddle_tpu.fault.CheckpointManager` —
        restores model/optimizer (+ GradScaler, when a manager-mode
        ModelCheckpoint callback carries one) from the newest verifiable
        checkpoint and fast-forwards the epoch/step counters, skipping
        past a corrupt latest checkpoint automatically."""
        from ..io import DataLoader
        from .callbacks import CallbackList
        loader = train_data
        if not isinstance(train_data, DataLoader):
            loader = DataLoader(train_data, batch_size=batch_size,
                                shuffle=shuffle, drop_last=drop_last,
                                num_workers=num_workers)
        cbks = CallbackList(_to_list(callbacks))
        cbks.set_model(self)
        cbks.set_params({"epochs": epochs, "batch_size": batch_size,
                         "verbose": verbose, "save_dir": save_dir,
                         "metrics": [m.name() for m in self._metrics]})
        # job health plane: wall-clock goodput account (begun BEFORE the
        # resume path, so auto_resume's rewind lands in this run)
        from ..observability import goodput as _goodput
        _goodput.ledger().run_begin()
        start_epoch, skip_steps = 0, 0
        if resume is not None:
            start_epoch, skip_steps = self._auto_resume(resume,
                                                        cbks.callbacks,
                                                        verbose)
        from ..core import flags as _flags
        from ..io.prefetch import DevicePrefetcher
        use_prefetch = bool(_flags.get_flag("prefetch"))
        self.stop_training = False
        history = []
        cbks.on_train_begin()
        for epoch in range(start_epoch, epochs):
            cbks.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            losses = []
            # double-buffered device prefetch (io/prefetch.py): the next
            # batch transfers on a background thread while train_batch
            # runs; teardown propagates to the loader's worker processes
            batches = (DevicePrefetcher(iter(loader)) if use_prefetch
                       else loader)
            try:
                self._fit_epoch(batches, epoch, start_epoch, skip_steps,
                                losses, cbks, verbose, log_freq)
            finally:
                if isinstance(batches, DevicePrefetcher):
                    batches.close()
            if losses:
                epoch_logs = {"loss": float(np.mean(losses))}
                history.append(epoch_logs["loss"])
            else:
                # resume skipped the whole epoch: no new training, so no
                # loss to report (np.mean([]) would hand callbacks a NaN)
                epoch_logs = {}
            if eval_data is not None and (epoch + 1) % eval_freq == 0:
                eval_res = self.evaluate(eval_data, batch_size=batch_size,
                                         verbose=verbose,
                                         callbacks=cbks.callbacks)
                # namespace eval results: 'loss' stays the TRAIN loss
                # (same float type with or without eval_data)
                from .callbacks import _scalar
                for k in eval_res:
                    v = _scalar(eval_res, k)
                    epoch_logs[f"eval_{k}"] = (v if v is not None
                                               else eval_res[k])
            if save_dir and (epoch + 1) % max(save_freq, 1) == 0:
                self.save(f"{save_dir}/epoch_{epoch}")
            cbks.on_epoch_end(epoch, epoch_logs)
            if self.stop_training:
                break
        cbks.on_train_end({"loss": history[-1] if history else None})
        return history

    def _fit_epoch(self, batches, epoch, start_epoch, skip_steps, losses,
                   cbks, verbose, log_freq):
        """One epoch's step loop over ``batches`` (a DevicePrefetcher or
        the raw loader)."""
        from ..fault import supervisor as _fault_sup
        from ..observability import goodput as _goodput
        from ..observability import sentinel as _sentinel
        led = _goodput.ledger()
        snt = _sentinel.get()
        for step, batch in enumerate(batches):
            if epoch == start_epoch and step < skip_steps:
                continue   # step-granular resume: already trained
            # heartbeat seam: publishes this rank's lease + fires the
            # rank.crash/hang drills; one dict lookup when no
            # supervisor is running
            _fault_sup.tick(self._global_step)
            led.step_begin()
            cbks.on_train_batch_begin(step)
            batch = _to_list(batch)
            xs, ys = batch[:-1], batch[-1:]
            out = self.train_batch(xs, ys)
            loss = out[0][0] if isinstance(out, tuple) else out[0]
            losses.append(loss)
            snt.observe_step(led.step_end(step=self._global_step),
                             loss=loss, step=self._global_step)
            if verbose and log_freq and step % log_freq == 0:
                msg = f"epoch {epoch} step {step} loss {loss:.4f}"
                for m, v in zip(self._metrics,
                                out[1] if isinstance(out, tuple)
                                else []):
                    msg += f" {m.name()}={v}"
                print(msg)
            cbks.on_train_batch_end(step, {"loss": loss})

    def _auto_resume(self, manager, callbacks, verbose):
        """Restore train state from ``manager`` and translate its meta
        into (start_epoch, steps-to-skip in that epoch).  Multi-process
        worlds resume through the supervisor's consensus rewind: ranks
        exchange checkpoint manifests and every rank restores the newest
        step completed on ALL of them (a rank that saved further ahead
        rewinds rather than split-brain the fleet)."""
        from ..fault import auto_resume
        from ..fault import supervisor as _fault_sup
        from ..observability import flight as _flight
        scaler = None
        for c in callbacks:
            scaler = getattr(c, "scaler", None) or scaler
        if scaler is not None:
            _fault_sup.register_scaler(scaler)
        try:
            world = _flight.rank_world()[1]
        except Exception:
            world = 1
        if world > 1:
            meta = _fault_sup.consensus_resume(
                manager, network=self.network,
                optimizer=self._optimizer, scaler=scaler)
        else:
            meta = auto_resume(manager, network=self.network,
                               optimizer=self._optimizer, scaler=scaler)
        if meta is None:
            return 0, 0
        self._global_step = int(meta.get("step", 0))
        epoch = meta.get("epoch")
        if epoch is None:
            return 0, 0
        if meta.get("epoch_complete", True):
            start_epoch, skip_steps = int(epoch) + 1, 0
        else:
            start_epoch = int(epoch)
            skip_steps = int(meta.get("step_in_epoch", -1)) + 1
        if verbose:
            print(f"[resume] restored step {self._global_step} "
                  f"(epoch {start_epoch}, skipping {skip_steps} "
                  f"completed steps; fallback depth "
                  f"{manager.last_fallback_depth})")
        return start_epoch, skip_steps

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None):
        from ..io import DataLoader
        from .callbacks import CallbackList
        loader = eval_data
        if not isinstance(eval_data, DataLoader):
            loader = DataLoader(eval_data, batch_size=batch_size,
                                num_workers=num_workers)
        cbks = CallbackList(_to_list(callbacks))
        cbks.set_model(self)
        cbks.on_eval_begin()
        for m in self._metrics:
            m.reset()
        losses = []
        for step, batch in enumerate(loader):
            cbks.on_eval_batch_begin(step)
            batch = _to_list(batch)
            xs, ys = batch[:-1], batch[-1:]
            out = self.eval_batch(xs, ys)
            loss = out[0][0] if isinstance(out, tuple) else out[0]
            losses.append(loss)
            cbks.on_eval_batch_end(step, {"loss": loss})
        result = {"loss": [float(np.mean(losses))]}
        for m in self._metrics:
            result[m.name()] = m.accumulate()
        if verbose:
            print("eval:", result)
        cbks.on_eval_end(result)
        return result

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, callbacks=None):
        from ..io import DataLoader
        loader = test_data
        if not isinstance(test_data, DataLoader):
            loader = DataLoader(test_data, batch_size=batch_size,
                                num_workers=num_workers)
        outs = []
        for batch in loader:
            batch = _to_list(batch)
            # split inputs from trailing labels: the Model(inputs=...)
            # spec decides when given (reference contract); otherwise fall
            # back to dropping one trailing label when a loss was prepared
            if self._inputs is not None:
                batch = batch[:len(_to_list(self._inputs))]
            elif self._loss is not None and len(batch) > 1:
                batch = batch[:-1]
            outs.append(self.predict_batch(batch))
        if stack_outputs and outs:
            n = len(outs[0])
            return [np.concatenate([o[i] for o in outs]) for i in range(n)]
        return outs

    # ------------------------------------------------------------ save/load
    def save(self, path, training=True):
        from ..framework.io import save as _save
        _save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None and \
                hasattr(self._optimizer, "state_dict"):
            _save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        import os

        from ..framework.io import load as _load
        self.network.set_state_dict(_load(path + ".pdparams"))
        opt_path = path + ".pdopt"
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(opt_path) and \
                hasattr(self._optimizer, "set_state_dict"):
            self._optimizer.set_state_dict(_load(opt_path))

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        from .summary import summary as _summary
        return _summary(self.network, input_size, dtypes=dtype)
