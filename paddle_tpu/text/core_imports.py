"""Internal import indirection for paddle_tpu.text."""
from ..core import dispatch
from ..core.tensor import Tensor, as_tensor

__all__ = ["dispatch", "Tensor", "as_tensor"]
