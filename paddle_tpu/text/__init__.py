"""paddle.text — text-domain helpers (reference: python/paddle/text/
datasets: Imdb/Conll05/...; viterbi_decode). Imdb/Imikolov/UCIHousing are
real loaders over LOCAL copies of the reference archives (datasets.py —
downloads need egress, absent here); the remaining dataset classes raise
with a pointer. viterbi_decode is a faithful implementation of the
reference kernel."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import dispatch
from ..core.tensor import Tensor, as_tensor


def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag: bool = True, name=None):
    """Batched Viterbi decode (reference python/paddle/text/
    viterbi_decode.py:26 + phi viterbi_decode_kernel.cc:215-300).

    potentials: [B, T, N] emissions; transition_params: [N, N];
    lengths: [B] valid lengths (None = full length).
    ``include_bos_eos_tag=True`` (reference default) treats the LAST row of
    transitions as the start tag's outgoing scores (added at step 0) and
    the SECOND-TO-LAST row as the stop tag's scores (added at each
    sequence's final valid step). Returns (scores [B], paths [B, T]) with
    path entries past a sequence's length set to 0.
    """
    pot = as_tensor(potentials)
    trans = as_tensor(transition_params)
    if lengths is None:
        lengths = jnp.full((pot.shape[0],), pot.shape[1], jnp.int32)
    else:
        lengths = as_tensor(lengths)._data.astype(jnp.int32)

    def f(p, tr):
        b, t, n = p.shape
        start = tr[n - 1]            # kernel: start_trans = last row
        stop = tr[n - 2]             # kernel: stop_trans = row n-2
        left0 = lengths

        alpha = p[:, 0]
        if include_bos_eos_tag:
            alpha = alpha + start[None, :]
            alpha = alpha + jnp.where((left0 == 1)[:, None], stop[None, :],
                                      0.0)
        ident = jnp.broadcast_to(jnp.arange(n)[None, :], (b, n))

        def step(carry, emit):
            alpha, left = carry
            scores = alpha[:, :, None] + tr[None, :, :]
            best = jnp.max(scores, axis=1) + emit
            back = jnp.argmax(scores, axis=1)
            valid = (left > 0)[:, None]
            if include_bos_eos_tag:
                best = best + jnp.where((left == 1)[:, None],
                                        stop[None, :], 0.0)
            alpha = jnp.where(valid, best, alpha)
            back = jnp.where(valid, back, ident)  # padded: pass-through
            return (alpha, left - 1), back

        emits = jnp.swapaxes(p, 0, 1)[1:]
        (alpha, _), backptrs = jax.lax.scan(
            step, (alpha, left0 - 1), emits)
        best_score = jnp.max(alpha, axis=-1)
        last = jnp.argmax(alpha, axis=-1)

        def backtrack(tag, back):
            prev = jnp.take_along_axis(back, tag[:, None], axis=1)[:, 0]
            return prev, tag

        tag0, path_rev = jax.lax.scan(backtrack, last, backptrs,
                                      reverse=True)
        path = jnp.concatenate([tag0[None, :], path_rev], axis=0)  # [T, B]
        path = jnp.swapaxes(path, 0, 1)                            # [B, T]
        mask = jnp.arange(t)[None, :] < lengths[:, None]
        return best_score, jnp.where(mask, path, 0)

    out = dispatch.call("viterbi_decode", f, [pot, trans])
    return out[0], out[1]


class ViterbiDecoder:
    """reference viterbi_decode.py:144 layer form."""

    def __init__(self, transitions, include_bos_eos_tag: bool = True,
                 name=None):
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def __call__(self, potentials, lengths=None):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)


class _NeedsDownload:
    def __init__(self, *a, **k):
        raise NotImplementedError(
            "dataset download requires network egress; provide local files "
            "through paddle_tpu.io.Dataset instead")


# implemented loaders read LOCAL copies of the reference archives
# (no-egress environment); the rest still point at io.Dataset
WMT14 = _NeedsDownload

from . import datasets  # noqa: E402,F401
from .datasets import (WMT16, Conll05st, Imdb,  # noqa: E402,F401
                       Imikolov, Movielens, UCIHousing)

__all__ = ["datasets", "viterbi_decode", "ViterbiDecoder", "Imdb",
           "Imikolov", "Conll05st", "Movielens", "UCIHousing", "WMT14",
           "WMT16"]
