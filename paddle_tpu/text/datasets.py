"""paddle.text.datasets — UCIHousing / Imdb / Imikolov loaders.

Reference: python/paddle/text/datasets/{uci_housing,imdb,imikolov}.py.
The reference downloads archives on demand; this environment has no
egress, so constructors take a local ``data_file`` and raise a clear
error when it is absent. Parsing matches the reference formats exactly
(whitespace floats for housing; the aclImdb tar layout with the same
regex selection and frequency-sorted word dict for Imdb), so files
fetched for the reference work unchanged.
"""
from __future__ import annotations

import collections
import os
import re
import string
import tarfile
import zipfile

import numpy as np

from ..io import Dataset

__all__ = ["UCIHousing", "Imdb", "Imikolov", "Movielens",
           "MovieInfo", "UserInfo"]


def _require(path, what):
    if path is None or not os.path.exists(path):
        raise FileNotFoundError(
            f"{what}: data file {path!r} not found. No-egress environment "
            f"— place the same archive the reference downloads there and "
            f"pass data_file=...")


class UCIHousing(Dataset):
    """Boston housing regression (reference uci_housing.py UCIHousing:
    13 normalized features + price; 80/20 train/test split)."""

    FEATURE_NUM = 14

    def __init__(self, data_file=None, mode="train", download=True):
        _require(data_file, "UCIHousing")
        if mode.lower() not in ("train", "test"):
            raise ValueError(f"mode must be train|test, got {mode!r}")
        self.mode = mode.lower()
        data = np.fromfile(data_file, sep=" ", dtype=np.float32)
        data = data.reshape(data.shape[0] // self.FEATURE_NUM,
                            self.FEATURE_NUM)
        maximums = data.max(axis=0)
        minimums = data.min(axis=0)
        avgs = data.sum(axis=0) / data.shape[0]
        for i in range(self.FEATURE_NUM - 1):
            data[:, i] = ((data[:, i] - avgs[i])
                          / (maximums[i] - minimums[i]))
        offset = int(data.shape[0] * 0.8)
        self.data = data[:offset] if self.mode == "train" else data[offset:]

    def __getitem__(self, idx):
        row = self.data[idx]
        return row[:-1], row[-1:]

    def __len__(self):
        return len(self.data)


class Imdb(Dataset):
    """IMDB sentiment (reference imdb.py Imdb): reads the aclImdb tar,
    builds a frequency-sorted word dict with cutoff, yields
    (ids ndarray, label) with label 0=pos, 1=neg."""

    def __init__(self, data_file=None, mode="train", cutoff=150,
                 download=True):
        _require(data_file, "Imdb")
        if mode.lower() not in ("train", "test"):
            raise ValueError(f"mode must be train|test, got {mode!r}")
        self.data_file = data_file
        self.mode = mode.lower()
        self.word_idx = self._build_word_dict(cutoff)
        self._load_anno()

    def _tokenize(self, pattern):
        docs = []
        with tarfile.open(self.data_file) as tarf:
            for member in tarf.getmembers():
                if bool(pattern.match(member.name)):
                    data = tarf.extractfile(member).read().decode(
                        "latin-1").lower()
                    docs.append(
                        data.translate(
                            str.maketrans("", "", string.punctuation))
                        .split())
        return docs

    def _build_word_dict(self, cutoff):
        pattern = re.compile(
            r"aclImdb/((train)|(test))/((pos)|(neg))/.*\.txt$")
        word_freq = collections.Counter()
        for doc in self._tokenize(pattern):
            for word in doc:
                word_freq[word] += 1
        word_freq.pop("<unk>", None)
        words = [w for w, f in word_freq.items() if f > cutoff]
        # frequency-descending then lexical, like the reference sort
        words.sort(key=lambda w: (-word_freq[w], w))
        word_idx = {w: i for i, w in enumerate(words)}
        word_idx["<unk>"] = len(words)
        return word_idx

    def _load_anno(self):
        unk = self.word_idx["<unk>"]
        self.docs, self.labels = [], []
        for label, sentiment in ((0, "pos"), (1, "neg")):
            pattern = re.compile(
                rf"aclImdb/{self.mode}/{sentiment}/.*\.txt$")
            for doc in self._tokenize(pattern):
                self.docs.append(np.asarray(
                    [self.word_idx.get(w, unk) for w in doc],
                    dtype=np.int64))
                self.labels.append(label)

    def __getitem__(self, idx):
        # label shape (1,) like the reference (np.array([label]))
        return self.docs[idx], np.asarray([self.labels[idx]], np.int64)

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """PTB-style n-gram dataset (reference imikolov.py Imikolov):
    sentences wrapped in <s> ... <e>, frequency dict with min_word_freq,
    yields n-gram windows (data_type=NGRAM) or sequences (SEQ)."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=-1,
                 mode="train", min_word_freq=50, download=True):
        _require(data_file, "Imikolov")
        if data_type.upper() not in ("NGRAM", "SEQ"):
            raise ValueError("data_type must be NGRAM or SEQ")
        if data_type.upper() == "NGRAM" and window_size < 1:
            raise ValueError("NGRAM needs window_size >= 1")
        # SEQ mode: window_size > 0 filters long sequences (reference)
        if mode.lower() not in ("train", "valid", "test"):
            raise ValueError(f"mode must be train|valid|test, got {mode!r}")
        self.data_file = data_file
        self.mode = mode.lower()
        self.data_type = data_type.upper()
        self.window_size = window_size
        self.word_idx = self._build_word_dict(min_word_freq)
        self._load_anno()

    def _member(self, tarf, split):
        for m in tarf.getmembers():
            if m.name.endswith(f"ptb.{split}.txt"):
                return m
        raise ValueError(f"no ptb.{split}.txt in {self.data_file}")

    def _build_word_dict(self, min_word_freq):
        # reference word_count: train + valid files, with <s>/<e> counted
        # once per line so they always earn real dict entries
        freq = collections.Counter()
        with tarfile.open(self.data_file) as tarf:
            for split in ("train", "valid"):
                text = tarf.extractfile(
                    self._member(tarf, split)).read().decode()
                for line in text.splitlines():
                    if not line.strip():
                        continue
                    freq["<s>"] += 1
                    freq["<e>"] += 1
                    for w in line.strip().split():
                        freq[w] += 1
        freq.pop("<unk>", None)
        words = [w for w, f in freq.items() if f > min_word_freq]
        words.sort(key=lambda w: (-freq[w], w))
        word_idx = {w: i for i, w in enumerate(words)}
        word_idx["<unk>"] = len(words)
        return word_idx

    def _load_anno(self):
        split = {"train": "train", "valid": "valid",
                 "test": "test"}[self.mode]
        unk = self.word_idx["<unk>"]
        self.data = []
        with tarfile.open(self.data_file) as tarf:
            text = tarf.extractfile(
                self._member(tarf, split)).read().decode()
        for line in text.splitlines():
            if not line.strip():
                continue
            body = [self.word_idx.get(w, unk)
                    for w in line.strip().split()]
            s_id = self.word_idx.get("<s>", unk)
            e_id = self.word_idx.get("<e>", unk)
            if self.data_type == "NGRAM":
                ids = [s_id] + body + [e_id]
                for i in range(len(ids) - self.window_size + 1):
                    self.data.append(
                        np.asarray(ids[i:i + self.window_size], np.int64))
            else:
                # reference SEQ contract: (src=[<s>]+l, trg=l+[<e>]),
                # dropped when window_size > 0 and src exceeds it
                src = [s_id] + body
                trg = body + [e_id]
                if 0 < self.window_size < len(src):
                    continue
                self.data.append((np.asarray(src, np.int64),
                                  np.asarray(trg, np.int64)))

    def __getitem__(self, idx):
        item = self.data[idx]
        return item if isinstance(item, tuple) else (item,)

    def __len__(self):
        return len(self.data)


_AGE_TABLE = [1, 18, 25, 35, 45, 50, 56]


class MovieInfo:
    """Movie id/categories/title record (reference movielens.py
    MovieInfo)."""

    def __init__(self, index, categories, title):
        self.index = int(index)
        self.categories = categories
        self.title = title

    def value(self, categories_dict, movie_title_dict):
        return [
            [self.index],
            [categories_dict[c] for c in self.categories],
            [movie_title_dict[w.lower()] for w in self.title.split()],
        ]


class UserInfo:
    """User id/gender/age/job record (reference movielens.py UserInfo)."""

    def __init__(self, index, gender, age, job_id):
        self.index = int(index)
        self.is_male = gender == "M"
        self.age = _AGE_TABLE.index(int(age))
        self.job_id = int(job_id)

    def value(self):
        return [[self.index], [0 if self.is_male else 1], [self.age],
                [self.job_id]]


class Movielens(Dataset):
    """Movielens-1M ratings (reference movielens.py Movielens): parses
    ml-1m/{movies,users,ratings}.dat from the zip; each item is
    (uid, gender, age, job, movie_id, categories, title_words, rating)
    with rating rescaled to [-5+2, 5] via r*2-5 and a random
    test_ratio split seeded by rand_seed."""

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0, download=True):
        _require(data_file, "Movielens (ml-1m.zip)")
        if mode.lower() not in ("train", "test"):
            raise ValueError(f"mode must be train|test, got {mode!r}")
        self.mode = mode.lower()
        pattern = re.compile(r"^(.*)\((\d+)\)$")
        self.movie_info, self.user_info = {}, {}
        title_words, categories = set(), set()
        with zipfile.ZipFile(data_file) as package:
            with package.open("ml-1m/movies.dat") as f:
                for line in f:
                    mid, title, cats = line.decode(
                        "latin").strip().split("::")
                    cats = cats.split("|")
                    categories.update(cats)
                    title = pattern.match(title).group(1)
                    self.movie_info[int(mid)] = MovieInfo(mid, cats, title)
                    title_words.update(w.lower() for w in title.split())
            self.movie_title_dict = {w: i for i, w in
                                     enumerate(sorted(title_words))}
            self.categories_dict = {c: i for i, c in
                                    enumerate(sorted(categories))}
            with package.open("ml-1m/users.dat") as f:
                for line in f:
                    uid, gender, age, job, _ = line.decode(
                        "latin").strip().split("::")
                    self.user_info[int(uid)] = UserInfo(uid, gender, age,
                                                        job)
            rng = np.random.RandomState(rand_seed)
            is_test = self.mode == "test"
            self.data = []
            with package.open("ml-1m/ratings.dat") as f:
                for line in f:
                    if (rng.random_sample() < test_ratio) != is_test:
                        continue
                    uid, mid, rating, _ = line.decode(
                        "latin").strip().split("::")
                    rating = float(rating) * 2 - 5.0
                    mov = self.movie_info[int(mid)]
                    usr = self.user_info[int(uid)]
                    self.data.append(
                        usr.value()
                        + mov.value(self.categories_dict,
                                    self.movie_title_dict)
                        + [[rating]])

    def __getitem__(self, idx):
        return tuple(np.array(d) for d in self.data[idx])

    def __len__(self):
        return len(self.data)
