"""paddle.text.datasets — UCIHousing / Imdb / Imikolov loaders.

Reference: python/paddle/text/datasets/{uci_housing,imdb,imikolov}.py.
The reference downloads archives on demand; this environment has no
egress, so constructors take a local ``data_file`` and raise a clear
error when it is absent. Parsing matches the reference formats exactly
(whitespace floats for housing; the aclImdb tar layout with the same
regex selection and frequency-sorted word dict for Imdb), so files
fetched for the reference work unchanged.
"""
from __future__ import annotations

import collections
import os
import re
import string
import tarfile
import zipfile

import numpy as np

from ..io import Dataset

__all__ = ["UCIHousing", "Imdb", "Imikolov", "Movielens",
           "MovieInfo", "UserInfo", "Conll05st", "WMT16"]


def _require(path, what):
    if path is None or not os.path.exists(path):
        raise FileNotFoundError(
            f"{what}: data file {path!r} not found. No-egress environment "
            f"— place the same archive the reference downloads there and "
            f"pass data_file=...")


class UCIHousing(Dataset):
    """Boston housing regression (reference uci_housing.py UCIHousing:
    13 normalized features + price; 80/20 train/test split)."""

    FEATURE_NUM = 14

    def __init__(self, data_file=None, mode="train", download=True):
        _require(data_file, "UCIHousing")
        if mode.lower() not in ("train", "test"):
            raise ValueError(f"mode must be train|test, got {mode!r}")
        self.mode = mode.lower()
        data = np.fromfile(data_file, sep=" ", dtype=np.float32)
        data = data.reshape(data.shape[0] // self.FEATURE_NUM,
                            self.FEATURE_NUM)
        maximums = data.max(axis=0)
        minimums = data.min(axis=0)
        avgs = data.sum(axis=0) / data.shape[0]
        for i in range(self.FEATURE_NUM - 1):
            data[:, i] = ((data[:, i] - avgs[i])
                          / (maximums[i] - minimums[i]))
        offset = int(data.shape[0] * 0.8)
        self.data = data[:offset] if self.mode == "train" else data[offset:]

    def __getitem__(self, idx):
        row = self.data[idx]
        return row[:-1], row[-1:]

    def __len__(self):
        return len(self.data)


class Imdb(Dataset):
    """IMDB sentiment (reference imdb.py Imdb): reads the aclImdb tar,
    builds a frequency-sorted word dict with cutoff, yields
    (ids ndarray, label) with label 0=pos, 1=neg."""

    def __init__(self, data_file=None, mode="train", cutoff=150,
                 download=True):
        _require(data_file, "Imdb")
        if mode.lower() not in ("train", "test"):
            raise ValueError(f"mode must be train|test, got {mode!r}")
        self.data_file = data_file
        self.mode = mode.lower()
        self.word_idx = self._build_word_dict(cutoff)
        self._load_anno()

    def _tokenize(self, pattern):
        docs = []
        with tarfile.open(self.data_file) as tarf:
            for member in tarf.getmembers():
                if bool(pattern.match(member.name)):
                    data = tarf.extractfile(member).read().decode(
                        "latin-1").lower()
                    docs.append(
                        data.translate(
                            str.maketrans("", "", string.punctuation))
                        .split())
        return docs

    def _build_word_dict(self, cutoff):
        pattern = re.compile(
            r"aclImdb/((train)|(test))/((pos)|(neg))/.*\.txt$")
        word_freq = collections.Counter()
        for doc in self._tokenize(pattern):
            for word in doc:
                word_freq[word] += 1
        word_freq.pop("<unk>", None)
        words = [w for w, f in word_freq.items() if f > cutoff]
        # frequency-descending then lexical, like the reference sort
        words.sort(key=lambda w: (-word_freq[w], w))
        word_idx = {w: i for i, w in enumerate(words)}
        word_idx["<unk>"] = len(words)
        return word_idx

    def _load_anno(self):
        unk = self.word_idx["<unk>"]
        self.docs, self.labels = [], []
        for label, sentiment in ((0, "pos"), (1, "neg")):
            pattern = re.compile(
                rf"aclImdb/{self.mode}/{sentiment}/.*\.txt$")
            for doc in self._tokenize(pattern):
                self.docs.append(np.asarray(
                    [self.word_idx.get(w, unk) for w in doc],
                    dtype=np.int64))
                self.labels.append(label)

    def __getitem__(self, idx):
        # label shape (1,) like the reference (np.array([label]))
        return self.docs[idx], np.asarray([self.labels[idx]], np.int64)

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """PTB-style n-gram dataset (reference imikolov.py Imikolov):
    sentences wrapped in <s> ... <e>, frequency dict with min_word_freq,
    yields n-gram windows (data_type=NGRAM) or sequences (SEQ)."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=-1,
                 mode="train", min_word_freq=50, download=True):
        _require(data_file, "Imikolov")
        if data_type.upper() not in ("NGRAM", "SEQ"):
            raise ValueError("data_type must be NGRAM or SEQ")
        if data_type.upper() == "NGRAM" and window_size < 1:
            raise ValueError("NGRAM needs window_size >= 1")
        # SEQ mode: window_size > 0 filters long sequences (reference)
        if mode.lower() not in ("train", "valid", "test"):
            raise ValueError(f"mode must be train|valid|test, got {mode!r}")
        self.data_file = data_file
        self.mode = mode.lower()
        self.data_type = data_type.upper()
        self.window_size = window_size
        self.word_idx = self._build_word_dict(min_word_freq)
        self._load_anno()

    def _member(self, tarf, split):
        for m in tarf.getmembers():
            if m.name.endswith(f"ptb.{split}.txt"):
                return m
        raise ValueError(f"no ptb.{split}.txt in {self.data_file}")

    def _build_word_dict(self, min_word_freq):
        # reference word_count: train + valid files, with <s>/<e> counted
        # once per line so they always earn real dict entries
        freq = collections.Counter()
        with tarfile.open(self.data_file) as tarf:
            for split in ("train", "valid"):
                text = tarf.extractfile(
                    self._member(tarf, split)).read().decode()
                for line in text.splitlines():
                    if not line.strip():
                        continue
                    freq["<s>"] += 1
                    freq["<e>"] += 1
                    for w in line.strip().split():
                        freq[w] += 1
        freq.pop("<unk>", None)
        words = [w for w, f in freq.items() if f > min_word_freq]
        words.sort(key=lambda w: (-freq[w], w))
        word_idx = {w: i for i, w in enumerate(words)}
        word_idx["<unk>"] = len(words)
        return word_idx

    def _load_anno(self):
        split = {"train": "train", "valid": "valid",
                 "test": "test"}[self.mode]
        unk = self.word_idx["<unk>"]
        self.data = []
        with tarfile.open(self.data_file) as tarf:
            text = tarf.extractfile(
                self._member(tarf, split)).read().decode()
        for line in text.splitlines():
            if not line.strip():
                continue
            body = [self.word_idx.get(w, unk)
                    for w in line.strip().split()]
            s_id = self.word_idx.get("<s>", unk)
            e_id = self.word_idx.get("<e>", unk)
            if self.data_type == "NGRAM":
                ids = [s_id] + body + [e_id]
                for i in range(len(ids) - self.window_size + 1):
                    self.data.append(
                        np.asarray(ids[i:i + self.window_size], np.int64))
            else:
                # reference SEQ contract: (src=[<s>]+l, trg=l+[<e>]),
                # dropped when window_size > 0 and src exceeds it
                src = [s_id] + body
                trg = body + [e_id]
                if 0 < self.window_size < len(src):
                    continue
                self.data.append((np.asarray(src, np.int64),
                                  np.asarray(trg, np.int64)))

    def __getitem__(self, idx):
        item = self.data[idx]
        return item if isinstance(item, tuple) else (item,)

    def __len__(self):
        return len(self.data)


_AGE_TABLE = [1, 18, 25, 35, 45, 50, 56]


class MovieInfo:
    """Movie id/categories/title record (reference movielens.py
    MovieInfo)."""

    def __init__(self, index, categories, title):
        self.index = int(index)
        self.categories = categories
        self.title = title

    def value(self, categories_dict, movie_title_dict):
        return [
            [self.index],
            [categories_dict[c] for c in self.categories],
            [movie_title_dict[w.lower()] for w in self.title.split()],
        ]


class UserInfo:
    """User id/gender/age/job record (reference movielens.py UserInfo)."""

    def __init__(self, index, gender, age, job_id):
        self.index = int(index)
        self.is_male = gender == "M"
        self.age = _AGE_TABLE.index(int(age))
        self.job_id = int(job_id)

    def value(self):
        return [[self.index], [0 if self.is_male else 1], [self.age],
                [self.job_id]]


class Movielens(Dataset):
    """Movielens-1M ratings (reference movielens.py Movielens): parses
    ml-1m/{movies,users,ratings}.dat from the zip; each item is
    (uid, gender, age, job, movie_id, categories, title_words, rating)
    with rating rescaled to [-5+2, 5] via r*2-5 and a random
    test_ratio split seeded by rand_seed."""

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0, download=True):
        _require(data_file, "Movielens (ml-1m.zip)")
        if mode.lower() not in ("train", "test"):
            raise ValueError(f"mode must be train|test, got {mode!r}")
        self.mode = mode.lower()
        pattern = re.compile(r"^(.*)\((\d+)\)$")
        self.movie_info, self.user_info = {}, {}
        title_words, categories = set(), set()
        with zipfile.ZipFile(data_file) as package:
            with package.open("ml-1m/movies.dat") as f:
                for line in f:
                    mid, title, cats = line.decode(
                        "latin").strip().split("::")
                    cats = cats.split("|")
                    categories.update(cats)
                    title = pattern.match(title).group(1)
                    self.movie_info[int(mid)] = MovieInfo(mid, cats, title)
                    title_words.update(w.lower() for w in title.split())
            self.movie_title_dict = {w: i for i, w in
                                     enumerate(sorted(title_words))}
            self.categories_dict = {c: i for i, c in
                                    enumerate(sorted(categories))}
            with package.open("ml-1m/users.dat") as f:
                for line in f:
                    uid, gender, age, job, _ = line.decode(
                        "latin").strip().split("::")
                    self.user_info[int(uid)] = UserInfo(uid, gender, age,
                                                        job)
            rng = np.random.RandomState(rand_seed)
            is_test = self.mode == "test"
            self.data = []
            with package.open("ml-1m/ratings.dat") as f:
                for line in f:
                    if (rng.random_sample() < test_ratio) != is_test:
                        continue
                    uid, mid, rating, _ = line.decode(
                        "latin").strip().split("::")
                    rating = float(rating) * 2 - 5.0
                    mov = self.movie_info[int(mid)]
                    usr = self.user_info[int(uid)]
                    self.data.append(
                        usr.value()
                        + mov.value(self.categories_dict,
                                    self.movie_title_dict)
                        + [[rating]])

    def __getitem__(self, idx):
        return tuple(np.array(d) for d in self.data[idx])

    def __len__(self):
        return len(self.data)


class Conll05st(Dataset):
    """CoNLL-2005 SRL test split (reference conll05.py Conll05st): parses
    the words/props gz pair inside the release tar into BIO-tagged
    (sentence, predicate, labels) items, with word/predicate/label dicts
    from their separate files. Yields the reference 9-tuple:
    (word, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, pred, mark, label).
    """

    UNK_IDX = 0

    def __init__(self, data_file=None, word_dict_file=None,
                 verb_dict_file=None, target_dict_file=None, mode="test",
                 download=True):
        import gzip as _gzip
        if mode != "test":
            raise ValueError(
                "Conll05st ships only the WSJ test split (the reference "
                "loader likewise); mode must be 'test'")
        for p, what in ((data_file, "release tar"),
                        (word_dict_file, "word dict"),
                        (verb_dict_file, "verb dict"),
                        (target_dict_file, "target dict")):
            _require(p, f"Conll05st {what}")
        self.word_dict = self._load_dict(word_dict_file)
        self.predicate_dict = self._load_dict(verb_dict_file)
        self.label_dict = self._load_label_dict(target_dict_file)
        self.sentences, self.predicates, self.labels = [], [], []
        with tarfile.open(data_file) as tf:
            wf = tf.extractfile(
                "conll05st-release/test.wsj/words/test.wsj.words.gz")
            pf = tf.extractfile(
                "conll05st-release/test.wsj/props/test.wsj.props.gz")
            with _gzip.GzipFile(fileobj=wf) as words_file, \
                    _gzip.GzipFile(fileobj=pf) as props_file:
                self._parse(words_file, props_file)

    @staticmethod
    def _load_dict(filename):
        with open(filename) as f:
            return {line.strip(): i for i, line in enumerate(f)}

    @staticmethod
    def _load_label_dict(filename):
        tag_dict = set()
        with open(filename) as f:
            for line in f:
                line = line.strip()
                if line.startswith(("B-", "I-")):
                    tag_dict.add(line[2:])
        d = {}
        index = 0
        for tag in sorted(tag_dict):  # deterministic across processes
            d["B-" + tag] = index
            d["I-" + tag] = index + 1
            index += 2
        d["O"] = index
        return d

    def _parse(self, words_file, props_file):
        """Column-major props -> BIO spans (reference _load_anno)."""
        sentences, labels, one_seg = [], [], []
        lines = list(zip(words_file, props_file))
        # a file without a trailing separator must still flush its last
        # sentence — append a synthetic boundary
        lines.append((b"", b""))
        for word, label in lines:
            word = word.strip().decode()
            label = label.strip().decode().split()
            if len(label) == 0:  # sentence boundary
                for i in range(len(one_seg[0]) if one_seg else 0):
                    labels.append([x[i] for x in one_seg])
                if len(labels) >= 1:
                    verb_list = [x for x in labels[0] if x != "-"]
                    for i, lbl in enumerate(labels[1:]):
                        cur_tag = "O"
                        in_bracket = False
                        seq = []
                        for tok in lbl:
                            if tok == "*" and not in_bracket:
                                seq.append("O")
                            elif tok == "*" and in_bracket:
                                seq.append("I-" + cur_tag)
                            elif tok == "*)":
                                seq.append("I-" + cur_tag)
                                in_bracket = False
                            elif "(" in tok and ")" in tok:
                                cur_tag = tok[1:tok.find("*")]
                                seq.append("B-" + cur_tag)
                                in_bracket = False
                            elif "(" in tok:
                                cur_tag = tok[1:tok.find("*")]
                                seq.append("B-" + cur_tag)
                                in_bracket = True
                            else:
                                raise RuntimeError(
                                    f"Unexpected label: {tok}")
                        self.sentences.append(sentences)
                        self.predicates.append(verb_list[i])
                        self.labels.append(seq)
                sentences, labels, one_seg = [], [], []
            else:
                sentences.append(word)
                one_seg.append(label)

    def __getitem__(self, idx):
        sentence = self.sentences[idx]
        predicate = self.predicates[idx]
        labels = self.labels[idx]
        sen_len = len(sentence)
        verb_index = labels.index("B-V")
        mark = [0] * len(labels)
        ctx = {}
        for off, key, pad in ((-2, "n2", "bos"), (-1, "n1", "bos"),
                              (0, "0", None), (1, "p1", "eos"),
                              (2, "p2", "eos")):
            j = verb_index + off
            if 0 <= j < len(labels):
                mark[j] = 1
                ctx[key] = sentence[j]
            else:
                ctx[key] = pad
        get = lambda w: self.word_dict.get(w, self.UNK_IDX)
        return (np.array([get(w) for w in sentence]),
                np.array([get(ctx["n2"])] * sen_len),
                np.array([get(ctx["n1"])] * sen_len),
                np.array([get(ctx["0"])] * sen_len),
                np.array([get(ctx["p1"])] * sen_len),
                np.array([get(ctx["p2"])] * sen_len),
                np.array([self.predicate_dict.get(predicate)] * sen_len),
                np.array(mark),
                np.array([self.label_dict.get(w) for w in labels]))

    def __len__(self):
        return len(self.sentences)


class WMT16(Dataset):
    """WMT16 en-de MT dataset (reference wmt16.py WMT16): the archive
    holds wmt16/{train,val,test} TSV pairs; dictionaries are built from
    the train split (frequency-sorted, capped, with <s>/<e>/<unk> heads)
    and cached next to the archive. Items are
    (src_ids, trg_ids, trg_ids_next) with <s>/<e> framing."""

    START_MARK, END_MARK, UNK_MARK = "<s>", "<e>", "<unk>"

    def __init__(self, data_file=None, mode="train", src_dict_size=-1,
                 trg_dict_size=-1, lang="en", download=True):
        _require(data_file, "WMT16 (wmt16.tar.gz)")
        if mode not in ("train", "test", "val"):
            raise ValueError(f"mode must be train|test|val, got {mode!r}")
        if lang not in ("en", "de"):
            raise ValueError(f"lang must be en|de, got {lang!r}")
        self.data_file = data_file
        self.mode = mode
        self.lang = lang
        self.src_dict = self._load_dict(lang, src_dict_size)
        self.trg_dict = self._load_dict("de" if lang == "en" else "en",
                                        trg_dict_size)
        self._load_data()

    def _train_freqs(self):
        """One decompression pass counts BOTH columns (the reference
        streams the gz train split once per language)."""
        if getattr(self, "_freq_cache", None) is None:
            en, de = collections.Counter(), collections.Counter()
            with tarfile.open(self.data_file) as tf:
                for line in tf.extractfile("wmt16/train"):
                    parts = line.decode().strip().split("\t")
                    if len(parts) != 2:
                        continue
                    for w in parts[0].split():
                        en[w] += 1
                    for w in parts[1].split():
                        de[w] += 1
            self._freq_cache = {"en": en, "de": de}
        return self._freq_cache

    def _build_dict(self, dict_path, dict_size, lang):
        freq = self._train_freqs()[lang]
        # atomic: an interrupted build must not leave a truncated cache
        tmp_path = dict_path + ".tmp"
        with open(tmp_path, "w") as f:
            f.write(f"{self.START_MARK}\n{self.END_MARK}\n"
                    f"{self.UNK_MARK}\n")
            for idx, (word, _) in enumerate(
                    sorted(freq.items(), key=lambda x: (-x[1], x[0]))):
                if dict_size > 0 and idx + 3 == dict_size:
                    break
                f.write(word + "\n")
        os.replace(tmp_path, dict_path)

    def _load_dict(self, lang, dict_size):
        dict_path = f"{self.data_file}.{lang}_{dict_size}.dict"
        if not os.path.exists(dict_path):
            self._build_dict(dict_path, dict_size, lang)
        with open(dict_path) as f:
            return {line.strip(): idx for idx, line in enumerate(f)}

    def _load_data(self):
        start_id = self.src_dict[self.START_MARK]
        end_id = self.src_dict[self.END_MARK]
        unk_id = self.src_dict[self.UNK_MARK]
        src_col = 0 if self.lang == "en" else 1
        trg_col = 1 - src_col
        self.src_ids, self.trg_ids, self.trg_ids_next = [], [], []
        with tarfile.open(self.data_file) as tf:
            for line in tf.extractfile(f"wmt16/{self.mode}"):
                parts = line.decode().strip().split("\t")
                if len(parts) != 2:
                    continue
                src = ([start_id]
                       + [self.src_dict.get(w, unk_id)
                          for w in parts[src_col].split()]
                       + [end_id])
                trg_body = [self.trg_dict.get(w, unk_id)
                            for w in parts[trg_col].split()]
                self.src_ids.append(src)
                self.trg_ids.append([start_id] + trg_body)
                self.trg_ids_next.append(trg_body + [end_id])

    def __getitem__(self, idx):
        return (np.array(self.src_ids[idx]),
                np.array(self.trg_ids[idx]),
                np.array(self.trg_ids_next[idx]))

    def __len__(self):
        return len(self.src_ids)
