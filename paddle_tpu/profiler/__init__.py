"""paddle.profiler — host+device profiling on the observability layer.

Capability parity with the reference profiler (reference:
python/paddle/profiler/profiler.py:79 — Profiler(targets, scheduler,
on_trace_ready), RecordEvent, make_scheduler, export_chrome_tracing; device
side backed by CUPTI fluid/platform/profiler/cuda_tracer.cc). TPU-native:
the device tracer is jax.profiler (XPlane/perfetto trace with XLA op and
TPU step timeline); the host side rides ``paddle_tpu.observability`` — the
dispatcher's op hook supplies per-op call counts AND host latency, the
span tracer collects compile/collective/autotune ranges from every
instrumented layer, and ``export_chrome_tracing`` merges them into one
chrome trace. ``timer_only`` mode reports step throughput (steps/sec,
examples/sec) without starting the device tracer.
"""
from __future__ import annotations

import contextlib
import json
import os
import time
from collections import defaultdict
from enum import Enum
from typing import Callable, Iterable, Optional

import jax

from ..observability import metrics as _metrics
from ..observability import trace as _trace


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1          # accepted alias (reference parity)
    CUSTOM_DEVICE = 3
    TPU = 4


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class SortedKeys(Enum):
    """Summary sort orders (reference profiler.SortedKeys subset — host
    timeline only; device time lives in the jax trace)."""
    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    Calls = 3


def make_scheduler(*, closed: int, ready: int, record: int, repeat: int = 0,
                   skip_first: int = 0) -> Callable[[int], ProfilerState]:
    """reference profiler.py make_scheduler — step-phase state machine."""
    cycle = closed + ready + record

    def scheduler(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        step -= skip_first
        if repeat and step >= repeat * cycle:
            return ProfilerState.CLOSED
        pos = step % cycle
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == cycle - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return scheduler


#: chrome-trace tid blocks per span category, so each instrumented layer
#: renders as its own named row in the viewer
_CAT_TID_BASE = {"user": 0, "dispatch": 100, "compile": 200,
                 "collective": 300, "autotune": 400,
                 # 500 is the unknown-category fallback lane; io/device
                 # get full 100-slot lanes so a process with many traced
                 # threads cannot bleed io spans into the device lane
                 "io": 600, "device": 700}


def _trace_rank() -> Optional[int]:
    """This process's trainer rank — read from the launcher env, not
    the jax backend. None when not launched distributed (rank 0 of a
    real launch still reports 0, so its trace filename stays globbable
    alongside its peers')."""
    from ..observability.flight import env_rank
    return env_rank()


def export_chrome_tracing(dir_name: str, worker_name: Optional[str] = None):
    """on_trace_ready callback writing ONE merged chrome trace: user
    RecordEvent ranges + every span the observability tracer collected
    while recording (dispatch ops, to_static/SOT compiles, collectives,
    autotune probes). The jax device trace (perfetto) lands in the same
    dir.

    Distributed runs: the default filename carries the trainer rank
    (``worker_r1_host_ops.json``) and, when ``fleet.clock_sync`` has run
    in this process, a ``clock_sync`` metadata event embeds the rank's
    perf_counter offset vs rank 0 — ``tools/fleet_trace.py`` reads it to
    merge every rank's file onto one aligned timeline."""
    def handler(prof: "Profiler"):
        os.makedirs(dir_name, exist_ok=True)
        rank = _trace_rank()
        # distributed launches (rank 0 included) get worker_rN so ONE
        # worker_r*_host_ops.json glob collects the whole fleet
        default_name = "worker" if rank is None else f"worker_r{rank}"
        rank = rank or 0
        fname = os.path.join(
            dir_name, f"{worker_name or default_name}_host_ops.json")
        events = []
        for name, t0, t1 in prof._events:
            events.append({"name": name, "cat": "user", "ph": "X",
                           "pid": 0, "tid": 0,
                           "ts": int(t0 * 1e6),
                           "dur": max(int((t1 - t0) * 1e6), 0)})
        for name, cat, t0, t1, tid, args in prof._spans:
            ev = {"name": name, "cat": cat, "ph": "X", "pid": 0,
                  "tid": _CAT_TID_BASE.get(cat, 500) + tid,
                  "ts": int(t0 * 1e6),
                  "dur": max(int((t1 - t0) * 1e6), 0)}
            if args:
                ev["args"] = dict(args)
            events.append(ev)
        events.sort(key=lambda e: (e["ts"], e["tid"]))
        meta = [{"name": "process_name", "ph": "M", "pid": 0,
                 "args": {"name": "paddle_tpu host"
                          + (f" (rank {rank})" if rank else "")}}]
        try:
            from ..observability import fleet as _fleet
            cs = _fleet.clock_state()
        except Exception:
            cs = None
        if cs is not None:
            # self-describing alignment: the merger needs no side file
            meta.append({"name": "clock_sync", "ph": "M", "pid": 0,
                         "args": {
                             "rank": rank, "world": cs.get("world"),
                             "offset_vs_rank0_s":
                                 cs["offsets"].get(rank, 0.0),
                             "skew_bound_s": cs.get("skew_bound_s"),
                             "synced_at_perf_counter":
                                 cs.get("synced_at_perf_counter")}})
        else:
            meta.append({"name": "clock_sync", "ph": "M", "pid": 0,
                         "args": {"rank": rank,
                                  "offset_vs_rank0_s": None}})
        if prof._spans_dropped:
            # truncation marker: the buffer overflowed, the timeline is
            # incomplete — tooling must not read it as full coverage
            meta.append({"name": "spans_dropped", "ph": "M", "pid": 0,
                         "args": {"count": prof._spans_dropped}})
        meta += [{"name": "thread_name", "ph": "M", "pid": 0,
                  "tid": base, "args": {"name": cat}}
                 for cat, base in sorted(_CAT_TID_BASE.items(),
                                         key=lambda kv: kv[1])]
        with open(fname, "w") as f:
            json.dump({"traceEvents": meta + events}, f)
        prof.trace_path = fname
    return handler


class RecordEvent:
    """User-scoped range marker (reference profiler/utils.py RecordEvent).
    Shows in the host-op summary, the merged chrome trace, and, under an
    active jax trace, as a TraceAnnotation on the device timeline."""

    def __init__(self, name: str, event_type=None):
        self.name = name
        self._jax_ctx = None
        self._t0 = None

    def begin(self):
        self._t0 = time.perf_counter()
        try:
            self._jax_ctx = jax.profiler.TraceAnnotation(self.name)
            self._jax_ctx.__enter__()
        except Exception:
            self._jax_ctx = None

    def end(self):
        if self._jax_ctx is not None:
            self._jax_ctx.__exit__(None, None, None)
        if self._t0 is None:
            return
        t1 = time.perf_counter()
        if _ACTIVE is not None:
            # the active profiler exports _events itself — adding to the
            # trace buffer too would render every user range twice
            _ACTIVE._events.append((self.name, self._t0, t1))
        else:
            _trace.add_complete(self.name, "user", self._t0, t1)
        self._t0 = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


_ACTIVE: Optional["Profiler"] = None

# Step-timer metrics (collection gated by FLAGS_enable_metrics)
_m_steps = _metrics.counter(
    "paddle_tpu_train_steps_total",
    "Profiler-observed training steps.")
_m_step_time = _metrics.histogram(
    "paddle_tpu_train_step_seconds", "Wall time per training step.")
_m_steps_per_s = _metrics.gauge(
    "paddle_tpu_steps_per_second",
    "Throughput of the most recent profiler-observed step.")
_m_examples_per_s = _metrics.gauge(
    "paddle_tpu_examples_per_second",
    "Examples/sec of the most recent step (step() called with "
    "num_samples).")


class Profiler:
    """reference profiler.py:79 Profiler. Usage::

        with profiler.Profiler(targets=[...], scheduler=(2, 5)) as p:
            for step, batch in enumerate(loader):
                train_step(batch)
                p.step(num_samples=batch_size)
        p.summary()
    """

    def __init__(self, *, targets: Optional[Iterable] = None,
                 scheduler=None, on_trace_ready: Optional[Callable] = None,
                 timer_only: bool = False, record_shapes: bool = False,
                 profile_memory: bool = False, with_flops: bool = False):
        self.targets = list(targets or [ProfilerTarget.CPU,
                                        ProfilerTarget.TPU])
        if isinstance(scheduler, tuple):
            start, end = scheduler
            scheduler = make_scheduler(closed=max(start, 0), ready=0,
                                       record=end - start, repeat=1)
        self.scheduler = scheduler or (lambda step: ProfilerState.RECORD)
        self.on_trace_ready = on_trace_ready
        self.timer_only = timer_only
        self._step = 0
        self._state = ProfilerState.CLOSED
        self._events = []                 # RecordEvent: (name, t0, t1)
        self._spans = []                  # harvested observability spans
        self._spans_dropped = 0
        self._op_stats = defaultdict(lambda: [0, 0.0, 0.0])  # n, total, max
        self._step_times = []
        self._step_samples = []
        self._step_t0 = None
        self._hook_handle = None
        self._device_trace_dir = None
        self._host_tracing = False
        self.trace_path = None

    # ---------------------------------------------------------------- hooks
    def _op_hook(self, op_name, inputs, outputs, attrs, duration=0.0):
        if self._state in (ProfilerState.RECORD,
                           ProfilerState.RECORD_AND_RETURN):
            st = self._op_stats[op_name]
            st[0] += 1
            st[1] += duration
            if duration > st[2]:
                st[2] = duration

    # ---------------------------------------------------------------- state
    def start(self):
        global _ACTIVE
        _ACTIVE = self
        # per-session hygiene: a restarted profiler must not report the
        # previous session's events/op stats/step timings
        self._events = []
        self._spans = []
        self._spans_dropped = 0
        self._op_stats = defaultdict(lambda: [0, 0.0, 0.0])
        self._step_times = []
        self._step_samples = []
        self._step = 0
        self._step_t0 = time.perf_counter()
        from ..core import dispatch
        if self._hook_handle is None:
            dispatch.register_op_hook(self._op_hook)
            self._hook_handle = self._op_hook
        self._transition(self.scheduler(self._step))
        return self

    def stop(self):
        global _ACTIVE
        self._transition(ProfilerState.CLOSED)
        if self._hook_handle is not None:
            from ..core import dispatch
            dispatch.unregister_op_hook(self._hook_handle)
            self._hook_handle = None
        _ACTIVE = None
        if self.on_trace_ready is not None:
            self.on_trace_ready(self)

    def step(self, num_samples: Optional[int] = None):
        now = time.perf_counter()
        if self._step_t0 is not None:
            dt = now - self._step_t0
            self._step_times.append(dt)
            if num_samples:
                self._step_samples.append(num_samples)
            if _metrics.enabled() and dt > 0:
                _m_steps.inc()
                _m_step_time.observe(dt)
                _m_steps_per_s.set(1.0 / dt)
                if num_samples:
                    _m_examples_per_s.set(num_samples / dt)
        self._step_t0 = now
        self._step += 1
        self._transition(self.scheduler(self._step))

    def _transition(self, new_state: ProfilerState):
        was_rec = self._state in (ProfilerState.RECORD,
                                  ProfilerState.RECORD_AND_RETURN)
        now_rec = new_state in (ProfilerState.RECORD,
                                ProfilerState.RECORD_AND_RETURN)
        if now_rec and not was_rec:
            # host span collection rides the same window as the device
            # trace; RecordEvent/_op_stats collection is hook-side
            if not self.timer_only:
                _trace.clear()
                _trace.activate()
                self._host_tracing = True
                self._device_trace_dir = os.environ.get(
                    "PADDLE_PROFILER_TRACE_DIR", "/tmp/paddle_tpu_trace")
                try:
                    jax.profiler.start_trace(self._device_trace_dir)
                except Exception:
                    self._device_trace_dir = None
        if was_rec and not now_rec:
            if self._host_tracing:
                _trace.deactivate()
                self._spans_dropped += _trace.dropped()
                self._spans.extend(_trace.drain())
                self._host_tracing = False
                if self._spans_dropped:
                    import warnings
                    warnings.warn(
                        f"profiler span buffer overflowed: "
                        f"{self._spans_dropped} span(s) dropped — the "
                        f"exported timeline is truncated (shorten the "
                        f"record window)")
            if self._device_trace_dir is not None:
                try:
                    jax.profiler.stop_trace()
                except Exception:
                    pass
                self._device_trace_dir = None
        self._state = new_state

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -------------------------------------------------------------- report
    def step_info(self, unit: Optional[str] = None) -> str:
        """Throughput line for timer_only mode (reference
        profiler/timer.py benchmark().step_info)."""
        if not self._step_times:
            return "no steps recorded"
        n = len(self._step_times)
        total = sum(self._step_times)
        avg = total / n
        ips = (1.0 / avg) if avg > 0 else 0.0
        out = (f"steps: {n} avg_step: {avg * 1e3:.3f} ms "
               f"steps/sec: {ips:.3f}")
        if self._step_samples and total > 0:
            # examples/sec from the num_samples the caller fed to step()
            out += (f" {unit or 'examples'}/sec: "
                    f"{sum(self._step_samples) / total:.3f}")
        elif unit:
            out += f" {unit}/sec: {ips:.3f}"
        return out

    @staticmethod
    def _sort_key(sorted_by):
        if sorted_by in (None, SortedKeys.CPUTotal, "time", "cpu_total"):
            return lambda kv: -kv[1][1]
        if sorted_by in (SortedKeys.Calls, "calls"):
            return lambda kv: -kv[1][0]
        if sorted_by in (SortedKeys.CPUAvg, "avg", "cpu_avg"):
            return lambda kv: -(kv[1][1] / kv[1][0] if kv[1][0] else 0.0)
        if sorted_by in (SortedKeys.CPUMax, "max", "cpu_max"):
            return lambda kv: -kv[1][2]
        raise ValueError(f"unsupported sorted_by {sorted_by!r}")

    def summary(self, sorted_by=None, op_detail: bool = True,
                thread_sep: bool = False, time_unit: str = "ms"):
        """Print the host-op table (calls + real host latency from the
        dispatch hook) and, in timer_only mode, step throughput. Returns
        ``{op_name: calls}`` (stable reporting surface)."""
        scale = {"s": 1.0, "ms": 1e3, "us": 1e6}.get(time_unit, 1e3)
        rows = sorted(self._op_stats.items(), key=self._sort_key(sorted_by))
        line = "-" * 78
        print(line)
        print(f"{'op':<32}{'calls':>8}{'total(' + time_unit + ')':>14}"
              f"{'avg(' + time_unit + ')':>12}{'max(' + time_unit + ')':>12}")
        print(line)
        for name, (n, tot, mx) in rows[:40]:
            avg = tot / n if n else 0.0
            print(f"{name:<32}{n:>8}{tot * scale:>14.3f}"
                  f"{avg * scale:>12.3f}{mx * scale:>12.3f}")
        print(line)
        if self._step_times:
            print(self.step_info())
        if self._events:
            print("user ranges:")
            for name, t0, t1 in self._events[:20]:
                print(f"  {name}: {(t1 - t0) * 1e3:.3f} ms")
        return {name: n for name, (n, _tot, _mx) in rows}

    def op_stats(self) -> dict:
        """Raw per-op host stats: {op: {"calls", "total_s", "max_s"}}."""
        return {name: {"calls": n, "total_s": tot, "max_s": mx}
                for name, (n, tot, mx) in self._op_stats.items()}


@contextlib.contextmanager
def profile(**kwargs):
    p = Profiler(**kwargs)
    p.start()
    try:
        yield p
    finally:
        p.stop()


__all__ = ["Profiler", "ProfilerTarget", "ProfilerState", "RecordEvent",
           "SortedKeys", "make_scheduler", "export_chrome_tracing",
           "profile"]
