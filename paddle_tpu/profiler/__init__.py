"""paddle.profiler — host+device profiling.

Capability parity with the reference profiler (reference:
python/paddle/profiler/profiler.py:79 — Profiler(targets, scheduler,
on_trace_ready), RecordEvent, make_scheduler, export_chrome_tracing; device
side backed by CUPTI fluid/platform/profiler/cuda_tracer.cc). TPU-native:
the device tracer is jax.profiler (XPlane/perfetto trace with XLA op and
TPU step timeline); the host-op timeline comes from the dispatcher's op
hook, giving per-op call counts and host latencies without codegen.
"""
from __future__ import annotations

import contextlib
import json
import os
import time
from collections import defaultdict
from enum import Enum
from typing import Callable, Iterable, Optional

import jax


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1          # accepted alias (reference parity)
    CUSTOM_DEVICE = 3
    TPU = 4


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


def make_scheduler(*, closed: int, ready: int, record: int, repeat: int = 0,
                   skip_first: int = 0) -> Callable[[int], ProfilerState]:
    """reference profiler.py make_scheduler — step-phase state machine."""
    cycle = closed + ready + record

    def scheduler(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        step -= skip_first
        if repeat and step >= repeat * cycle:
            return ProfilerState.CLOSED
        pos = step % cycle
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == cycle - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return scheduler


def export_chrome_tracing(dir_name: str, worker_name: Optional[str] = None):
    """on_trace_ready callback writing the collected host-op events as a
    chrome trace; the jax device trace (perfetto) lands in the same dir."""
    def handler(prof: "Profiler"):
        os.makedirs(dir_name, exist_ok=True)
        fname = os.path.join(
            dir_name, f"{worker_name or 'worker'}_host_ops.json")
        events = [{"name": name, "ph": "X", "pid": 0, "tid": 0,
                   "ts": int(t0 * 1e6), "dur": int((t1 - t0) * 1e6)}
                  for name, t0, t1 in prof._events]
        with open(fname, "w") as f:
            json.dump({"traceEvents": events}, f)
        prof.trace_path = fname
    return handler


class RecordEvent:
    """User-scoped range marker (reference profiler/utils.py RecordEvent).
    Shows in the host-op summary and, under an active jax trace, as a
    TraceAnnotation on the device timeline."""

    def __init__(self, name: str, event_type=None):
        self.name = name
        self._jax_ctx = None
        self._t0 = None

    def begin(self):
        self._t0 = time.perf_counter()
        try:
            self._jax_ctx = jax.profiler.TraceAnnotation(self.name)
            self._jax_ctx.__enter__()
        except Exception:
            self._jax_ctx = None
        if _ACTIVE is not None:
            _ACTIVE._begin_event(self.name, self._t0)

    def end(self):
        if self._jax_ctx is not None:
            self._jax_ctx.__exit__(None, None, None)
        if _ACTIVE is not None and self._t0 is not None:
            _ACTIVE._events.append((self.name, self._t0,
                                    time.perf_counter()))

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


_ACTIVE: Optional["Profiler"] = None


class Profiler:
    """reference profiler.py:79 Profiler. Usage::

        with profiler.Profiler(targets=[...], scheduler=(2, 5)) as p:
            for step, batch in enumerate(loader):
                train_step(batch)
                p.step()
        p.summary()
    """

    def __init__(self, *, targets: Optional[Iterable] = None,
                 scheduler=None, on_trace_ready: Optional[Callable] = None,
                 timer_only: bool = False, record_shapes: bool = False,
                 profile_memory: bool = False, with_flops: bool = False):
        self.targets = list(targets or [ProfilerTarget.CPU,
                                        ProfilerTarget.TPU])
        if isinstance(scheduler, tuple):
            start, end = scheduler
            scheduler = make_scheduler(closed=max(start, 0), ready=0,
                                       record=end - start, repeat=1)
        self.scheduler = scheduler or (lambda step: ProfilerState.RECORD)
        self.on_trace_ready = on_trace_ready
        self.timer_only = timer_only
        self._step = 0
        self._state = ProfilerState.CLOSED
        self._events = []                 # (name, t0, t1)
        self._op_stats = defaultdict(lambda: [0, 0.0])   # name -> [n, time]
        self._hook_handle = None
        self._device_trace_dir = None
        self.trace_path = None

    # ---------------------------------------------------------------- hooks
    def _op_hook(self, op_name, inputs, outputs, attrs):
        if self._state in (ProfilerState.RECORD,
                           ProfilerState.RECORD_AND_RETURN):
            self._op_stats[op_name][0] += 1

    def _begin_event(self, name, t0):
        pass

    # ---------------------------------------------------------------- state
    def start(self):
        global _ACTIVE
        _ACTIVE = self
        from ..core import dispatch
        if self._hook_handle is None:
            dispatch.register_op_hook(self._op_hook)
            self._hook_handle = self._op_hook
        self._transition(self.scheduler(self._step))
        return self

    def stop(self):
        global _ACTIVE
        self._transition(ProfilerState.CLOSED)
        if self._hook_handle is not None:
            from ..core import dispatch
            dispatch.unregister_op_hook(self._hook_handle)
            self._hook_handle = None
        _ACTIVE = None
        if self.on_trace_ready is not None:
            self.on_trace_ready(self)

    def step(self):
        self._step += 1
        self._transition(self.scheduler(self._step))

    def _transition(self, new_state: ProfilerState):
        was_rec = self._state in (ProfilerState.RECORD,
                                  ProfilerState.RECORD_AND_RETURN)
        now_rec = new_state in (ProfilerState.RECORD,
                                ProfilerState.RECORD_AND_RETURN)
        if now_rec and not was_rec and not self.timer_only:
            self._device_trace_dir = os.environ.get(
                "PADDLE_PROFILER_TRACE_DIR", "/tmp/paddle_tpu_trace")
            try:
                jax.profiler.start_trace(self._device_trace_dir)
            except Exception:
                self._device_trace_dir = None
        if was_rec and not now_rec and self._device_trace_dir is not None:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._device_trace_dir = None
        self._state = new_state

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -------------------------------------------------------------- report
    def summary(self, sorted_by=None, op_detail: bool = True,
                thread_sep: bool = False, time_unit: str = "ms"):
        rows = sorted(self._op_stats.items(), key=lambda kv: -kv[1][0])
        line = "-" * 48
        print(line)
        print(f"{'op':<32}{'calls':<8}")
        print(line)
        for name, (n, _) in rows[:40]:
            print(f"{name:<32}{n:<8}")
        print(line)
        if self._events:
            print("user ranges:")
            for name, t0, t1 in self._events[:20]:
                print(f"  {name}: {(t1 - t0) * 1e3:.3f} ms")
        return {name: n for name, (n, _) in rows}


@contextlib.contextmanager
def profile(**kwargs):
    p = Profiler(**kwargs)
    p.start()
    try:
        yield p
    finally:
        p.stop()


__all__ = ["Profiler", "ProfilerTarget", "ProfilerState", "RecordEvent",
           "make_scheduler", "export_chrome_tracing", "profile"]
