"""Continuous-batching LLM serving over paged KV caches.

Reference surface: the block-attention serving op family
(phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu,
fused_multi_transformer cached decoding) that PaddleNLP's serving stack
drives. TPU-native redesign: the whole decode tick for every in-flight
request is ONE jitted SPMD-friendly program — paged K/V caches live as
donated device arrays, a host-side BlockManager owns the physical-block
free list, and admission/eviction is plain Python between ticks:

* prefill runs per request in block_size chunks (two compiled shapes:
  a full chunk and each remainder), appending K/V pages via
  ``nn.functional.block_multihead_attention``;
* decode runs ALL active slots in one (B, 1) step; idle slots point at a
  reserved trash block so the compiled program never branches on
  occupancy;
* positions are per-slot (each sequence is at a different length — the
  batch shares one program, not one position): RoPE offsets for Llama,
  learned-position gathers for GPT (architecture adapters `_LlamaArch` /
  `_GPTArch`).

Greedy sampling v1; numerics are locked to the training models by
token-parity tests against ``LlamaForCausalLM.generate`` and a
full-recompute GPT greedy loop.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor

__all__ = ["BlockManager", "Request", "PagedEngine", "LlamaPagedEngine",
           "GPTPagedEngine"]


class BlockManager:
    """Physical-block free list (block 0 is the reserved trash block idle
    slots write into)."""

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError("need at least 2 blocks (one is reserved)")
        self._free = list(range(num_blocks - 1, 0, -1))

    @property
    def available(self) -> int:
        return len(self._free)

    def allocate(self, n: int) -> List[int]:
        if n > len(self._free):
            raise MemoryError(
                f"paged KV cache exhausted: need {n} blocks, "
                f"{len(self._free)} free")
        return [self._free.pop() for _ in range(n)]

    def release(self, blocks: List[int]):
        self._free.extend(b for b in blocks if b != 0)


@dataclass
class Request:
    rid: int
    prompt: List[int]
    max_new_tokens: int = 32
    temperature: float = 0.0          # 0 = greedy
    top_p: float = 1.0
    generated: List[int] = field(default_factory=list)

    @property
    def seq_len(self) -> int:
        return len(self.prompt) + len(self.generated)


class _LlamaArch:
    """Architecture adapter: per-chunk forward for LlamaForCausalLM."""

    def __init__(self, model):
        self.model = model
        self.cfg = model.cfg
        self.num_kv_heads = model.cfg.num_kv_heads or model.cfg.num_heads

    def forward_chunk(self, tokens, start, attend):
        from paddle_tpu import ops
        from ..models.llama import rotary_embedding

        model = self.model
        cfg = self.cfg
        B, T = tokens.shape
        nh = cfg.num_heads
        hd = cfg.hidden_size // nh
        nkv = self.num_kv_heads
        x = model.model.embed_tokens(Tensor(tokens))
        for li, blk in enumerate(model.model.layers):
            ln = blk.input_layernorm(x)
            q = ops.reshape(blk.self_attn.q_proj(ln), [B, T, nh, hd])
            k = ops.reshape(blk.self_attn.k_proj(ln), [B, T, nkv, hd])
            v = ops.reshape(blk.self_attn.v_proj(ln), [B, T, nkv, hd])
            q = rotary_embedding(q, cfg.rope_theta, pos_offset=start)
            k = rotary_embedding(k, cfg.rope_theta, pos_offset=start)
            out = attend(li, q, k, v)
            x = x + blk.self_attn.o_proj(
                ops.reshape(out, [B, T, nh * hd]))
            x = x + blk.mlp(blk.post_attention_layernorm(x))
        x = model.model.norm(x)
        last = Tensor(x._data[:, -1:, :])
        if model.lm_head is None:
            return ops.matmul(last, model.model.embed_tokens.weight,
                              transpose_y=True)
        return model.lm_head(last)


class _GPTArch:
    """Architecture adapter for GPTForCausalLM (learned positions, fused
    qkv, tied head)."""

    def __init__(self, model):
        self.model = model
        self.cfg = model.cfg
        self.num_kv_heads = model.cfg.num_heads
        self.max_positions = model.cfg.max_seq_len

    def forward_chunk(self, tokens, start, attend):
        from paddle_tpu import ops

        m = self.model.gpt
        cfg = self.cfg
        B, T = tokens.shape
        nh = cfg.num_heads
        hd = cfg.hidden_size // nh
        # learned positional embeddings at per-slot positions
        pos_idx = (start[:, None]
                   + jnp.arange(T, dtype=start.dtype)[None, :])
        pos_emb = jnp.take(m.wpe.weight._data, pos_idx, axis=0)
        x = m.wte(Tensor(tokens)) + Tensor(pos_emb)
        for li, blk in enumerate(m.blocks):
            ln = blk.ln1(x)
            qkv = blk.attn.qkv_proj(ln)
            q, k, v = ops.split(qkv, 3, axis=-1)
            q = ops.reshape(q, [B, T, nh, hd])
            k = ops.reshape(k, [B, T, nh, hd])
            v = ops.reshape(v, [B, T, nh, hd])
            out = attend(li, q, k, v)
            x = x + blk.attn.out_proj(ops.reshape(out, [B, T, nh * hd]))
            x = x + blk.mlp(blk.ln2(x))
        x = m.ln_f(x)
        last = Tensor(x._data[:, -1:, :])
        return ops.matmul(last, m.wte.weight, transpose_y=True)


def _pick_arch(model):
    from ..models.gpt import GPTForCausalLM
    from ..models.llama import LlamaForCausalLM
    if isinstance(model, LlamaForCausalLM):
        return _LlamaArch(model)
    if isinstance(model, GPTForCausalLM):
        return _GPTArch(model)
    raise TypeError(
        f"PagedEngine supports LlamaForCausalLM / GPTForCausalLM (or "
        f"subclasses), got {type(model).__name__}")


def _tuned_decode_block_size(cfg, nkv, max_batch, max_blocks_per_seq,
                             candidates=(8, 16, 32)) -> int:
    """Measured KV page size for the decode tick on this chip.

    Probes one paged-attention decode step (T=1, full batch) per
    candidate on zero caches sized to the engine's real geometry; the
    winner persists in the autotune cache (ops/pallas/autotune.py), so
    one process per chip ever pays the probe. Off-TPU: 16.
    """
    from ..ops.pallas import autotune as at

    default = 16
    if not at.should_autotune():
        return default
    head_dim = cfg.hidden_size // cfg.num_heads
    key = at.make_key("serving_decode_block", nkv=nkv, d=head_dim,
                      b=max_batch)
    cached = at.get_cache().get(key)
    if cached is not None:
        return int(cached)

    import paddle_tpu.nn.functional as F
    from ..core.tensor import Tensor

    prepared = {}
    nvar = 3

    def run(bs, i):
        entry = prepared.get(bs)
        if entry is None:
            import jax
            nb = max_batch * max_blocks_per_seq + 1
            kc = jnp.zeros((nb, bs, nkv, head_dim), jnp.bfloat16)
            vc = jnp.zeros_like(kc)
            tables = jnp.asarray(
                np.arange(1, max_batch * max_blocks_per_seq + 1)
                .reshape(max_batch, max_blocks_per_seq).astype(np.int32))
            # mid-stream decode: every sequence half way into its pages
            seq_lens = jnp.full((max_batch,),
                                (max_blocks_per_seq // 2) * bs, jnp.int32)
            # distinct probe queries per timed iteration (replay-caching
            # backends fake repeat-identical executions)
            q_vars = [jnp.asarray(np.random.RandomState(v).randn(
                max_batch, 1, cfg.num_heads, head_dim), jnp.bfloat16)
                for v in range(nvar)]
            nk = jnp.asarray(np.random.RandomState(9).randn(
                max_batch, 1, nkv, head_dim), jnp.bfloat16)

            def tick(qa, kca, vca, ta, sla, nka):
                out, _, _ = F.block_multihead_attention(
                    Tensor(qa), Tensor(kca), Tensor(vca), Tensor(ta),
                    Tensor(sla), new_k=Tensor(nka), new_v=Tensor(nka),
                    causal=True)
                return out._data

            def chained(qa, kca, vca, ta, sla, nka):
                # chain ticks data-dependently (out is q-shaped) so
                # device time dominates per-call dispatch/transport
                return jax.lax.fori_loop(
                    0, 128,
                    lambda _, qq: tick(qq, kca, vca, ta, sla, nka), qa)

            entry = prepared[bs] = (jax.jit(chained), q_vars,
                                    (kc, vc, tables, seq_lens, nk))
        fn, q_vars, rest = entry
        return fn(q_vars[i % nvar], *rest)

    return int(at.autotune(key, list(candidates), run, default,
                           warmup=2, iters=5))


class PagedEngine:
    """Continuous-batching engine for causal LMs (paged KV caches)."""

    def __init__(self, model, *, max_batch: int = 8,
                 block_size: Optional[int] = 16,
                 num_blocks: int = 256, max_blocks_per_seq: int = 32,
                 eos_id: Optional[int] = None, seed: int = 0,
                 kv_dtype=None):
        self.model = model
        self.arch = _pick_arch(model)
        self.cfg = model.cfg
        self.max_batch = max_batch
        if block_size is None:
            # measured choice for this chip/model-geometry (falls back to
            # 16 off-TPU); ops/pallas/autotune.py caches winners on disk
            block_size = _tuned_decode_block_size(
                self.cfg, self.arch.num_kv_heads, max_batch,
                max_blocks_per_seq)
        self.block_size = block_size
        self.max_blocks_per_seq = max_blocks_per_seq
        self.eos_id = eos_id
        cfg = self.cfg
        self.head_dim = cfg.hidden_size // cfg.num_heads
        nkv = self.arch.num_kv_heads
        self.num_kv_heads = nkv

        self.bm = BlockManager(num_blocks)
        self._total_usable = num_blocks - 1
        # K/V pages live in the model's compute dtype (the attention math
        # upcasts to f32 inside the kernel) — a bf16 model must not pay
        # 2x KV HBM for fp32 pages; on a 16 GB chip KV capacity IS the
        # serving ceiling.
        if kv_dtype is None:
            kv_dtype = next(
                (p._data.dtype for p in model.parameters()
                 if jnp.issubdtype(p._data.dtype, jnp.floating)),
                jnp.float32)
        self.kv_dtype = jnp.dtype(kv_dtype)
        self.kc = [jnp.zeros((num_blocks, block_size, nkv, self.head_dim),
                             self.kv_dtype) for _ in range(cfg.num_layers)]
        self.vc = [jnp.zeros_like(self.kc[0])
                   for _ in range(cfg.num_layers)]

        self.tables = np.zeros((max_batch, max_blocks_per_seq), np.int32)
        self.seq_lens = np.ones((max_batch,), np.int32)  # idle: len 1
        self.last_token = np.zeros((max_batch,), np.int32)
        self.slots: List[Optional[Request]] = [None] * max_batch
        self.slot_blocks: List[List[int]] = [[] for _ in range(max_batch)]
        self.queue: List[Request] = []
        self.rejected: Dict[int, str] = {}
        self._params = [p for p in model.parameters()]
        # one jit wrapper: jax.jit itself specializes per (B, T) shape
        self._fn = jax.jit(self._forward, donate_argnums=(1, 2))
        self._key = jax.random.key(seed)
        self._done: List[Request] = []
        self._rid = 0

    # ---------------------------------------------------------------- API
    def add_request(self, prompt_ids, max_new_tokens: int = 32,
                    temperature: float = 0.0, top_p: float = 1.0) -> int:
        prompt = [int(t) for t in prompt_ids]
        if not prompt:
            raise ValueError("add_request: prompt must be non-empty")
        if max_new_tokens < 1:
            raise ValueError("add_request: max_new_tokens must be >= 1")
        if not 0.0 < top_p <= 1.0:
            raise ValueError("add_request: top_p must be in (0, 1]")
        if not temperature >= 0.0:   # also rejects NaN
            raise ValueError("add_request: temperature must be >= 0")
        max_pos = getattr(self.arch, "max_positions", None)
        if max_pos is not None and len(prompt) + max_new_tokens > max_pos:
            # learned-position models: a sequence growing past the table
            # would silently clip-gather the last embedding
            raise ValueError(
                f"add_request: prompt ({len(prompt)}) + max_new_tokens "
                f"({max_new_tokens}) exceeds the model's position table "
                f"({max_pos})")
        self._rid += 1
        self.queue.append(Request(self._rid, prompt, max_new_tokens,
                                  temperature=temperature, top_p=top_p))
        return self._rid

    @property
    def num_active(self) -> int:
        return sum(1 for s in self.slots if s is not None)

    def has_work(self) -> bool:
        return bool(self.queue) or self.num_active > 0

    # ----------------------------------------------------------- compute
    def _forward(self, param_arrays, kcs, vcs, tokens, seq_lens, tables,
                 temps, top_ps, key):
        """One chunk for a (B, T) token batch; returns (next-token ids,
        new caches). Traced under jit."""
        import paddle_tpu.nn.functional as F

        params = self._params
        originals = [p._data for p in params]
        for p, a in zip(params, param_arrays):
            p._data = a
        try:
            B, T = tokens.shape
            start = seq_lens - T
            sl_t = Tensor(seq_lens)
            tb_t = Tensor(tables)

            def attend(li, q, k, v):
                out, nkc, nvc = F.block_multihead_attention(
                    q, Tensor(kcs[li]), Tensor(vcs[li]), tb_t, sl_t,
                    new_k=k, new_v=v, causal=True)
                kcs[li] = nkc._data
                vcs[li] = nvc._data
                return out

            logits = self.arch.forward_chunk(tokens, start, attend)
            nxt = self._sample(logits._data[:, -1, :], temps, top_ps, key)
            return nxt.astype(jnp.int32), kcs, vcs
        finally:
            for p, o in zip(params, originals):
                p._data = o

    @staticmethod
    def _sample(logits, temps, top_ps, key):
        """Per-slot greedy / temperature / nucleus sampling — the same
        kernel as ops.top_p_sampling (shared helper), keyed per tick so
        the program is reusable across calls."""
        from ..ops.search import nucleus_sample_ids
        greedy = jnp.argmax(logits, axis=-1)
        safe_t = jnp.maximum(temps, 1e-6)[:, None]
        probs = jax.nn.softmax(logits / safe_t, axis=-1)
        sampled = nucleus_sample_ids(probs, top_ps, key)[:, 0]
        return jnp.where(temps > 0, sampled, greedy)

    def _run_chunk(self, tokens_np, seq_lens_np, tables_np,
                   temps_np, top_ps_np):
        self._key, sub = jax.random.split(self._key)
        # serving always runs eval-mode (dropout off); restore the
        # caller's training flag afterwards — the engine must not mutate
        # a model a training loop is still using
        was_training = getattr(self.model, "training", False)
        if was_training:
            self.model.eval()
        try:
            nxt, self.kc, self.vc = self._fn(
                [p._data for p in self._params], self.kc, self.vc,
                jnp.asarray(tokens_np), jnp.asarray(seq_lens_np),
                jnp.asarray(tables_np),
                jnp.asarray(temps_np, jnp.float32),
                jnp.asarray(top_ps_np, jnp.float32), sub)
        finally:
            if was_training:
                self.model.train()
        return np.asarray(nxt)

    # -------------------------------------------------------- scheduling
    def _blocks_needed(self, length: int) -> int:
        return -(-length // self.block_size)

    def _ensure_blocks(self, slot: int, length: int) -> bool:
        need = self._blocks_needed(length)
        have = len(self.slot_blocks[slot])
        if need > self.max_blocks_per_seq:
            raise MemoryError(
                f"sequence needs {need} blocks > max_blocks_per_seq "
                f"{self.max_blocks_per_seq}")
        if need > have:
            if need - have > self.bm.available:
                return False
            new = self.bm.allocate(need - have)
            for j, b in enumerate(new):
                self.tables[slot, have + j] = b
            self.slot_blocks[slot].extend(new)
        return True

    def _admit(self):
        admitted = []
        for slot in range(self.max_batch):
            if not self.queue or self.slots[slot] is not None:
                continue
            req = self.queue[0]
            prefix_len = len(req.prompt) + len(req.generated)
            need_total = self._blocks_needed(
                len(req.prompt) + req.max_new_tokens)
            if (need_total > self.max_blocks_per_seq
                    or need_total > self._total_usable):
                # reject WITHOUT raising mid-step: completed results from
                # other requests must never be lost to one bad request.
                # Callers read eng.rejected; run_to_completion raises
                # AFTER everything else finished.
                self.queue.pop(0)
                self.rejected[req.rid] = (
                    f"needs {need_total} blocks (max_blocks_per_seq="
                    f"{self.max_blocks_per_seq}, usable="
                    f"{self._total_usable})")
                continue
            if (self._blocks_needed(prefix_len + 1)
                    > self.bm.available):
                break  # head-of-line blocks until memory frees
            self.queue.pop(0)
            self.slots[slot] = req
            self.tables[slot, :] = 0
            self.slot_blocks[slot] = []
            # allocate the prefix blocks NOW so the next admission's
            # availability check sees the reduced pool
            if not self._ensure_blocks(slot, prefix_len):
                raise MemoryError("admission raced cache exhaustion")
            admitted.append(slot)
        if admitted:
            self._prefill_batch(admitted)

    def _prefill_batch(self, slots: List[int]):
        """Prefill every same-tick admission TOGETHER: one (max_batch,
        block_size) chunk program per chunk tick instead of per-request
        [1, t] loops. Each slot's prefix is LEFT-padded to a multiple of
        block_size — padded positions sit at negative sequence positions,
        which the paged-attention kernel drops from the cache write and
        fully masks from attention, so only two compiled shapes exist in
        steady state: (max_batch, block_size) and the (max_batch, 1)
        decode. The final chunk of each slot yields its first sampled
        token."""
        bs = self.block_size
        prefixes = {}
        chunks_of = {}
        pad_of = {}
        for slot in slots:
            req = self.slots[slot]
            prefix = np.asarray(req.prompt + req.generated, np.int32)
            n_chunks = -(-len(prefix) // bs)
            prefixes[slot] = np.concatenate(
                [np.zeros(n_chunks * bs - len(prefix), np.int32), prefix])
            chunks_of[slot] = n_chunks
            pad_of[slot] = n_chunks * bs - len(prefix)
        nxt_of = {}
        for j in range(max(chunks_of.values())):
            tokens = np.zeros((self.max_batch, bs), np.int32)
            seq = np.zeros((self.max_batch,), np.int32)   # 0 = inactive
            temps = np.zeros((self.max_batch,), np.float32)
            top_ps = np.ones((self.max_batch,), np.float32)
            involved = []
            for slot in slots:
                if j >= chunks_of[slot]:
                    continue
                req = self.slots[slot]
                tokens[slot] = prefixes[slot][j * bs:(j + 1) * bs]
                seq[slot] = (j + 1) * bs - pad_of[slot]
                temps[slot] = req.temperature
                top_ps[slot] = req.top_p
                involved.append(slot)
            nxt = self._run_chunk(tokens, seq, self.tables, temps, top_ps)
            for slot in involved:
                if j == chunks_of[slot] - 1:
                    nxt_of[slot] = int(nxt[slot])
        for slot in slots:
            req = self.slots[slot]
            self.seq_lens[slot] = len(req.prompt) + len(req.generated)
            tok = nxt_of[slot]
            req.generated.append(tok)
            self.last_token[slot] = tok
            self._maybe_finish(slot)


    def _evict(self, slot: int):
        """Preempt a running request: release its blocks and requeue it
        for later re-admission (its generated prefix re-prefills then —
        vLLM-style recompute preemption)."""
        req = self.slots[slot]
        self.slots[slot] = None
        self.bm.release(self.slot_blocks[slot])
        self.slot_blocks[slot] = []
        self.tables[slot, :] = 0
        self.seq_lens[slot] = 1
        self.last_token[slot] = 0
        self.queue.append(req)

    def _maybe_finish(self, slot: int):
        req = self.slots[slot]
        if req is None:
            return
        last = req.generated[-1] if req.generated else None
        if (len(req.generated) >= req.max_new_tokens
                or (self.eos_id is not None and last == self.eos_id)):
            self._done.append(req)
            self.slots[slot] = None
            self.bm.release(self.slot_blocks[slot])
            self.slot_blocks[slot] = []
            self.tables[slot, :] = 0
            self.seq_lens[slot] = 1
            self.last_token[slot] = 0

    def step(self) -> Dict[int, List[int]]:
        """One engine tick: admit + prefill queued requests, then a single
        batched decode step for every active slot. Returns {rid:
        generated_tokens} for requests that finished this tick."""
        self._admit()
        active = [i for i, s in enumerate(self.slots) if s is not None]
        if active:
            seq = self.seq_lens.copy()
            skipped = []
            for i in active:
                # the cache holds seq_len-1 positions; the token being fed
                # (the newest sample) lands at position seq_len-1, so the
                # total INCLUDING it is exactly req.seq_len
                seq[i] = self.slots[i].seq_len
                if not self._ensure_blocks(i, int(seq[i])):
                    # OOM: skip this slot's tick. Sentinel 0 — with seq=1
                    # the op would write the token's K/V into position 0
                    # of the slot's first REAL block, corrupting the
                    # cached prompt; seq=0 puts the write at pos -1,
                    # which the kernel drops and fully masks.
                    seq[i] = 0
                    skipped.append(i)
            if skipped and len(skipped) == len(active):
                # every active slot is memory-stalled: nobody can finish
                # to free blocks, so this would livelock. Preempt the
                # youngest request (vLLM recompute-preemption policy) and
                # retry next tick with its blocks available.
                victim = max(skipped, key=lambda i: self.slots[i].rid)
                self._evict(victim)
                return self._drain_done()
            tokens = self.last_token[:, None].astype(np.int32)
            temps = np.zeros((self.max_batch,), np.float32)
            top_ps = np.ones((self.max_batch,), np.float32)
            for i in active:
                temps[i] = self.slots[i].temperature
                top_ps[i] = self.slots[i].top_p
            nxt = self._run_chunk(tokens, seq, self.tables, temps, top_ps)
            for i in active:
                if seq[i] == 0:
                    continue
                req = self.slots[i]
                req.generated.append(int(nxt[i]))
                self.seq_lens[i] = int(seq[i])   # cached positions now
                self.last_token[i] = int(nxt[i])
                self._maybe_finish(i)
        return self._drain_done()

    def _drain_done(self) -> Dict[int, List[int]]:
        """Hand completed requests to the caller and DROP them — a
        long-running server must not retain every request ever served."""
        out = {req.rid: req.generated for req in self._done}
        self._done.clear()
        return out

    def run_to_completion(self, max_ticks: int = 10_000):
        """Drain the queue; returns {rid: generated_tokens}. If any
        request was rejected as never-fitting, raises MemoryError AFTER
        all servable requests completed (their results stay retrievable
        via step()/self.rejected for callers that need partial output)."""
        out: Dict[int, List[int]] = {}
        ticks = 0
        while self.has_work():
            out.update(self.step())
            ticks += 1
            if ticks > max_ticks:
                raise RuntimeError("serving engine did not converge")
        if self.rejected:
            detail = "; ".join(f"request {rid}: {why}"
                               for rid, why in self.rejected.items())
            rejected = dict(self.rejected)
            self.rejected.clear()
            err = MemoryError(f"rejected never-fitting request(s): "
                              f"{detail}")
            # completed generations must survive the raise — callers that
            # catch can still read every successful result
            err.results = out
            err.rejected = rejected
            raise err
        return out


# Backward-compatible names: the generic engine picks the adapter itself.
LlamaPagedEngine = PagedEngine
GPTPagedEngine = PagedEngine
