"""Continuous-batching LLM serving over paged KV caches.

Reference surface: the block-attention serving op family
(phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu,
fused_multi_transformer cached decoding) that PaddleNLP's serving stack
drives. TPU-native redesign: the whole decode tick for every in-flight
request is ONE jitted SPMD-friendly program — paged K/V caches live as
donated device arrays, a host-side BlockManager owns the physical-block
free list, and admission/eviction is plain Python between ticks:

* prefill runs per request in block_size chunks (two compiled shapes:
  a full chunk and each remainder), appending K/V pages via
  ``nn.functional.block_multihead_attention``;
* decode runs ALL active slots in one (B, 1) step; idle slots point at a
  reserved trash block so the compiled program never branches on
  occupancy;
* positions are per-slot (each sequence is at a different length — the
  batch shares one program, not one position): RoPE offsets for Llama,
  learned-position gathers for GPT (architecture adapters `_LlamaArch` /
  `_GPTArch`).

Greedy sampling v1; numerics are locked to the training models by
token-parity tests against ``LlamaForCausalLM.generate`` and a
full-recompute GPT greedy loop.

Resilience contract (see ``inference/resilience.py`` and README "Serving
resilience"): the tick loop never raises — overload, deadline expiry,
memory races and injected faults become per-request terminal statuses
(``FINISHED/SHED/DEADLINE_MISSED/CANCELLED/FAILED``) recorded in
``engine.outcomes``; submitters see :class:`Overloaded` backpressure from
the bounded queue; the replica walks an explicit lifecycle
(``STARTING→WARMING→READY→DEGRADED→DRAINING→STOPPED``) with ``drain()``
and health/readiness probes, and a stalled tick flips it DEGRADED via the
attached watchdog.
"""
from __future__ import annotations

import math
import time
import weakref
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from .resilience import (Overloaded, ReplicaLifecycle, ReplicaState,
                         RequestOutcome, RequestStatus, ResilienceConfig)
from . import resilience as _res

__all__ = ["BlockManager", "Request", "PagedEngine", "LlamaPagedEngine",
           "GPTPagedEngine", "Overloaded", "RequestStatus", "ReplicaState",
           "ResilienceConfig", "RequestOutcome"]


class BlockManager:
    """Physical-block free list (block 0 is the reserved trash block idle
    slots write into)."""

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError("need at least 2 blocks (one is reserved)")
        self._free = list(range(num_blocks - 1, 0, -1))

    @property
    def available(self) -> int:
        return len(self._free)

    def allocate(self, n: int) -> List[int]:
        if n > len(self._free):
            raise MemoryError(
                f"paged KV cache exhausted: need {n} blocks, "
                f"{len(self._free)} free")
        return [self._free.pop() for _ in range(n)]

    def release(self, blocks: List[int]):
        self._free.extend(b for b in blocks if b != 0)


@dataclass
class Request:
    rid: int
    prompt: List[int]
    max_new_tokens: int = 32
    temperature: float = 0.0          # 0 = greedy
    top_p: float = 1.0
    generated: List[int] = field(default_factory=list)
    # --- resilience bookkeeping (engine-managed) ---
    status: str = RequestStatus.QUEUED
    detail: str = ""                  # terminal reason for non-FINISHED
    submit_t: Optional[float] = None
    first_token_t: Optional[float] = None
    finish_t: Optional[float] = None
    token_times: List[float] = field(default_factory=list)
    ttft_deadline_s: Optional[float] = None   # submit → first token
    deadline_s: Optional[float] = None        # submit → completion

    @property
    def seq_len(self) -> int:
        return len(self.prompt) + len(self.generated)


class _LlamaArch:
    """Architecture adapter: per-chunk forward for LlamaForCausalLM."""

    def __init__(self, model):
        self.model = model
        self.cfg = model.cfg
        self.num_kv_heads = model.cfg.num_kv_heads or model.cfg.num_heads

    def forward_chunk(self, tokens, start, attend):
        from paddle_tpu import ops
        from ..models.llama import rotary_embedding

        model = self.model
        cfg = self.cfg
        B, T = tokens.shape
        nh = cfg.num_heads
        hd = cfg.hidden_size // nh
        nkv = self.num_kv_heads
        x = model.model.embed_tokens(Tensor(tokens))
        for li, blk in enumerate(model.model.layers):
            ln = blk.input_layernorm(x)
            q = ops.reshape(blk.self_attn.q_proj(ln), [B, T, nh, hd])
            k = ops.reshape(blk.self_attn.k_proj(ln), [B, T, nkv, hd])
            v = ops.reshape(blk.self_attn.v_proj(ln), [B, T, nkv, hd])
            q = rotary_embedding(q, cfg.rope_theta, pos_offset=start)
            k = rotary_embedding(k, cfg.rope_theta, pos_offset=start)
            out = attend(li, q, k, v)
            x = x + blk.self_attn.o_proj(
                ops.reshape(out, [B, T, nh * hd]))
            x = x + blk.mlp(blk.post_attention_layernorm(x))
        x = model.model.norm(x)
        last = Tensor(x._data[:, -1:, :])
        if model.lm_head is None:
            return ops.matmul(last, model.model.embed_tokens.weight,
                              transpose_y=True)
        return model.lm_head(last)


class _GPTArch:
    """Architecture adapter for GPTForCausalLM (learned positions, fused
    qkv, tied head)."""

    def __init__(self, model):
        self.model = model
        self.cfg = model.cfg
        self.num_kv_heads = model.cfg.num_heads
        self.max_positions = model.cfg.max_seq_len

    def forward_chunk(self, tokens, start, attend):
        from paddle_tpu import ops

        m = self.model.gpt
        cfg = self.cfg
        B, T = tokens.shape
        nh = cfg.num_heads
        hd = cfg.hidden_size // nh
        # learned positional embeddings at per-slot positions
        pos_idx = (start[:, None]
                   + jnp.arange(T, dtype=start.dtype)[None, :])
        pos_emb = jnp.take(m.wpe.weight._data, pos_idx, axis=0)
        x = m.wte(Tensor(tokens)) + Tensor(pos_emb)
        for li, blk in enumerate(m.blocks):
            ln = blk.ln1(x)
            qkv = blk.attn.qkv_proj(ln)
            q, k, v = ops.split(qkv, 3, axis=-1)
            q = ops.reshape(q, [B, T, nh, hd])
            k = ops.reshape(k, [B, T, nh, hd])
            v = ops.reshape(v, [B, T, nh, hd])
            out = attend(li, q, k, v)
            x = x + blk.attn.out_proj(ops.reshape(out, [B, T, nh * hd]))
            x = x + blk.mlp(blk.ln2(x))
        x = m.ln_f(x)
        last = Tensor(x._data[:, -1:, :])
        return ops.matmul(last, m.wte.weight, transpose_y=True)


def _pick_arch(model):
    from ..models.gpt import GPTForCausalLM
    from ..models.llama import LlamaForCausalLM
    if isinstance(model, LlamaForCausalLM):
        return _LlamaArch(model)
    if isinstance(model, GPTForCausalLM):
        return _GPTArch(model)
    raise TypeError(
        f"PagedEngine supports LlamaForCausalLM / GPTForCausalLM (or "
        f"subclasses), got {type(model).__name__}")


def _tuned_decode_block_size(cfg, nkv, max_batch, max_blocks_per_seq,
                             candidates=(8, 16, 32)) -> int:
    """Measured KV page size for the decode tick on this chip.

    Probes one paged-attention decode step (T=1, full batch) per
    candidate on zero caches sized to the engine's real geometry; the
    winner persists in the autotune cache (ops/pallas/autotune.py), so
    one process per chip ever pays the probe. Off-TPU: 16.
    """
    from ..ops.pallas import autotune as at

    default = 16
    if not at.should_autotune():
        return default
    head_dim = cfg.hidden_size // cfg.num_heads
    key = at.make_key("serving_decode_block", nkv=nkv, d=head_dim,
                      b=max_batch)
    cached = at.get_cache().get(key)
    if cached is not None:
        return int(cached)

    import paddle_tpu.nn.functional as F
    from ..core.tensor import Tensor

    prepared = {}
    nvar = 3

    def run(bs, i):
        entry = prepared.get(bs)
        if entry is None:
            import jax
            nb = max_batch * max_blocks_per_seq + 1
            kc = jnp.zeros((nb, bs, nkv, head_dim), jnp.bfloat16)
            vc = jnp.zeros_like(kc)
            tables = jnp.asarray(
                np.arange(1, max_batch * max_blocks_per_seq + 1)
                .reshape(max_batch, max_blocks_per_seq).astype(np.int32))
            # mid-stream decode: every sequence half way into its pages
            seq_lens = jnp.full((max_batch,),
                                (max_blocks_per_seq // 2) * bs, jnp.int32)
            # distinct probe queries per timed iteration (replay-caching
            # backends fake repeat-identical executions)
            q_vars = [jnp.asarray(np.random.RandomState(v).randn(
                max_batch, 1, cfg.num_heads, head_dim), jnp.bfloat16)
                for v in range(nvar)]
            nk = jnp.asarray(np.random.RandomState(9).randn(
                max_batch, 1, nkv, head_dim), jnp.bfloat16)

            def tick(qa, kca, vca, ta, sla, nka):
                out, _, _ = F.block_multihead_attention(
                    Tensor(qa), Tensor(kca), Tensor(vca), Tensor(ta),
                    Tensor(sla), new_k=Tensor(nka), new_v=Tensor(nka),
                    causal=True)
                return out._data

            def chained(qa, kca, vca, ta, sla, nka):
                # chain ticks data-dependently (out is q-shaped) so
                # device time dominates per-call dispatch/transport
                return jax.lax.fori_loop(
                    0, 128,
                    lambda _, qq: tick(qq, kca, vca, ta, sla, nka), qa)

            entry = prepared[bs] = (jax.jit(chained), q_vars,
                                    (kc, vc, tables, seq_lens, nk))
        fn, q_vars, rest = entry
        return fn(q_vars[i % nvar], *rest)

    return int(at.autotune(key, list(candidates), run, default,
                           warmup=2, iters=5))


#: model -> {arch name: jitted tick fn} — shared across engines of one
#: model (entries die with the model; see PagedEngine.__init__)
_PAGED_JIT_CACHE: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def _sample_tokens(logits, temps, top_ps, key):
    """Per-slot greedy / temperature / nucleus sampling — the same
    kernel as ops.top_p_sampling (shared helper), keyed per tick so
    the program is reusable across calls."""
    from ..ops.search import nucleus_sample_ids
    greedy = jnp.argmax(logits, axis=-1)
    safe_t = jnp.maximum(temps, 1e-6)[:, None]
    probs = jax.nn.softmax(logits / safe_t, axis=-1)
    sampled = nucleus_sample_ids(probs, top_ps, key)[:, 0]
    return jnp.where(temps > 0, sampled, greedy)


def _paged_forward(arch, params, param_arrays, kcs, vcs, tokens, seq_lens,
                   tables, temps, top_ps, key):
    """One chunk for a (B, T) token batch; returns (next-token ids, new
    caches). Traced under jit. A module-level function (arch + params
    pre-bound via functools.partial) so the shared jit cache holds only
    the model's small adapter/parameter objects — NEVER an engine
    instance, whose paged K/V arrays are the largest allocation in the
    process."""
    import paddle_tpu.nn.functional as F

    originals = [p._data for p in params]
    for p, a in zip(params, param_arrays):
        p._data = a
    try:
        B, T = tokens.shape
        start = seq_lens - T
        sl_t = Tensor(seq_lens)
        tb_t = Tensor(tables)

        def attend(li, q, k, v):
            out, nkc, nvc = F.block_multihead_attention(
                q, Tensor(kcs[li]), Tensor(vcs[li]), tb_t, sl_t,
                new_k=k, new_v=v, causal=True)
            kcs[li] = nkc._data
            vcs[li] = nvc._data
            return out

        logits = arch.forward_chunk(tokens, start, attend)
        nxt = _sample_tokens(logits._data[:, -1, :], temps, top_ps, key)
        return nxt.astype(jnp.int32), kcs, vcs
    finally:
        for p, o in zip(params, originals):
            p._data = o


class PagedEngine:
    """Continuous-batching engine for causal LMs (paged KV caches)."""

    def __init__(self, model, *, max_batch: int = 8,
                 block_size: Optional[int] = 16,
                 num_blocks: int = 256, max_blocks_per_seq: int = 32,
                 eos_id: Optional[int] = None, seed: int = 0,
                 kv_dtype=None,
                 resilience: Optional[ResilienceConfig] = None):
        self.model = model
        self.arch = _pick_arch(model)
        self.cfg = model.cfg
        self.max_batch = max_batch
        if block_size is None:
            # measured choice for this chip/model-geometry (falls back to
            # 16 off-TPU); ops/pallas/autotune.py caches winners on disk
            block_size = _tuned_decode_block_size(
                self.cfg, self.arch.num_kv_heads, max_batch,
                max_blocks_per_seq)
        self.block_size = block_size
        self.max_blocks_per_seq = max_blocks_per_seq
        self.eos_id = eos_id
        cfg = self.cfg
        self.head_dim = cfg.hidden_size // cfg.num_heads
        nkv = self.arch.num_kv_heads
        self.num_kv_heads = nkv

        self.bm = BlockManager(num_blocks)
        self._total_usable = num_blocks - 1
        # K/V pages live in the model's compute dtype (the attention math
        # upcasts to f32 inside the kernel) — a bf16 model must not pay
        # 2x KV HBM for fp32 pages; on a 16 GB chip KV capacity IS the
        # serving ceiling.
        if kv_dtype is None:
            kv_dtype = next(
                (p._data.dtype for p in model.parameters()
                 if jnp.issubdtype(p._data.dtype, jnp.floating)),
                jnp.float32)
        self.kv_dtype = jnp.dtype(kv_dtype)
        self._kv_shape = (num_blocks, block_size, nkv, self.head_dim)
        self.kc = [jnp.zeros(self._kv_shape, self.kv_dtype)
                   for _ in range(cfg.num_layers)]
        self.vc = [jnp.zeros(self._kv_shape, self.kv_dtype)
                   for _ in range(cfg.num_layers)]

        self.tables = np.zeros((max_batch, max_blocks_per_seq), np.int32)
        self.seq_lens = np.ones((max_batch,), np.int32)  # idle: len 1
        self.last_token = np.zeros((max_batch,), np.int32)
        self.slots: List[Optional[Request]] = [None] * max_batch
        self.slot_blocks: List[List[int]] = [[] for _ in range(max_batch)]
        self.queue: List[Request] = []
        self.rejected: Dict[int, str] = {}
        self._params = [p for p in model.parameters()]
        # one jit wrapper: jax.jit itself specializes per (B, T) shape.
        # Engines over the SAME model share it — _paged_forward reads
        # only the model's Parameter objects (identical across engines)
        # and takes caches/tables/tokens as arguments, so a second
        # replica (or the single-stream baseline in bench.py) reuses
        # compiled programs instead of re-tracing identical ones. The
        # cache lives in a weak side table, NOT on the model: jitted
        # callables hold locks and must not ride through deepcopy/pickle
        # of the model.
        import functools
        cache = _PAGED_JIT_CACHE.setdefault(model, {})
        arch_key = type(self.arch).__name__
        fn = cache.get(arch_key)
        if fn is None:
            fn = cache[arch_key] = jax.jit(
                functools.partial(_paged_forward, self.arch,
                                  tuple(self._params)),
                donate_argnums=(1, 2))
        self._fn = fn
        self._key = jax.random.key(seed)
        self._done: List[Request] = []
        self._rid = 0
        # --- resilience state ---
        self.resilience = resilience or ResilienceConfig()
        self._clock = time.monotonic      # seam for deterministic tests
        self.lifecycle = ReplicaLifecycle(clock=self._clock)
        #: terminal outcome per request (drained by ``drain_outcomes``;
        #: long-running callers should drain it alongside step())
        self.outcomes: Dict[int, RequestOutcome] = {}
        self._ticks = 0
        self.tick_failures = 0
        self._watchdog = None
        # finished results produced while warmup() owned the step loop —
        # re-delivered by the next step()/run_to_completion
        self._spillover: Dict[int, List[int]] = {}
        # HBM attribution: KV pages report under the "kv_cache" tag (the
        # getter re-reads kc/vc, which donation replaces every tick)
        from ..observability.perf import memory as _perf_memory
        _perf_memory.register_object("kv_cache", self,
                                     lambda e: (e.kc, e.vc))
        # fleet telemetry: this replica's health() rides every
        # fleet.snapshot(), so a multi-replica router polls one endpoint
        # per rank (weakly held — a dropped engine unregisters itself)
        from ..observability import fleet as _fleet
        _fleet.register_replica(self)

    # ---------------------------------------------------------------- API
    def add_request(self, prompt_ids, max_new_tokens: int = 32,
                    temperature: float = 0.0, top_p: float = 1.0,
                    ttft_deadline_s: Optional[float] = None,
                    deadline_s: Optional[float] = None) -> int:
        prompt = [int(t) for t in prompt_ids]
        if not prompt:
            raise ValueError("add_request: prompt must be non-empty")
        if max_new_tokens < 1:
            raise ValueError("add_request: max_new_tokens must be >= 1")
        if not 0.0 < top_p <= 1.0:
            raise ValueError("add_request: top_p must be in (0, 1]")
        if not temperature >= 0.0:   # also rejects NaN
            raise ValueError("add_request: temperature must be >= 0")
        max_pos = getattr(self.arch, "max_positions", None)
        if max_pos is not None and len(prompt) + max_new_tokens > max_pos:
            # learned-position models: a sequence growing past the table
            # would silently clip-gather the last embedding
            raise ValueError(
                f"add_request: prompt ({len(prompt)}) + max_new_tokens "
                f"({max_new_tokens}) exceeds the model's position table "
                f"({max_pos})")
        # ---- admission control (backpressure is an exception the
        # SUBMITTER handles; everything after acceptance is a status) ----
        if not self.lifecycle.admitting():
            raise Overloaded(
                f"replica is {self.lifecycle.state}: not accepting "
                f"requests")
        rcfg = self.resilience
        if len(self.queue) >= rcfg.max_queue:
            raise Overloaded(
                f"admission queue full ({rcfg.max_queue} queued); retry "
                f"on another replica")
        self._rid += 1
        req = Request(self._rid, prompt, max_new_tokens,
                      temperature=temperature, top_p=top_p)
        req.submit_t = self._clock()
        req.ttft_deadline_s = (ttft_deadline_s if ttft_deadline_s
                               is not None
                               else rcfg.default_ttft_deadline_s)
        req.deadline_s = (deadline_s if deadline_s is not None
                          else rcfg.default_deadline_s)
        need_total = self._blocks_needed(len(prompt) + max_new_tokens)
        if (need_total > self.max_blocks_per_seq
                or need_total > self._total_usable):
            # can NEVER fit this replica's geometry: terminal FAILED at
            # submit time (round 3 raised MemoryError from
            # run_to_completion after other requests already ran)
            reason = (f"needs {need_total} blocks (max_blocks_per_seq="
                      f"{self.max_blocks_per_seq}, usable="
                      f"{self._total_usable})")
            self.rejected[req.rid] = reason
            self._finish_request(req, RequestStatus.FAILED, detail=reason)
            return req.rid
        self.queue.append(req)
        _res.M_QUEUE_DEPTH.set(len(self.queue))
        return req.rid

    @property
    def num_active(self) -> int:
        return sum(1 for s in self.slots if s is not None)

    def has_work(self) -> bool:
        return bool(self.queue) or self.num_active > 0

    # ----------------------------------------------------------- compute
    def _run_chunk(self, tokens_np, seq_lens_np, tables_np,
                   temps_np, top_ps_np, phase: str = "decode"):
        from ..observability import trace as _otrace

        self._key, sub = jax.random.split(self._key)
        # serving always runs eval-mode (dropout off); restore the
        # caller's training flag afterwards — the engine must not mutate
        # a model a training loop is still using
        was_training = getattr(self.model, "training", False)
        if was_training:
            self.model.eval()
        t0 = time.perf_counter() if _otrace._active["on"] else 0.0
        try:
            nxt, self.kc, self.vc = self._fn(
                [p._data for p in self._params], self.kc, self.vc,
                jnp.asarray(tokens_np), jnp.asarray(seq_lens_np),
                jnp.asarray(tables_np),
                jnp.asarray(temps_np, jnp.float32),
                jnp.asarray(top_ps_np, jnp.float32), sub)
            # np.asarray blocks until the program finishes, so this span
            # covers the chunk's actual device execution — the per-tick
            # prefill-vs-decode attribution loadgen/bench report
            out = np.asarray(nxt)  # tpulint: disable=TPU104 — host boundary by design: sampled token ids feed python-side scheduling
        finally:
            if was_training:
                self.model.train()
        if t0:
            _otrace.add_complete(f"serving.{phase}", "device", t0,
                                 time.perf_counter(),
                                 {"phase": phase,
                                  "batch": int(len(seq_lens_np))})
        return out

    # -------------------------------------------------------- scheduling
    def _blocks_needed(self, length: int) -> int:
        return -(-length // self.block_size)

    def _ensure_blocks(self, slot: int, length: int) -> bool:
        need = self._blocks_needed(length)
        have = len(self.slot_blocks[slot])
        if need > self.max_blocks_per_seq:
            raise MemoryError(
                f"sequence needs {need} blocks > max_blocks_per_seq "
                f"{self.max_blocks_per_seq}")
        if need > have:
            if need - have > self.bm.available:
                return False
            new = self.bm.allocate(need - have)
            for j, b in enumerate(new):
                self.tables[slot, have + j] = b
            self.slot_blocks[slot].extend(new)
        return True

    def _admit(self):
        from ..fault import inject as _inject

        admitted = []
        for slot in range(self.max_batch):
            if not self.queue or self.slots[slot] is not None:
                continue
            req = self.queue[0]
            prefix_len = len(req.prompt) + len(req.generated)
            if (self._blocks_needed(prefix_len + 1)
                    > self.bm.available):
                break  # head-of-line blocks until memory frees
            self.queue.pop(0)
            self.slots[slot] = req
            self.tables[slot, :] = 0
            self.slot_blocks[slot] = []
            # allocate the prefix blocks NOW so the next admission's
            # availability check sees the reduced pool
            raced = _inject.fire("serving.admission_oom") is not None
            if raced or not self._ensure_blocks(slot, prefix_len):
                # admission raced cache exhaustion (a concurrent slot's
                # growth won the last blocks between the availability
                # check and the allocate): un-admit and retry next tick
                # — round 3 raised MemoryError here and killed the
                # engine with every in-flight decode
                self._release_slot(slot)
                self.queue.insert(0, req)
                break
            req.status = RequestStatus.RUNNING
            _res.M_ADMITTED.inc()
            admitted.append(slot)
        if admitted:
            self._prefill_batch(admitted)

    def _prefill_batch(self, slots: List[int]):
        """Prefill every same-tick admission TOGETHER: one (max_batch,
        block_size) chunk program per chunk tick instead of per-request
        [1, t] loops. Each slot's prefix is LEFT-padded to a multiple of
        block_size — padded positions sit at negative sequence positions,
        which the paged-attention kernel drops from the cache write and
        fully masks from attention, so only two compiled shapes exist in
        steady state: (max_batch, block_size) and the (max_batch, 1)
        decode. The final chunk of each slot yields its first sampled
        token."""
        bs = self.block_size
        prefixes = {}
        chunks_of = {}
        pad_of = {}
        for slot in slots:
            req = self.slots[slot]
            prefix = np.asarray(req.prompt + req.generated, np.int32)
            n_chunks = -(-len(prefix) // bs)
            prefixes[slot] = np.concatenate(
                [np.zeros(n_chunks * bs - len(prefix), np.int32), prefix])
            chunks_of[slot] = n_chunks
            pad_of[slot] = n_chunks * bs - len(prefix)
        nxt_of = {}
        for j in range(max(chunks_of.values())):
            tokens = np.zeros((self.max_batch, bs), np.int32)
            seq = np.zeros((self.max_batch,), np.int32)   # 0 = inactive
            temps = np.zeros((self.max_batch,), np.float32)
            top_ps = np.ones((self.max_batch,), np.float32)
            involved = []
            for slot in slots:
                if j >= chunks_of[slot]:
                    continue
                req = self.slots[slot]
                tokens[slot] = prefixes[slot][j * bs:(j + 1) * bs]
                seq[slot] = (j + 1) * bs - pad_of[slot]
                temps[slot] = req.temperature
                top_ps[slot] = req.top_p
                involved.append(slot)
            nxt = self._run_chunk(tokens, seq, self.tables, temps, top_ps,
                                  phase="prefill")
            for slot in involved:
                if j == chunks_of[slot] - 1:
                    nxt_of[slot] = int(nxt[slot])
        now = self._clock()
        for slot in slots:
            req = self.slots[slot]
            self.seq_lens[slot] = len(req.prompt) + len(req.generated)
            tok = nxt_of[slot]
            req.generated.append(tok)
            self.last_token[slot] = tok
            self._record_token(req, now)
            self._maybe_finish(slot)


    def _evict(self, slot: int):
        """Preempt a running request: release its blocks and requeue it
        for later re-admission (its generated prefix re-prefills then —
        vLLM-style recompute preemption)."""
        req = self.slots[slot]
        self._release_slot(slot)
        req.status = RequestStatus.QUEUED
        _res.M_EVICTIONS.inc()
        self.queue.append(req)

    def _release_slot(self, slot: int):
        """Return a slot's KV blocks to the free list and reset its lane
        in the batch state (idle lanes point at the trash block)."""
        self.slots[slot] = None
        self.bm.release(self.slot_blocks[slot])
        self.slot_blocks[slot] = []
        self.tables[slot, :] = 0
        self.seq_lens[slot] = 1
        self.last_token[slot] = 0

    def _finish_request(self, req: Request, status: str,
                        detail: str = ""):
        """Move ``req`` to a terminal status and record its outcome. The
        caller must already have released any slot/blocks it held."""
        req.status = status
        req.detail = detail
        req.finish_t = self._clock()
        _res.M_REQUESTS.inc(outcome=status)
        if status == RequestStatus.SHED:
            _res.M_SHED.inc()
        elif status == RequestStatus.DEADLINE_MISSED:
            _res.M_DEADLINE_MISSED.inc()
        self.outcomes[req.rid] = RequestOutcome(
            rid=req.rid, status=status, detail=detail,
            tokens=list(req.generated), submit_t=req.submit_t,
            first_token_t=req.first_token_t, finish_t=req.finish_t,
            token_times=list(req.token_times))
        if status == RequestStatus.FINISHED:
            self._done.append(req)

    def _record_token(self, req: Request, now: float):
        """TTFT / inter-token latency bookkeeping for one new token."""
        if req.first_token_t is None:
            req.first_token_t = now
            if req.submit_t is not None:
                _res.M_TTFT.observe(now - req.submit_t)
        elif req.token_times:
            _res.M_ITL.observe(now - req.token_times[-1])
        req.token_times.append(now)

    def _maybe_finish(self, slot: int):
        req = self.slots[slot]
        if req is None:
            return
        last = req.generated[-1] if req.generated else None
        if (len(req.generated) >= req.max_new_tokens
                or (self.eos_id is not None and last == self.eos_id)):
            self._release_slot(slot)
            self._finish_request(req, RequestStatus.FINISHED)

    # ------------------------------------------------- deadlines/overload
    def _deadline_expired(self, req: Request, now: float) -> Optional[str]:
        """Reason string when ``req`` is past a deadline, else None."""
        if req.submit_t is None:
            return None
        waited = now - req.submit_t
        if req.deadline_s is not None and waited > req.deadline_s:
            return (f"total deadline {req.deadline_s}s expired after "
                    f"{waited:.3f}s ({len(req.generated)} tokens)")
        if (req.first_token_t is None and req.ttft_deadline_s is not None
                and waited > req.ttft_deadline_s):
            return (f"TTFT deadline {req.ttft_deadline_s}s expired after "
                    f"{waited:.3f}s with no first token")
        return None

    def _expire_deadlines(self):
        """Cancel queued AND in-flight requests whose TTFT/total deadline
        has passed; in-flight cancellations reclaim their KV blocks."""
        now = self._clock()
        kept = []
        for req in self.queue:
            why = self._deadline_expired(req, now)
            if why is None:
                kept.append(req)
            else:
                self._finish_request(req, RequestStatus.DEADLINE_MISSED,
                                     detail=why)
        self.queue = kept
        for slot, req in enumerate(self.slots):
            if req is None:
                continue
            why = self._deadline_expired(req, now)
            if why is not None:
                self._release_slot(slot)
                self._finish_request(req, RequestStatus.DEADLINE_MISSED,
                                     detail=why)

    def _shed_overload(self):
        """Past the queue high-water mark, shed the NEWEST queued
        requests (they would wait longest; the oldest are closest to a
        slot) down to the mark. Preempted requests carrying generated
        tokens are spared — shedding them would discard paid-for
        prefill/decode compute (the queue stays bounded by max_queue
        regardless)."""
        hw = self.resilience.queue_high_water
        if hw is None or len(self.queue) <= hw:
            return
        excess = len(self.queue) - hw
        kept_rev: List[Request] = []
        for req in reversed(self.queue):          # newest first
            if excess > 0 and not req.generated:
                excess -= 1
                self._finish_request(
                    req, RequestStatus.SHED,
                    detail=f"queue past high-water mark ({hw})")
            else:
                kept_rev.append(req)
        self.queue = kept_rev[::-1]

    def _eviction_key(self, slot: int):
        """Preemption victim ordering: most deadline slack first (no
        deadline = infinite slack), youngest rid as tie-break — evicting
        the request closest to its deadline would turn one preemption
        into a deadline miss."""
        req = self.slots[slot]
        if req.deadline_s is not None and req.submit_t is not None:
            dl = req.submit_t + req.deadline_s
        else:
            dl = float("inf")
        return (dl, req.rid)

    # ------------------------------------------------------------- ticks
    def step(self) -> Dict[int, List[int]]:
        """One engine tick: expire deadlines, shed overload, admit +
        prefill queued requests, then a single batched decode step for
        every active slot. Returns {rid: generated_tokens} for requests
        that finished this tick.

        Never raises from scheduling, memory pressure, or injected
        faults: an internal tick failure marks the in-flight requests
        FAILED, reclaims their KV blocks, and flips the replica
        DEGRADED — the engine keeps serving."""
        from ..observability import trace

        wd = self._watchdog
        if wd is not None:
            wd.begin_work()
        self._ticks += 1
        t0 = time.perf_counter()
        try:
            with trace.span("serving.tick", "serving",
                            args={"tick": self._ticks}):
                try:
                    self._tick()
                    if self.lifecycle.state == ReplicaState.STARTING:
                        self.lifecycle.to(ReplicaState.READY, "serving")
                except Exception as e:
                    self._on_tick_failure(e)
        finally:
            if wd is not None:
                wd.end_work()
            _res.M_TICK_SECONDS.observe(time.perf_counter() - t0)
            _res.M_QUEUE_DEPTH.set(len(self.queue))
            _res.M_KV_BLOCKS.set(self._total_usable - self.bm.available)
        return self._drain_done()

    def _tick(self):
        from ..fault import inject as _inject

        stall = _inject.fire("serving.tick_stall")
        if stall is not None:
            # a wedged device transfer/compile: the tick thread blocks,
            # no heartbeat reaches the watchdog
            time.sleep(float(stall.get("seconds", 0.1)))
        if _inject.fire("serving.crash_at_tick",
                        tick=self._ticks) is not None:
            raise _inject.InjectedFault(
                "serving.crash_at_tick",
                f"injected crash at tick {self._ticks}")
        self._expire_deadlines()
        # admit BEFORE shedding: a burst hitting an idle replica flows
        # into free decode slots first; only what capacity could not
        # absorb this tick counts against the high-water mark
        self._admit()
        self._shed_overload()
        self._decode_active()

    def _decode_active(self):
        active = [i for i, s in enumerate(self.slots) if s is not None]
        if not active:
            return
        seq = self.seq_lens.copy()
        skipped = []
        for i in active:
            # the cache holds seq_len-1 positions; the token being fed
            # (the newest sample) lands at position seq_len-1, so the
            # total INCLUDING it is exactly req.seq_len
            seq[i] = self.slots[i].seq_len
            if not self._ensure_blocks(i, int(seq[i])):
                # OOM: skip this slot's tick. Sentinel 0 — with seq=1
                # the op would write the token's K/V into position 0
                # of the slot's first REAL block, corrupting the
                # cached prompt; seq=0 puts the write at pos -1,
                # which the kernel drops and fully masks.
                seq[i] = 0
                skipped.append(i)
        if skipped and len(skipped) == len(active):
            # every active slot is memory-stalled: nobody can finish
            # to free blocks, so this would livelock. Preempt the slot
            # with the most deadline slack (vLLM recompute-preemption,
            # deadline-aware) and retry next tick with its blocks free.
            victim = max(skipped, key=self._eviction_key)
            self._evict(victim)
            return
        tokens = self.last_token[:, None].astype(np.int32)
        temps = np.zeros((self.max_batch,), np.float32)
        top_ps = np.ones((self.max_batch,), np.float32)
        for i in active:
            temps[i] = self.slots[i].temperature
            top_ps[i] = self.slots[i].top_p
        nxt = self._run_chunk(tokens, seq, self.tables, temps, top_ps,
                              phase="decode")
        now = self._clock()
        for i in active:
            if seq[i] == 0:
                continue
            req = self.slots[i]
            req.generated.append(int(nxt[i]))
            self.seq_lens[i] = int(seq[i])   # cached positions now
            self.last_token[i] = int(nxt[i])
            self._record_token(req, now)
            self._maybe_finish(i)

    def _on_tick_failure(self, exc: BaseException):
        """Contain an unexpected tick error: the in-flight requests are
        FAILED (their KV state is suspect), their blocks reclaimed, and
        the replica degrades — it keeps serving new requests, but the
        readiness probe goes red so the balancer backs off."""
        _res.M_TICK_FAILURES.inc()
        self.tick_failures += 1
        detail = f"tick {self._ticks} failed: {exc!r}"
        for slot, req in enumerate(self.slots):
            if req is None:
                continue
            try:
                self._release_slot(slot)
            except Exception:
                self.slots[slot] = None   # never mask the containment
            self._finish_request(req, RequestStatus.FAILED, detail=detail)
        # the decode call DONATES kc/vc: a crash inside the executable
        # may have invalidated those buffers with the new ones never
        # assigned. Reallocate fresh pages — every slot was discarded
        # above, so later admissions re-prefill from their prompts; a
        # stale-buffer engine would otherwise fail every future tick
        # while still admitting.
        self.kc = [jnp.zeros(self._kv_shape, self.kv_dtype)
                   for _ in range(self.cfg.num_layers)]
        self.vc = [jnp.zeros(self._kv_shape, self.kv_dtype)
                   for _ in range(self.cfg.num_layers)]
        self.lifecycle.degrade(detail)

    def _drain_done(self) -> Dict[int, List[int]]:
        """Hand completed requests to the caller and DROP them — a
        long-running server must not retain every request ever served."""
        out = dict(self._spillover)   # client traffic served mid-warmup
        self._spillover.clear()
        out.update((req.rid, req.generated) for req in self._done)
        self._done.clear()
        return out

    def run_to_completion(self, max_ticks: int = 10_000):
        """Tick until no work remains; returns {rid: generated_tokens}
        for FINISHED requests. Requests that ended SHED / DEADLINE_MISSED
        / CANCELLED / FAILED are absent here — read ``self.outcomes``
        (or ``drain_outcomes()``) for their terminal records; never-
        fitting submissions also appear in ``self.rejected``."""
        out: Dict[int, List[int]] = {}
        ticks = 0
        while self.has_work():
            out.update(self.step())
            ticks += 1
            if ticks > max_ticks:
                raise RuntimeError("serving engine did not converge")
        return out

    # ------------------------------------------------ replica operations
    def request_status(self, rid: int) -> Optional[str]:
        """Current status of a submitted request (terminal statuses stay
        readable until ``drain_outcomes`` pops them); None = unknown."""
        oc = self.outcomes.get(rid)
        if oc is not None:
            return oc.status
        for req in self.queue:
            if req.rid == rid:
                return req.status
        for req in self.slots:
            if req is not None and req.rid == rid:
                return req.status
        return None

    def drain_outcomes(self) -> Dict[int, RequestOutcome]:
        """Hand terminal outcomes to the caller and drop them (same
        retention contract as ``_drain_done``: a long-running replica
        must not retain every request ever served)."""
        out, self.outcomes = self.outcomes, {}
        for rid in out:          # rejected mirrors submit-time FAILED
            self.rejected.pop(rid, None)
        return out

    def cancel(self, rid: int, reason: str = "cancelled by caller") -> bool:
        """Cancel a queued or in-flight request; its KV blocks return to
        the free list immediately. False if ``rid`` is not live."""
        for i, req in enumerate(self.queue):
            if req.rid == rid:
                self.queue.pop(i)
                self._finish_request(req, RequestStatus.CANCELLED,
                                     detail=reason)
                return True
        for slot, req in enumerate(self.slots):
            if req is not None and req.rid == rid:
                self._release_slot(slot)
                self._finish_request(req, RequestStatus.CANCELLED,
                                     detail=reason)
                return True
        return False

    def warmup(self, prompt_len: Optional[int] = None,
               max_new_tokens: int = 2) -> "PagedEngine":
        """Compile the steady-state programs (full prefill chunk + the
        batched decode step) before real traffic:
        STARTING→WARMING→READY. Idempotent on a READY replica.

        Traffic that arrived before READY (admission is open from
        STARTING — those requests wait for exactly these compiles) is
        served alongside the synthetic warmup request; its results are
        re-delivered by the next ``step()``/``run_to_completion``."""
        if self.lifecycle.state == ReplicaState.READY:
            return self
        self.lifecycle.to(ReplicaState.WARMING, "warmup")
        n = prompt_len if prompt_len is not None else self.block_size
        rid = self.add_request([1] * max(1, n),
                               max_new_tokens=max_new_tokens)
        # the synthetic request is operator work: no SLO deadlines
        # (expiring it mid-compile would block READY), and it jumps to
        # the queue head so a pre-READY client burst can neither starve
        # nor shed it
        for i, req in enumerate(self.queue):
            if req.rid == rid:
                req.ttft_deadline_s = req.deadline_s = None
                self.queue.insert(0, self.queue.pop(i))
                break
        while self.outcomes.get(rid) is None and self.has_work():
            res = self.step()
            res.pop(rid, None)          # warmup is not traffic
            self._spillover.update(res)
        oc = self.outcomes.pop(rid, None)
        if oc is None or oc.status != RequestStatus.FINISHED:
            # stay in WARMING (still admits): READY would advertise a
            # replica whose steady-state programs never compiled
            raise RuntimeError(
                f"warmup request ended "
                f"{oc.status if oc else '<missing>'}: "
                f"{oc.detail if oc else ''}")
        self.lifecycle.to(ReplicaState.READY, "warmup complete")
        return self

    def drain(self, max_ticks: int = 10_000) -> Dict[int, List[int]]:
        """Graceful shutdown: stop admission, finish in-flight decodes,
        then STOP. Queued requests that never got a slot are CANCELLED
        (their clients retry on another replica); running requests
        decode to completion. Returns {rid: tokens} finished during the
        drain."""
        if self.lifecycle.state == ReplicaState.STOPPED:
            return {}
        self.lifecycle.to(ReplicaState.DRAINING, "drain()")
        for req in self.queue:
            self._finish_request(req, RequestStatus.CANCELLED,
                                 detail="drained before admission")
        self.queue = []
        out: Dict[int, List[int]] = {}
        ticks = 0
        # loop on has_work(), not num_active: livelock preemption can
        # bounce an in-flight request back through the queue mid-drain,
        # and it still must reach a terminal status
        while self.has_work():
            out.update(self.step())
            ticks += 1
            if ticks > max_ticks:
                # fail whatever is still live rather than spin forever
                for req in self.queue:
                    self._finish_request(req, RequestStatus.FAILED,
                                         detail="drain did not converge")
                self.queue = []
                for slot, req in enumerate(self.slots):
                    if req is not None:
                        self._release_slot(slot)
                        self._finish_request(
                            req, RequestStatus.FAILED,
                            detail="drain did not converge")
                break
        self.lifecycle.to(ReplicaState.STOPPED, "drained")
        _res.M_QUEUE_DEPTH.set(0)
        _res.M_KV_BLOCKS.set(self._total_usable - self.bm.available)
        return out

    def recover(self, reason: str = "operator recover"):
        """DEGRADED → READY once the operator (or an orchestrator health
        check) has decided the stall/crash cause is gone."""
        self.lifecycle.to(ReplicaState.READY, reason)

    def attach_watchdog(self, watchdog) -> "PagedEngine":
        """Wire a :class:`~paddle_tpu.distributed.watchdog.Watchdog`
        into the tick loop: every tick brackets begin_work/end_work (so
        an idle engine stays quiet), and a tick stalled past the
        watchdog timeout flips this replica DEGRADED while the watchdog
        dumps thread stacks + the span-buffer tail."""
        self._watchdog = watchdog
        prev = watchdog.on_hang

        def _on_hang(wd):
            self.lifecycle.degrade(
                f"tick stalled > {wd.timeout}s (watchdog)")
            if prev is not None:
                prev(wd)

        watchdog.on_hang = _on_hang
        return self

    def health(self) -> dict:
        """Liveness/readiness probe payload (what an HTTP /healthz in
        front of this replica returns)."""
        lc = self.lifecycle
        return {"state": lc.state, "ready": lc.ready(),
                "live": lc.live(),
                "queue_depth": len(self.queue),
                "active": self.num_active,
                "kv_blocks_free": self.bm.available,
                "kv_blocks_total": self._total_usable,
                "ticks": self._ticks,
                "tick_failures": self.tick_failures}


# Backward-compatible names: the generic engine picks the adapter itself.
LlamaPagedEngine = PagedEngine
GPTPagedEngine = PagedEngine
