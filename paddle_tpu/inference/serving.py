"""Continuous-batching LLM serving over paged KV caches.

Reference surface: the block-attention serving op family
(phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu,
fused_multi_transformer cached decoding) that PaddleNLP's serving stack
drives. TPU-native redesign: the whole decode tick for every in-flight
request is ONE jitted SPMD-friendly program — paged K/V caches live as
donated device arrays, a host-side BlockManager owns the physical-block
free list, and admission/eviction is plain Python between ticks:

* prefill runs per request in block_size chunks (two compiled shapes:
  a full chunk and each remainder), appending K/V pages via
  ``nn.functional.block_multihead_attention``; under a phase-split
  scheduler (``paddle_tpu.serving.Scheduler``) the chunks are budgeted
  per tick and interleaved with decode, so a long prompt stops stalling
  every in-flight stream's inter-token latency;
* decode runs ALL active slots in one (B, 1) step; idle slots point at a
  reserved trash block so the compiled program never branches on
  occupancy. With ``speculate=`` the decode step becomes a speculative
  verify: draft tokens appended to the feed, one (B, k+1) forward, and
  the accept-prefix rule in-graph — still ONE compiled program, now
  yielding up to k+1 tokens per request per tick;
* positions are per-slot (each sequence is at a different length — the
  batch shares one program, not one position): RoPE offsets for Llama,
  learned-position gathers for GPT (architecture adapters `_LlamaArch` /
  `_GPTArch`);
* K/V pages are stored in the model's compute dtype, or as an int8 page
  pool with sidecar per-(position, head) scales (``kv_dtype="int8"`` —
  the ``nn/quant`` weight-only pattern applied to KV), halving resident
  KV vs bf16 and roughly doubling the resident batch a chip can hold.

Sampling is per-request deterministic: every sampled token draws from a
key folded from (engine seed, request id, token position), so a request
preempted and re-prefilled resumes the SAME sampled continuation — a
replica restart or recompute preemption is invisible in the tokens.

Greedy numerics are locked to the training models by token-parity tests
against ``LlamaForCausalLM.generate`` and a full-recompute GPT greedy
loop; the int8-KV and speculative paths are parity-gated greedy-token-
identical against the baseline engine.

Resilience contract (see ``inference/resilience.py`` and README "Serving
resilience"): the tick loop never raises — overload, deadline expiry,
memory races and injected faults become per-request terminal statuses
(``FINISHED/SHED/DEADLINE_MISSED/CANCELLED/FAILED``) recorded in
``engine.outcomes``; submitters see :class:`Overloaded` backpressure from
the bounded queue; the replica walks an explicit lifecycle
(``STARTING→WARMING→READY→DEGRADED→DRAINING→STOPPED``) with ``drain()``
and health/readiness probes, and a stalled tick flips it DEGRADED via the
attached watchdog. ``engine.stream(rid)`` exposes per-request incremental
tokens under the same nothing-raises contract (the stream ends with the
terminal status). The multi-replica front door over R engines is
``paddle_tpu.serving.Router``.
"""
from __future__ import annotations

import time
import weakref
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..observability import reqtrace as _reqtrace
from .resilience import (Overloaded, ReplicaLifecycle, ReplicaState,
                         RequestOutcome, RequestStatus, ResilienceConfig,
                         TERMINAL_STATUSES)
from . import resilience as _res

__all__ = ["BlockManager", "Request", "PagedEngine", "LlamaPagedEngine",
           "GPTPagedEngine", "Overloaded", "RequestStatus", "ReplicaState",
           "ResilienceConfig", "RequestOutcome"]


class BlockManager:
    """Physical-block free list (block 0 is the reserved trash block idle
    slots write into)."""

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError("need at least 2 blocks (one is reserved)")
        self._free = list(range(num_blocks - 1, 0, -1))

    @property
    def available(self) -> int:
        return len(self._free)

    def allocate(self, n: int) -> List[int]:
        if n > len(self._free):
            raise MemoryError(
                f"paged KV cache exhausted: need {n} blocks, "
                f"{len(self._free)} free")
        return [self._free.pop() for _ in range(n)]

    def release(self, blocks: List[int]):
        self._free.extend(b for b in blocks if b != 0)


@dataclass
class Request:
    rid: int
    prompt: List[int]
    max_new_tokens: int = 32
    temperature: float = 0.0          # 0 = greedy
    top_p: float = 1.0
    generated: List[int] = field(default_factory=list)
    # --- resilience bookkeeping (engine-managed) ---
    status: str = RequestStatus.QUEUED
    detail: str = ""                  # terminal reason for non-FINISHED
    submit_t: Optional[float] = None
    first_token_t: Optional[float] = None
    finish_t: Optional[float] = None
    token_times: List[float] = field(default_factory=list)
    ttft_deadline_s: Optional[float] = None   # submit → first token
    deadline_s: Optional[float] = None        # submit → completion

    @property
    def seq_len(self) -> int:
        return len(self.prompt) + len(self.generated)


class _LlamaArch:
    """Architecture adapter: per-chunk forward for LlamaForCausalLM."""

    def __init__(self, model):
        self.model = model
        self.cfg = model.cfg
        self.num_kv_heads = model.cfg.num_kv_heads or model.cfg.num_heads

    def forward_chunk(self, tokens, start, attend, logits_t: int = 1):
        from paddle_tpu import ops
        from ..models.llama import rotary_embedding

        model = self.model
        cfg = self.cfg
        B, T = tokens.shape
        nh = cfg.num_heads
        hd = cfg.hidden_size // nh
        nkv = self.num_kv_heads
        x = model.model.embed_tokens(Tensor(tokens))
        for li, blk in enumerate(model.model.layers):
            ln = blk.input_layernorm(x)
            q = ops.reshape(blk.self_attn.q_proj(ln), [B, T, nh, hd])
            k = ops.reshape(blk.self_attn.k_proj(ln), [B, T, nkv, hd])
            v = ops.reshape(blk.self_attn.v_proj(ln), [B, T, nkv, hd])
            q = rotary_embedding(q, cfg.rope_theta, pos_offset=start)
            k = rotary_embedding(k, cfg.rope_theta, pos_offset=start)
            out = attend(li, q, k, v)
            x = x + blk.self_attn.o_proj(
                ops.reshape(out, [B, T, nh * hd]))
            x = x + blk.mlp(blk.post_attention_layernorm(x))
        x = model.model.norm(x)
        last = Tensor(x._data[:, -logits_t:, :])
        if model.lm_head is None:
            return ops.matmul(last, model.model.embed_tokens.weight,
                              transpose_y=True)
        return model.lm_head(last)


class _GPTArch:
    """Architecture adapter for GPTForCausalLM (learned positions, fused
    qkv, tied head)."""

    def __init__(self, model):
        self.model = model
        self.cfg = model.cfg
        self.num_kv_heads = model.cfg.num_heads
        self.max_positions = model.cfg.max_seq_len

    def forward_chunk(self, tokens, start, attend, logits_t: int = 1):
        from paddle_tpu import ops

        m = self.model.gpt
        cfg = self.cfg
        B, T = tokens.shape
        nh = cfg.num_heads
        hd = cfg.hidden_size // nh
        # learned positional embeddings at per-slot positions
        pos_idx = (start[:, None]
                   + jnp.arange(T, dtype=start.dtype)[None, :])
        pos_emb = jnp.take(m.wpe.weight._data, pos_idx, axis=0)
        x = m.wte(Tensor(tokens)) + Tensor(pos_emb)
        for li, blk in enumerate(m.blocks):
            ln = blk.ln1(x)
            qkv = blk.attn.qkv_proj(ln)
            q, k, v = ops.split(qkv, 3, axis=-1)
            q = ops.reshape(q, [B, T, nh, hd])
            k = ops.reshape(k, [B, T, nh, hd])
            v = ops.reshape(v, [B, T, nh, hd])
            out = attend(li, q, k, v)
            x = x + blk.attn.out_proj(ops.reshape(out, [B, T, nh * hd]))
            x = x + blk.mlp(blk.ln2(x))
        x = m.ln_f(x)
        last = Tensor(x._data[:, -logits_t:, :])
        return ops.matmul(last, m.wte.weight, transpose_y=True)


class _DenseArch:
    """Adapter for dense-scoring models (DLRM / two-tower recsys): the
    model provides ``serve_dense(flat_ids) -> (B,) scores in [0, 1]``
    plus ``serve_dense_width`` (the flat-id row width requests pad to).
    No KV cache, no positions, no autoregression — each request is ONE
    forward that emits a single "score token" (the score in basis
    points), so the whole engine surface (Router placement, outcomes,
    streams, SLO burn, warmup/drain) works unchanged on top of it."""

    def __init__(self, model):
        self.model = model
        self.width = int(model.serve_dense_width)


def _pick_arch(model):
    from ..models.gpt import GPTForCausalLM
    from ..models.llama import LlamaForCausalLM
    if isinstance(model, LlamaForCausalLM):
        return _LlamaArch(model)
    if isinstance(model, GPTForCausalLM):
        return _GPTArch(model)
    if hasattr(model, "serve_dense"):
        return _DenseArch(model)
    raise TypeError(
        f"PagedEngine supports LlamaForCausalLM / GPTForCausalLM (or "
        f"subclasses) and dense-scoring models exposing serve_dense(); "
        f"got {type(model).__name__}")


def _tuned_decode_block_size(cfg, nkv, max_batch, max_blocks_per_seq,
                             candidates=(8, 16, 32)) -> int:
    """Measured KV page size for the decode tick on this chip.

    Probes one paged-attention decode step (T=1, full batch) per
    candidate on zero caches sized to the engine's real geometry; the
    winner persists in the autotune cache (ops/pallas/autotune.py), so
    one process per chip ever pays the probe. Off-TPU: 16.
    """
    from ..ops.pallas import autotune as at

    default = 16
    if not at.should_autotune():
        return default
    head_dim = cfg.hidden_size // cfg.num_heads
    key = at.make_key("serving_decode_block", nkv=nkv, d=head_dim,
                      b=max_batch)
    cached = at.get_cache().get(key)
    if cached is not None:
        return int(cached)

    import paddle_tpu.nn.functional as F
    from ..core.tensor import Tensor

    prepared = {}
    nvar = 3

    def run(bs, i):
        entry = prepared.get(bs)
        if entry is None:
            import jax
            nb = max_batch * max_blocks_per_seq + 1
            kc = jnp.zeros((nb, bs, nkv, head_dim), jnp.bfloat16)
            vc = jnp.zeros_like(kc)
            tables = jnp.asarray(
                np.arange(1, max_batch * max_blocks_per_seq + 1)
                .reshape(max_batch, max_blocks_per_seq).astype(np.int32))
            # mid-stream decode: every sequence half way into its pages
            seq_lens = jnp.full((max_batch,),
                                (max_blocks_per_seq // 2) * bs, jnp.int32)
            # distinct probe queries per timed iteration (replay-caching
            # backends fake repeat-identical executions)
            q_vars = [jnp.asarray(np.random.RandomState(v).randn(
                max_batch, 1, cfg.num_heads, head_dim), jnp.bfloat16)
                for v in range(nvar)]
            nk = jnp.asarray(np.random.RandomState(9).randn(
                max_batch, 1, nkv, head_dim), jnp.bfloat16)

            def tick(qa, kca, vca, ta, sla, nka):
                out, _, _ = F.block_multihead_attention(
                    Tensor(qa), Tensor(kca), Tensor(vca), Tensor(ta),
                    Tensor(sla), new_k=Tensor(nka), new_v=Tensor(nka),
                    causal=True)
                return out._data

            def chained(qa, kca, vca, ta, sla, nka):
                # chain ticks data-dependently (out is q-shaped) so
                # device time dominates per-call dispatch/transport
                return jax.lax.fori_loop(
                    0, 128,
                    lambda _, qq: tick(qq, kca, vca, ta, sla, nka), qa)

            entry = prepared[bs] = (jax.jit(chained), q_vars,
                                    (kc, vc, tables, seq_lens, nk))
        fn, q_vars, rest = entry
        return fn(q_vars[i % nvar], *rest)

    return int(at.autotune(key, list(candidates), run, default,
                           warmup=2, iters=5))


#: model -> {(arch name, program kind): jitted tick fn} — shared across
#: engines of one model (entries die with the model; see
#: PagedEngine.__init__)
_PAGED_JIT_CACHE: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def _request_keys(base_key, rids, ngens):
    """Per-slot sampling keys folded from (engine seed, request id, token
    position): a request's key stream depends only on its own identity
    and how many tokens it has sampled, NEVER on which tick/slot/batch
    it happens to run in — preemption, re-admission and replica restarts
    reproduce the same sampled continuation under a fixed seed."""
    return jax.vmap(lambda r, n: jax.random.fold_in(
        jax.random.fold_in(base_key, r), n))(rids, ngens)


def _sample_tokens(logits, temps, top_ps, base_key, rids, ngens,
                   sampling: bool):
    """Per-slot greedy / temperature / nucleus sampling — the same
    kernel as ops.top_p_sampling (shared helper), keyed per (request,
    position) so the program is reusable across calls AND deterministic
    per request (see _request_keys). ``sampling`` is STATIC: the
    all-greedy tick (the common serving batch) compiles without the
    sort/cumsum/gumbel kernel at all — a smaller, faster program; the
    sampled variant traces only once a sampled request enters the
    batch."""
    greedy = jnp.argmax(logits, axis=-1)
    if not sampling:
        return greedy
    from ..ops.search import nucleus_sample_ids
    safe_t = jnp.maximum(temps, 1e-6)[:, None]
    probs = jax.nn.softmax(logits / safe_t, axis=-1)
    keys = _request_keys(base_key, rids, ngens)
    sampled = jax.vmap(
        lambda pr, pp, kk: nucleus_sample_ids(
            pr[None], pp[None, 0], kk)[0, 0])(
        probs, top_ps[:, None], keys)
    return jnp.where(temps > 0, sampled, greedy)


def _bind_params(params, param_arrays):
    """Swap traced arrays into the model's Parameter objects; returns
    the originals for the caller's finally-restore."""
    originals = [p._data for p in params]
    for p, a in zip(params, param_arrays):
        p._data = a
    return originals


def _make_attend(kcs, vcs, tb_t, sl_t):
    """Paged-attention closure over one chunk's cache state. Cache
    entries are arrays (float pages) or (payload, scales) tuples (int8
    pages) — the structure picks the kernel path at trace time."""
    import paddle_tpu.nn.functional as F

    def attend(li, q, k, v):
        if isinstance(kcs[li], tuple):
            (kp, ksc), (vp, vsc) = kcs[li], vcs[li]
            out, nkp, nvp, nks, nvs = F.block_multihead_attention(
                q, Tensor(kp), Tensor(vp), tb_t, sl_t,
                new_k=k, new_v=v, causal=True,
                k_scale=Tensor(ksc), v_scale=Tensor(vsc))
            kcs[li] = (nkp._data, nks._data)
            vcs[li] = (nvp._data, nvs._data)
        else:
            out, nkc, nvc = F.block_multihead_attention(
                q, Tensor(kcs[li]), Tensor(vcs[li]), tb_t, sl_t,
                new_k=k, new_v=v, causal=True)
            kcs[li] = nkc._data
            vcs[li] = nvc._data
        return out

    return attend


def _paged_forward(arch, params, param_arrays, kcs, vcs, tokens, seq_lens,
                   tables, temps, top_ps, rids, ngens, base_key,
                   sampling: bool = False):
    """One chunk for a (B, T) token batch; returns (next-token ids, new
    caches). Traced under jit. A module-level function (arch + params
    pre-bound via functools.partial) so the shared jit cache holds only
    the model's small adapter/parameter objects — NEVER an engine
    instance, whose paged K/V arrays are the largest allocation in the
    process."""
    originals = _bind_params(params, param_arrays)
    try:
        B, T = tokens.shape
        start = seq_lens - T
        attend = _make_attend(kcs, vcs, Tensor(tables), Tensor(seq_lens))
        logits = arch.forward_chunk(tokens, start, attend)
        nxt = _sample_tokens(logits._data[:, -1, :], temps, top_ps,
                             base_key, rids, ngens, sampling)
        return nxt.astype(jnp.int32), kcs, vcs
    finally:
        for p, o in zip(params, originals):
            p._data = o


def _paged_verify(arch, params, param_arrays, kcs, vcs, tokens, seq_lens,
                  tables, temps, top_ps, rids, ngens, base_key,
                  max_accept, sampling: bool = False):
    """Speculative verify: one (B, k+1) forward over [last_token, k
    draft tokens] per slot, greedy accept-prefix in-graph — draft
    append, target forward, and acceptance are ONE compiled program with
    a stable shape (``ops.pallas.serving.spec_accept_prefix``). Returns
    (emit (B, k+1) candidate tokens, n_emit (B,) how many of them are
    real, new caches). Sampling slots ride the same program with
    ``max_accept=0``: their position-0 logits sample exactly as a normal
    decode step would (same per-request key), drafts ignored."""
    from ..ops.pallas.serving import spec_accept_prefix

    originals = _bind_params(params, param_arrays)
    try:
        B, T = tokens.shape
        start = seq_lens - T
        attend = _make_attend(kcs, vcs, Tensor(tables), Tensor(seq_lens))
        logits = arch.forward_chunk(tokens, start, attend, logits_t=T)
        lg = logits._data                      # (B, T, V)
        greedy = jnp.argmax(lg, axis=-1).astype(jnp.int32)
        first = _sample_tokens(lg[:, 0, :], temps, top_ps,
                               base_key, rids, ngens, sampling)
        emit = jnp.concatenate(
            [jnp.where(temps > 0, first, greedy[:, 0])[:, None],
             greedy[:, 1:]], axis=1)
        n_emit, _accepted = spec_accept_prefix(
            tokens[:, 1:], greedy, max_accept)
        return emit.astype(jnp.int32), n_emit.astype(jnp.int32), kcs, vcs
    finally:
        for p, o in zip(params, originals):
            p._data = o


def _dense_forward(arch, params, param_arrays, ids):
    """Dense-path scoring program: one (B, width) padded id batch in,
    (B,) scores out. Same param-rebinding discipline as _paged_forward
    so the shared jit cache never captures an engine instance."""
    originals = _bind_params(params, param_arrays)
    try:
        scores = arch.model.serve_dense(Tensor(ids))
        return scores._data.astype(jnp.float32)
    finally:
        for p, o in zip(params, originals):
            p._data = o


class PagedEngine:
    """Continuous-batching engine for causal LMs (paged KV caches).

    Dense-scoring models (anything exposing ``serve_dense`` /
    ``serve_dense_width``, e.g. :class:`~paddle_tpu.models.DLRM`) run
    on the same engine through the dense path: no KV pool, one forward
    per tick over up to ``max_batch`` queued requests, one score token
    per request — so the Router load-balances recsys replicas exactly
    like LM replicas."""

    def __init__(self, model, *, max_batch: int = 8,
                 block_size: Optional[int] = 16,
                 num_blocks: int = 256, max_blocks_per_seq: int = 32,
                 eos_id: Optional[int] = None, seed: int = 0,
                 kv_dtype=None, scheduler=None, speculate=None,
                 speculate_k: int = 4,
                 resilience: Optional[ResilienceConfig] = None):
        from ..serving.scheduler import Scheduler, SchedulerConfig

        self.model = model
        self.arch = _pick_arch(model)
        self._dense = isinstance(self.arch, _DenseArch)
        self.cfg = model.cfg
        self.max_batch = max_batch
        if self._dense:
            # dense path: "block size" only sizes the synthetic warmup
            # prompt — use the model's id-row width so warmup compiles
            # the exact steady-state program
            block_size = self.arch.width
            speculate = None
        if block_size is None:
            # measured choice for this chip/model-geometry (falls back to
            # 16 off-TPU); ops/pallas/autotune.py caches winners on disk
            block_size = _tuned_decode_block_size(
                self.cfg, self.arch.num_kv_heads, max_batch,
                max_blocks_per_seq)
        self.block_size = block_size
        self.max_blocks_per_seq = max_blocks_per_seq
        self.eos_id = eos_id
        cfg = self.cfg
        if self._dense:
            self.head_dim = 0
            nkv = 0
        else:
            self.head_dim = cfg.hidden_size // cfg.num_heads
            nkv = self.arch.num_kv_heads
        self.num_kv_heads = nkv

        # ---- phase-split scheduler (paddle_tpu.serving.Scheduler) ----
        if scheduler is None:
            scheduler = Scheduler()
        elif isinstance(scheduler, SchedulerConfig):
            scheduler = Scheduler(scheduler)
        self.scheduler = scheduler
        #: slot -> in-progress chunked-prefill state (padded prefix,
        #: chunk cursor); a slot decodes only once it leaves this map
        self._prefilling: Dict[int, dict] = {}

        # ---- speculative decoding (paddle_tpu.serving.NgramProposer) --
        if speculate == "ngram":
            from ..serving.speculative import NgramProposer
            speculate = NgramProposer(k=speculate_k)
        self._spec = speculate
        self._spec_k = getattr(speculate, "k", speculate_k)
        self.spec_proposed = 0
        self.spec_accepted = 0

        self.bm = BlockManager(num_blocks)
        self._total_usable = num_blocks - 1
        # K/V pages live in the model's compute dtype (the attention math
        # upcasts to f32 inside the kernel) — a bf16 model must not pay
        # 2x KV HBM for fp32 pages; on a 16 GB chip KV capacity IS the
        # serving ceiling. kv_dtype="int8" swaps in the quantized page
        # pool (payload int8 + per-(position, head) fp32 scales), halving
        # resident KV again vs bf16.
        self._kv_int8 = (kv_dtype == "int8"
                         or (kv_dtype is not None
                             and jnp.dtype(kv_dtype) == jnp.int8))
        if self._kv_int8:
            kv_dtype = jnp.int8
        elif kv_dtype is None:
            kv_dtype = next(
                (p._data.dtype for p in model.parameters()
                 if jnp.issubdtype(p._data.dtype, jnp.floating)),
                jnp.float32)
        self.kv_dtype = jnp.dtype(kv_dtype)
        self._kv_shape = (num_blocks, block_size, nkv, self.head_dim)
        self._kv_scale_shape = (num_blocks, block_size, nkv)
        if self._dense:
            self.kc, self.vc = [], []     # no KV state on the dense path
        else:
            self.kc = [self._fresh_cache() for _ in range(cfg.num_layers)]
            self.vc = [self._fresh_cache() for _ in range(cfg.num_layers)]

        self.tables = np.zeros((max_batch, max_blocks_per_seq), np.int32)
        self.seq_lens = np.ones((max_batch,), np.int32)  # idle: len 1
        self.last_token = np.zeros((max_batch,), np.int32)
        self.slots: List[Optional[Request]] = [None] * max_batch
        self.slot_blocks: List[List[int]] = [[] for _ in range(max_batch)]
        self.queue: List[Request] = []
        self.rejected: Dict[int, str] = {}
        self._params = [p for p in model.parameters()]
        # one jit wrapper per program kind: jax.jit itself specializes
        # per (B, T) shape and cache pytree structure. Engines over the
        # SAME model share them — the forward fns read only the model's
        # Parameter objects (identical across engines) and take
        # caches/tables/tokens as arguments, so a second replica (or the
        # single-stream baseline in bench.py) reuses compiled programs
        # instead of re-tracing identical ones. The cache lives in a
        # weak side table, NOT on the model: jitted callables hold locks
        # and must not ride through deepcopy/pickle of the model.
        import functools
        cache = _PAGED_JIT_CACHE.setdefault(model, {})
        arch_key = type(self.arch).__name__
        if self._dense:
            dfn = cache.get((arch_key, "dense"))
            if dfn is None:
                dfn = cache[(arch_key, "dense")] = jax.jit(
                    functools.partial(_dense_forward, self.arch,
                                      tuple(self._params)))
            self._dense_fn = dfn
            self._fn = self._vfn = None
        else:
            fn = cache.get((arch_key, "chunk"))
            if fn is None:
                fn = cache[(arch_key, "chunk")] = jax.jit(
                    functools.partial(_paged_forward, self.arch,
                                      tuple(self._params)),
                    donate_argnums=(1, 2), static_argnames=("sampling",))
            self._fn = fn
            vfn = cache.get((arch_key, "verify"))
            if vfn is None:
                vfn = cache[(arch_key, "verify")] = jax.jit(
                    functools.partial(_paged_verify, self.arch,
                                      tuple(self._params)),
                    donate_argnums=(1, 2), static_argnames=("sampling",))
            self._vfn = vfn
        self._base_key = jax.random.key(seed)
        self._done: List[Request] = []
        self._rid = 0
        # --- resilience state ---
        self.resilience = resilience or ResilienceConfig()
        self._clock = time.monotonic      # seam for deterministic tests
        self.lifecycle = ReplicaLifecycle(clock=self._clock)
        # SLO burn-rate accounting (reqtrace): every terminal outcome
        # feeds the multiwindow burn gauges for this replica's scope
        rc = self.resilience
        self._slo = _reqtrace.SloTracker(
            self.lifecycle.name, target=rc.slo_target,
            fast_window_s=rc.slo_fast_window_s,
            slow_window_s=rc.slo_slow_window_s)
        #: terminal outcome per request (drained by ``drain_outcomes``;
        #: long-running callers should drain it alongside step())
        self.outcomes: Dict[int, RequestOutcome] = {}
        self._ticks = 0
        self.tick_failures = 0
        self._watchdog = None
        # finished results produced while warmup() owned the step loop —
        # re-delivered by the next step()/run_to_completion
        self._spillover: Dict[int, List[int]] = {}
        #: per-request incremental token buffers (see stream())
        self._stream_bufs: Dict[int, List[int]] = {}
        # HBM attribution: KV pages report under the "kv_cache" tag (the
        # getter re-reads kc/vc, which donation replaces every tick)
        from ..observability.perf import memory as _perf_memory
        _perf_memory.register_object("kv_cache", self,
                                     lambda e: (e.kc, e.vc))
        _res.M_KV_BYTES_PER_TOKEN.set(self.kv_bytes_per_token)
        # fleet telemetry: this replica's health() rides every
        # fleet.snapshot(), so a multi-replica router polls one endpoint
        # per rank (weakly held — a dropped engine unregisters itself)
        from ..observability import fleet as _fleet
        _fleet.register_replica(self)

    def _fresh_cache(self):
        """One layer's K (or V) page pool: a float array, or the int8
        (payload, scales) pair."""
        if self._kv_int8:
            return (jnp.zeros(self._kv_shape, jnp.int8),
                    jnp.zeros(self._kv_scale_shape, jnp.float32))
        return jnp.zeros(self._kv_shape, self.kv_dtype)

    @property
    def kv_bytes_per_token(self) -> int:
        """Resident KV bytes one cached token costs across all layers
        (the resident-batch ceiling is HBM / (this * mean seq len))."""
        if self._dense:
            return 0                     # dense path keeps no KV state
        per = self.num_kv_heads * self.head_dim * self.kv_dtype.itemsize
        if self._kv_int8:
            per += self.num_kv_heads * 4          # sidecar fp32 scale
        return 2 * self.cfg.num_layers * per      # K and V

    # ------------------------------------------------- request tracing
    @property
    def reqtrace_scope(self) -> str:
        """Timeline scope this replica records under (the lifecycle's
        stable per-process replica name)."""
        return self.lifecycle.name

    def _rt_event(self, rid: int, event: str,
                  t: Optional[float] = None, **meta):
        """Stamp one lifecycle event into the request flight recorder
        (``reqtrace.emit``: enabled-gate first — the disabled path reads
        NO clock — timestamps from the engine clock seam so FakeClock
        drills produce deterministic timelines)."""
        _reqtrace.emit(self.lifecycle.name, self._clock, rid, event, t,
                       **meta)

    # ---------------------------------------------------------------- API
    def add_request(self, prompt_ids, max_new_tokens: int = 32,
                    temperature: float = 0.0, top_p: float = 1.0,
                    ttft_deadline_s: Optional[float] = None,
                    deadline_s: Optional[float] = None) -> int:
        prompt = [int(t) for t in prompt_ids]
        if not prompt:
            raise ValueError("add_request: prompt must be non-empty")
        if max_new_tokens < 1:
            raise ValueError("add_request: max_new_tokens must be >= 1")
        if not 0.0 < top_p <= 1.0:
            raise ValueError("add_request: top_p must be in (0, 1]")
        if not temperature >= 0.0:   # also rejects NaN
            raise ValueError("add_request: temperature must be >= 0")
        if self._dense and len(prompt) > self.arch.width:
            # the id row is padded, never truncated — silently dropping
            # trailing feature ids would score a different request
            raise ValueError(
                f"add_request: dense-path prompt ({len(prompt)} ids) "
                f"exceeds the model's serve width ({self.arch.width})")
        max_pos = getattr(self.arch, "max_positions", None)
        if max_pos is not None and len(prompt) + max_new_tokens > max_pos:
            # learned-position models: a sequence growing past the table
            # would silently clip-gather the last embedding
            raise ValueError(
                f"add_request: prompt ({len(prompt)}) + max_new_tokens "
                f"({max_new_tokens}) exceeds the model's position table "
                f"({max_pos})")
        # ---- admission control (backpressure is an exception the
        # SUBMITTER handles; everything after acceptance is a status) ----
        if not self.lifecycle.admitting():
            raise Overloaded(
                f"replica is {self.lifecycle.state}: not accepting "
                f"requests")
        rcfg = self.resilience
        if len(self.queue) >= rcfg.max_queue:
            raise Overloaded(
                f"admission queue full ({rcfg.max_queue} queued); retry "
                f"on another replica")
        self._rid += 1
        req = Request(self._rid, prompt, max_new_tokens,
                      temperature=temperature, top_p=top_p)
        req.submit_t = self._clock()
        req.ttft_deadline_s = (ttft_deadline_s if ttft_deadline_s
                               is not None
                               else rcfg.default_ttft_deadline_s)
        req.deadline_s = (deadline_s if deadline_s is not None
                          else rcfg.default_deadline_s)
        self._rt_event(req.rid, "submitted", t=req.submit_t,
                       prompt_tokens=len(prompt),
                       max_new_tokens=max_new_tokens,
                       ttft_deadline_s=req.ttft_deadline_s,
                       deadline_s=req.deadline_s)
        need_total = self._blocks_needed(len(prompt) + max_new_tokens)
        if (need_total > self.max_blocks_per_seq
                or need_total > self._total_usable):
            # can NEVER fit this replica's geometry: terminal FAILED at
            # submit time (round 3 raised MemoryError from
            # run_to_completion after other requests already ran)
            reason = (f"needs {need_total} blocks (max_blocks_per_seq="
                      f"{self.max_blocks_per_seq}, usable="
                      f"{self._total_usable})")
            self.rejected[req.rid] = reason
            self._finish_request(req, RequestStatus.FAILED, detail=reason)
            return req.rid
        self.queue.append(req)
        _res.M_QUEUE_DEPTH.set(len(self.queue))
        return req.rid

    @property
    def num_active(self) -> int:
        return sum(1 for s in self.slots if s is not None)

    def has_work(self) -> bool:
        return bool(self.queue) or self.num_active > 0

    # ----------------------------------------------------------- compute
    def _chunk_args(self, tokens_np, seq_lens_np, tables_np, temps_np,
                    top_ps_np, rids_np, ngens_np):
        return ([p._data for p in self._params], self.kc, self.vc,
                jnp.asarray(tokens_np), jnp.asarray(seq_lens_np),
                jnp.asarray(tables_np),
                jnp.asarray(temps_np, jnp.float32),
                jnp.asarray(top_ps_np, jnp.float32),
                jnp.asarray(rids_np, jnp.int32),
                jnp.asarray(ngens_np, jnp.int32), self._base_key)

    def _run_chunk(self, tokens_np, seq_lens_np, tables_np,
                   temps_np, top_ps_np, rids_np, ngens_np,
                   phase: str = "decode"):
        from ..observability import trace as _otrace

        # serving always runs eval-mode (dropout off); restore the
        # caller's training flag afterwards — the engine must not mutate
        # a model a training loop is still using
        was_training = getattr(self.model, "training", False)
        if was_training:
            self.model.eval()
        t0 = time.perf_counter()
        try:
            nxt, self.kc, self.vc = self._fn(
                *self._chunk_args(tokens_np, seq_lens_np, tables_np,
                                  temps_np, top_ps_np, rids_np, ngens_np),
                sampling=bool(np.any(np.asarray(temps_np) > 0)))
            # np.asarray blocks until the program finishes, so this span
            # covers the chunk's actual device execution — the per-tick
            # prefill-vs-decode attribution loadgen/bench report
            out = np.asarray(nxt)  # tpulint: disable=TPU104 — host boundary by design: sampled token ids feed python-side scheduling
        finally:
            if was_training:
                self.model.train()
        t1 = time.perf_counter()
        self.scheduler.note_phase(
            phase, int(len(seq_lens_np)) * int(tokens_np.shape[1]),
            t1 - t0)
        if _otrace._active["on"]:
            _otrace.add_complete(f"serving.{phase}", "device", t0, t1,
                                 {"phase": phase,
                                  "batch": int(len(seq_lens_np))})
        return out

    def _run_verify(self, tokens_np, seq_lens_np, tables_np, temps_np,
                    top_ps_np, rids_np, ngens_np, max_accept_np):
        """Speculative verify program: decode-phase compute (the spans
        and token counters attribute it to decode — it IS the decode
        step, just yielding up to k+1 tokens)."""
        from ..observability import trace as _otrace

        was_training = getattr(self.model, "training", False)
        if was_training:
            self.model.eval()
        t0 = time.perf_counter()
        try:
            emit, n_emit, self.kc, self.vc = self._vfn(
                *self._chunk_args(tokens_np, seq_lens_np, tables_np,
                                  temps_np, top_ps_np, rids_np, ngens_np),
                jnp.asarray(max_accept_np, jnp.int32),
                sampling=bool(np.any(np.asarray(temps_np) > 0)))
            out = np.asarray(emit)  # tpulint: disable=TPU104 — host boundary by design: verified token ids feed python-side scheduling
            n_out = np.asarray(n_emit)  # tpulint: disable=TPU104 — same verify-result host boundary
        finally:
            if was_training:
                self.model.train()
        t1 = time.perf_counter()
        self.scheduler.note_phase(
            "decode", int(len(seq_lens_np)) * int(tokens_np.shape[1]),
            t1 - t0)
        if _otrace._active["on"]:
            _otrace.add_complete("serving.decode", "device", t0, t1,
                                 {"phase": "decode", "speculative": True,
                                  "batch": int(len(seq_lens_np))})
        return out, n_out

    # -------------------------------------------------------- scheduling
    def _blocks_needed(self, length: int) -> int:
        return -(-length // self.block_size)

    def _ensure_blocks(self, slot: int, length: int) -> bool:
        need = self._blocks_needed(length)
        have = len(self.slot_blocks[slot])
        if need > self.max_blocks_per_seq:
            raise MemoryError(
                f"sequence needs {need} blocks > max_blocks_per_seq "
                f"{self.max_blocks_per_seq}")
        if need > have:
            if need - have > self.bm.available:
                return False
            new = self.bm.allocate(need - have)
            for j, b in enumerate(new):
                self.tables[slot, have + j] = b
            self.slot_blocks[slot].extend(new)
        return True

    def _admit(self):
        from ..fault import inject as _inject

        for slot in range(self.max_batch):
            if not self.queue or self.slots[slot] is not None:
                continue
            req = self.queue[0]
            prefix_len = len(req.prompt) + len(req.generated)
            if (self._blocks_needed(prefix_len + 1)
                    > self.bm.available):
                break  # head-of-line blocks until memory frees
            self.queue.pop(0)
            self.slots[slot] = req
            self.tables[slot, :] = 0
            self.slot_blocks[slot] = []
            # allocate the prefix blocks NOW so the next admission's
            # availability check sees the reduced pool
            raced = _inject.fire("serving.admission_oom") is not None
            if raced or not self._ensure_blocks(slot, prefix_len):
                # admission raced cache exhaustion (a concurrent slot's
                # growth won the last blocks between the availability
                # check and the allocate): un-admit and retry next tick
                # — round 3 raised MemoryError here and killed the
                # engine with every in-flight decode
                self._release_slot(slot)
                self.queue.insert(0, req)
                break
            req.status = RequestStatus.RUNNING
            _res.M_ADMITTED.inc()
            self._rt_event(req.rid, "admitted", slot=slot,
                           prefix_tokens=prefix_len,
                           tick=self._ticks,
                           kv_blocks=len(self.slot_blocks[slot]))
            # stage the chunked prefill; compute happens in
            # _prefill_step under the scheduler's per-tick budget. The
            # prefix is LEFT-padded to a multiple of block_size — padded
            # positions sit at negative sequence positions, which the
            # paged-attention kernel drops from the cache write and
            # fully masks, so only two compiled shapes exist in steady
            # state: (max_batch, block_size) and the (max_batch, 1-or-
            # k+1) decode/verify.
            bs = self.block_size
            prefix = np.asarray(req.prompt + req.generated, np.int32)
            n_chunks = -(-len(prefix) // bs)
            pad = n_chunks * bs - len(prefix)
            self._prefilling[slot] = {
                "prefix": np.concatenate(
                    [np.zeros(pad, np.int32), prefix]),
                "n_chunks": n_chunks, "next": 0, "pad": pad}

    def _prefill_step(self):
        """Advance pending chunked prefills under the scheduler's
        per-tick budget: each chunk program carries the NEXT chunk of up
        to ``quota`` prefilling slots (slots at different chunk indices
        share one program — per-slot seq_lens position the writes). The
        final chunk of a slot yields its first sampled token; chunks
        past the budget defer to later ticks so the decode step below
        never waits out a long prompt."""
        bs = self.block_size
        quota = self.scheduler.chunk_quota(bs)
        while self._prefilling:
            slots = sorted(self._prefilling)
            if quota is not None:
                slots = slots[:quota]
                if not slots:
                    self.scheduler.note_deferred(sum(
                        st["n_chunks"] - st["next"]
                        for st in self._prefilling.values()))
                    # the WHY of a slow TTFT: this tick's budget pushed
                    # these requests' remaining chunks to a later tick
                    for slot, st in self._prefilling.items():
                        req = self.slots[slot]
                        if req is not None:
                            self._rt_event(
                                req.rid, "prefill_deferred",
                                tick=self._ticks,
                                chunks_left=st["n_chunks"] - st["next"])
                    return
            tokens = np.zeros((self.max_batch, bs), np.int32)
            seq = np.zeros((self.max_batch,), np.int32)   # 0 = inactive
            temps = np.zeros((self.max_batch,), np.float32)
            top_ps = np.ones((self.max_batch,), np.float32)
            rids = np.zeros((self.max_batch,), np.int32)
            ngens = np.zeros((self.max_batch,), np.int32)
            finalists = []
            for slot in slots:
                st = self._prefilling[slot]
                req = self.slots[slot]
                j = st["next"]
                tokens[slot] = st["prefix"][j * bs:(j + 1) * bs]
                seq[slot] = (j + 1) * bs - st["pad"]
                temps[slot] = req.temperature
                top_ps[slot] = req.top_p
                rids[slot] = req.rid
                ngens[slot] = len(req.generated)
                st["next"] = j + 1
                if st["next"] == st["n_chunks"]:
                    finalists.append(slot)
            nxt = self._run_chunk(tokens, seq, self.tables, temps, top_ps,
                                  rids, ngens, phase="prefill")
            if quota is not None:
                quota -= len(slots)
            now = self._clock()
            for slot in slots:
                # finalists' state entries are still live here — the
                # chunk just computed is the one BEFORE the cursor
                st = self._prefilling[slot]
                req = self.slots[slot]
                self._rt_event(req.rid, "prefill_chunk", t=now,
                               chunk=st["next"] - 1,
                               n_chunks=st["n_chunks"], tokens=bs,
                               tick=self._ticks)
            for slot in finalists:
                del self._prefilling[slot]
                req = self.slots[slot]
                # cached positions == the prefilled prefix; the sampled
                # token lands in the cache on its decode step
                self.seq_lens[slot] = len(req.prompt) + len(req.generated)
                tok = int(nxt[slot])
                req.generated.append(tok)
                self.last_token[slot] = tok
                self._record_token(req, now)
                self._maybe_finish(slot)

    def _evict(self, slot: int,
               reason: str = "kv-block pressure (livelock preemption)"):
        """Preempt a running request: release its blocks and requeue it
        for later re-admission (its generated prefix re-prefills then —
        vLLM-style recompute preemption)."""
        req = self.slots[slot]
        freed = len(self.slot_blocks[slot])
        self._release_slot(slot)
        req.status = RequestStatus.QUEUED
        _res.M_EVICTIONS.inc()
        self._rt_event(req.rid, "preempted", victim_reason=reason,
                       tick=self._ticks, kv_blocks_reclaimed=freed,
                       tokens_so_far=len(req.generated))
        self.queue.append(req)

    def _release_slot(self, slot: int):
        """Return a slot's KV blocks to the free list and reset its lane
        in the batch state (idle lanes point at the trash block)."""
        self.slots[slot] = None
        self._prefilling.pop(slot, None)
        self.bm.release(self.slot_blocks[slot])
        self.slot_blocks[slot] = []
        self.tables[slot, :] = 0
        self.seq_lens[slot] = 1
        self.last_token[slot] = 0

    def _finish_request(self, req: Request, status: str,
                        detail: str = ""):
        """Move ``req`` to a terminal status and record its outcome. The
        caller must already have released any slot/blocks it held."""
        req.status = status
        req.detail = detail
        req.finish_t = self._clock()
        self._rt_event(req.rid, "terminal", t=req.finish_t,
                       outcome=status, detail=detail,
                       tokens=len(req.generated))
        self._slo.note(req.finish_t,
                       good=(status == RequestStatus.FINISHED))
        _res.M_REQUESTS.inc(outcome=status)
        if status == RequestStatus.SHED:
            _res.M_SHED.inc()
        elif status == RequestStatus.DEADLINE_MISSED:
            _res.M_DEADLINE_MISSED.inc()
        self.outcomes[req.rid] = RequestOutcome(
            rid=req.rid, status=status, detail=detail,
            tokens=list(req.generated), submit_t=req.submit_t,
            first_token_t=req.first_token_t, finish_t=req.finish_t,
            token_times=list(req.token_times))
        self._stream_bufs.pop(req.rid, None)
        if status == RequestStatus.FINISHED:
            self._done.append(req)

    def _record_token(self, req: Request, now: float):
        """TTFT / inter-token latency bookkeeping for one new token.
        Exemplar linkage rides here: the worst TTFT/ITL samples keep
        the request id, so a p99 regression resolves to a timeline."""
        traced = _reqtrace.enabled()
        if req.first_token_t is None:
            req.first_token_t = now
            if req.submit_t is not None:
                ttft = now - req.submit_t
                _res.M_TTFT.observe(ttft)
                if traced:
                    self._rt_event(req.rid, "first_token", t=now,
                                   ttft_s=ttft)
                    _reqtrace.EXEMPLARS.note(
                        "ttft", self.lifecycle.name, req.rid, ttft, now)
        elif req.token_times:
            itl = now - req.token_times[-1]
            _res.M_ITL.observe(itl)
            if traced:
                _reqtrace.EXEMPLARS.note(
                    "itl", self.lifecycle.name, req.rid, itl, now)
        req.token_times.append(now)
        buf = self._stream_bufs.get(req.rid)
        if buf is not None:
            buf.append(req.generated[-1])

    def _maybe_finish(self, slot: int):
        req = self.slots[slot]
        if req is None:
            return
        last = req.generated[-1] if req.generated else None
        if (len(req.generated) >= req.max_new_tokens
                or (self.eos_id is not None and last == self.eos_id)):
            self._release_slot(slot)
            self._finish_request(req, RequestStatus.FINISHED)

    # ------------------------------------------------- deadlines/overload
    def _deadline_expired(self, req: Request, now: float) -> Optional[str]:
        """Reason string when ``req`` is past a deadline, else None."""
        if req.submit_t is None:
            return None
        waited = now - req.submit_t
        if req.deadline_s is not None and waited > req.deadline_s:
            return (f"total deadline {req.deadline_s}s expired after "
                    f"{waited:.3f}s ({len(req.generated)} tokens)")
        if (req.first_token_t is None and req.ttft_deadline_s is not None
                and waited > req.ttft_deadline_s):
            return (f"TTFT deadline {req.ttft_deadline_s}s expired after "
                    f"{waited:.3f}s with no first token")
        return None

    def _expire_deadlines(self):
        """Cancel queued AND in-flight requests whose TTFT/total deadline
        has passed; in-flight cancellations reclaim their KV blocks."""
        now = self._clock()
        kept = []
        for req in self.queue:
            why = self._deadline_expired(req, now)
            if why is None:
                kept.append(req)
            else:
                self._finish_request(req, RequestStatus.DEADLINE_MISSED,
                                     detail=why)
        self.queue = kept
        for slot, req in enumerate(self.slots):
            if req is None:
                continue
            why = self._deadline_expired(req, now)
            if why is not None:
                self._release_slot(slot)
                self._finish_request(req, RequestStatus.DEADLINE_MISSED,
                                     detail=why)

    def _shed_overload(self):
        """Past the queue high-water mark, shed the NEWEST queued
        requests (they would wait longest; the oldest are closest to a
        slot) down to the mark. Preempted requests carrying generated
        tokens are spared — shedding them would discard paid-for
        prefill/decode compute (the queue stays bounded by max_queue
        regardless)."""
        hw = self.resilience.queue_high_water
        if hw is None or len(self.queue) <= hw:
            return
        excess = len(self.queue) - hw
        kept_rev: List[Request] = []
        for req in reversed(self.queue):          # newest first
            if excess > 0 and not req.generated:
                excess -= 1
                self._finish_request(
                    req, RequestStatus.SHED,
                    detail=f"queue past high-water mark ({hw})")
            else:
                kept_rev.append(req)
        self.queue = kept_rev[::-1]

    def _eviction_key(self, slot: int):
        """Preemption victim ordering: most deadline slack first (no
        deadline = infinite slack), youngest rid as tie-break — evicting
        the request closest to its deadline would turn one preemption
        into a deadline miss."""
        req = self.slots[slot]
        if req.deadline_s is not None and req.submit_t is not None:
            dl = req.submit_t + req.deadline_s
        else:
            dl = float("inf")
        return (dl, req.rid)

    # ------------------------------------------------------------- ticks
    def step(self) -> Dict[int, List[int]]:
        """One engine tick: expire deadlines, admit queued requests,
        shed overload, advance chunked prefill under the scheduler's
        budget, then a single batched decode (or speculative verify)
        step for every fully-prefilled slot. Returns
        {rid: generated_tokens} for requests that finished this tick.

        Never raises from scheduling, memory pressure, or injected
        faults: an internal tick failure marks the in-flight requests
        FAILED, reclaims their KV blocks, and flips the replica
        DEGRADED — the engine keeps serving."""
        from ..observability import trace

        wd = self._watchdog
        if wd is not None:
            wd.begin_work()
        self._ticks += 1
        t0 = time.perf_counter()
        span_args = {"tick": self._ticks}
        try:
            with trace.span("serving.tick", "serving", args=span_args):
                try:
                    self._tick()
                    if self.lifecycle.state == ReplicaState.STARTING:
                        self.lifecycle.to(ReplicaState.READY, "serving")
                except Exception as e:
                    self._on_tick_failure(e)
                finally:
                    # this tick's phase split rides its span (read at
                    # span EXIT — end_tick resets the accumulator later)
                    span_args.update(
                        self.scheduler.tick_phase_seconds())
        finally:
            if wd is not None:
                wd.end_work()
            self.scheduler.end_tick()
            _res.M_TICK_SECONDS.observe(time.perf_counter() - t0)
            _res.M_QUEUE_DEPTH.set(len(self.queue))
            _res.M_KV_BLOCKS.set(self._total_usable - self.bm.available)
        return self._drain_done()

    def _tick(self):
        from ..fault import inject as _inject

        stall = _inject.fire("serving.tick_stall")
        if stall is not None:
            # a wedged device transfer/compile: the tick thread blocks,
            # no heartbeat reaches the watchdog
            time.sleep(float(stall.get("seconds", 0.1)))
        if _inject.fire("serving.crash_at_tick",
                        tick=self._ticks) is not None:
            raise _inject.InjectedFault(
                "serving.crash_at_tick",
                f"injected crash at tick {self._ticks}")
        self._expire_deadlines()
        if self._dense:
            # dense path: the tick itself admits (it consumes up to
            # max_batch from the queue head), so shed only what the
            # forward could not absorb
            self._dense_tick()
            self._shed_overload()
            return
        # admit BEFORE shedding: a burst hitting an idle replica flows
        # into free decode slots first; only what capacity could not
        # absorb this tick counts against the high-water mark
        self._admit()
        self._shed_overload()
        # phase split: bounded prefill, then decode — decode runs EVERY
        # tick there is decodable work, however much prefill is pending
        self._prefill_step()
        self._decode_active()

    def _dense_tick(self):
        """Score up to ``max_batch`` queued requests in ONE
        ``serve_dense`` forward. The id matrix is always
        (max_batch, width) — short batches ride zero rows — so jit
        compiles exactly one steady-state program. Each request emits a
        single score token (the [0, 1] score in basis points) and
        finishes; no engine state survives the tick."""
        if not self.queue:
            return
        batch = self.queue[:self.max_batch]
        del self.queue[:len(batch)]
        w = self.arch.width
        ids = np.zeros((self.max_batch, w), np.int32)
        for i, req in enumerate(batch):
            ids[i, :len(req.prompt)] = req.prompt
        was_training = getattr(self.model, "training", False)
        if was_training:
            self.model.eval()
        t0 = time.perf_counter()
        try:
            scores = self._dense_fn([p._data for p in self._params],
                                    jnp.asarray(ids))
            out = np.asarray(scores)  # tpulint: disable=TPU104 — host boundary by design: scores become outcome tokens
        finally:
            if was_training:
                self.model.train()
        self.scheduler.note_phase("decode", len(batch),
                                  time.perf_counter() - t0)
        now = self._clock()
        for i, req in enumerate(batch):
            bp = int(round(float(out[i]) * 10000.0))  # tpulint: disable=TPU103 — host boundary by design: the score token enters the python-side outcome
            req.generated.append(bp)
            self._rt_event(req.rid, "dense_score", t=now, score_bp=bp,
                           tick=self._ticks)
            self._record_token(req, now)
            self._finish_request(req, RequestStatus.FINISHED)

    def _decode_lanes(self) -> List[int]:
        """Slots holding a fully-prefilled request (mid-prefill slots
        stay out of the decode batch — their lanes run with the seq=0
        sentinel so the compiled shape never changes)."""
        return [i for i, s in enumerate(self.slots)
                if s is not None and i not in self._prefilling]

    def _decode_active(self):
        active = self._decode_lanes()
        if not active:
            return
        if self._spec is not None and self._spec_feasible(active):
            self._decode_speculative(active)
            return
        self._decode_plain(active)

    def _spec_feasible(self, active: List[int]) -> bool:
        """Speculate this tick only when every active slot has table
        room for the k draft positions — a slot whose sequence is
        within k of its ``max_blocks_per_seq`` ceiling must NOT feed a
        (seq+k)-length verify (the block-table lookup would clamp and
        corrupt another block's pages, and _ensure_blocks would raise
        out of the tick). Near-capacity ticks fall back to plain
        decode, which admission guarantees always fits."""
        cap = self.max_blocks_per_seq * self.block_size
        return all(self.slots[i].seq_len + self._spec_k <= cap
                   for i in active)

    def _decode_plain(self, active: List[int]):
        seq = self.seq_lens.copy()
        for i in self._prefilling:
            seq[i] = 0               # masked lane: no write, no attend
        skipped = []
        for i in active:
            # the cache holds seq_len-1 positions; the token being fed
            # (the newest sample) lands at position seq_len-1, so the
            # total INCLUDING it is exactly req.seq_len
            seq[i] = self.slots[i].seq_len
            if not self._ensure_blocks(i, int(seq[i])):
                # OOM: skip this slot's tick. Sentinel 0 — with seq=1
                # the op would write the token's K/V into position 0
                # of the slot's first REAL block, corrupting the
                # cached prompt; seq=0 puts the write at pos -1,
                # which the kernel drops and fully masks.
                seq[i] = 0
                skipped.append(i)
        if skipped and len(skipped) == len(active):
            # every active slot is memory-stalled: nobody can finish
            # to free blocks, so this would livelock. Preempt the slot
            # with the most deadline slack (vLLM recompute-preemption,
            # deadline-aware) and retry next tick with its blocks free.
            victim = max(skipped, key=self._eviction_key)
            self._evict(victim)
            return
        tokens = self.last_token[:, None].astype(np.int32)
        temps = np.zeros((self.max_batch,), np.float32)
        top_ps = np.ones((self.max_batch,), np.float32)
        rids = np.zeros((self.max_batch,), np.int32)
        ngens = np.zeros((self.max_batch,), np.int32)
        for i in active:
            temps[i] = self.slots[i].temperature
            top_ps[i] = self.slots[i].top_p
            rids[i] = self.slots[i].rid
            ngens[i] = len(self.slots[i].generated)
        nxt = self._run_chunk(tokens, seq, self.tables, temps, top_ps,
                              rids, ngens, phase="decode")
        now = self._clock()
        for i in active:
            if seq[i] == 0:
                continue
            req = self.slots[i]
            req.generated.append(int(nxt[i]))
            self.seq_lens[i] = int(seq[i])   # cached positions now
            self.last_token[i] = int(nxt[i])
            self._rt_event(req.rid, "decode_tick", t=now,
                           tick=self._ticks, new_tokens=1)
            self._record_token(req, now)
            self._maybe_finish(i)

    def _decode_speculative(self, active: List[int]):
        """Decode via the fused verify program: per active slot, feed
        [last_token, k n-gram draft tokens] in one (B, k+1) forward and
        take the accepted prefix + the model's own next token — up to
        k+1 tokens per slot per tick, greedy output identical to plain
        decode by construction (acceptance only keeps drafts the target
        model would have emitted itself)."""
        from ..serving import speculative as _spec_mod

        k = self._spec_k
        T = k + 1
        seq = self.seq_lens.copy()
        for i in range(self.max_batch):
            if i not in active:
                seq[i] = 0           # idle / mid-prefill: masked lane
        tokens = np.zeros((self.max_batch, T), np.int32)
        temps = np.zeros((self.max_batch,), np.float32)
        top_ps = np.ones((self.max_batch,), np.float32)
        rids = np.zeros((self.max_batch,), np.int32)
        ngens = np.zeros((self.max_batch,), np.int32)
        max_accept = np.zeros((self.max_batch,), np.int32)
        skipped = []
        max_pos = getattr(self.arch, "max_positions", None)
        for i in active:
            req = self.slots[i]
            # draft positions extend to seq_len-1+k: allocate for the
            # whole verify up front (stale tail entries are masked by
            # the rolled-back seq_len and overwritten as the sequence
            # legitimately reaches them)
            if not self._ensure_blocks(i, req.seq_len + k):
                seq[i] = 0
                skipped.append(i)
                continue
            draft: List[int] = []
            if req.temperature == 0:
                draft = list(self._spec.propose(
                    req.prompt + req.generated))[:k]
            ma = len(draft)
            if max_pos is not None:
                # drafts whose positions would clip-gather past the
                # learned-position table can never be verified honestly
                ma = max(0, min(ma, max_pos - req.seq_len))
            row = [int(self.last_token[i])] + draft
            row += [row[-1]] * (T - len(row))     # pad: always rejected
            tokens[i] = row
            seq[i] = req.seq_len + k
            temps[i] = req.temperature
            top_ps[i] = req.top_p
            rids[i] = req.rid
            ngens[i] = len(req.generated)
            max_accept[i] = ma
        if skipped and len(skipped) == len(active):
            victim = max(skipped, key=self._eviction_key)
            self._evict(victim)
            return
        if not skipped and not max_accept.any():
            # nothing speculates this tick (sampling-only batch, or the
            # proposer came up dry everywhere): the plain (B, 1) decode
            # emits the same tokens for (k+1)x less attention/logit
            # work — a dry proposer costs one ordinary decode step
            self._decode_plain(active)
            return
        emit, n_emit = self._run_verify(tokens, seq, self.tables, temps,
                                        top_ps, rids, ngens, max_accept)
        now = self._clock()
        proposed = accepted = 0
        for i in active:
            if seq[i] == 0:
                continue
            req = self.slots[i]
            ne = int(n_emit[i])
            proposed += int(max_accept[i])
            accepted += ne - 1
            self._rt_event(req.rid, "spec_verify", t=now,
                           tick=self._ticks,
                           proposed=int(max_accept[i]),
                           accepted=ne - 1, new_tokens=ne)
            for j in range(ne):
                tok = int(emit[i, j])
                req.generated.append(tok)
                self.last_token[i] = tok
                self._record_token(req, now)
                if (len(req.generated) >= req.max_new_tokens
                        or (self.eos_id is not None
                            and tok == self.eos_id)):
                    break            # _maybe_finish releases the slot
            # valid cached positions: everything up to (not including)
            # the newest sampled token — identical invariant to decode
            self.seq_lens[i] = req.seq_len - 1
            self._maybe_finish(i)
        if proposed:
            self.spec_proposed += proposed
            self.spec_accepted += accepted
            _spec_mod.M_SPEC_PROPOSED.inc(proposed)
            _spec_mod.M_SPEC_ACCEPTED.inc(accepted)
            _spec_mod.M_SPEC_ACCEPT_RATE.set(
                self.spec_accepted / max(self.spec_proposed, 1))

    def _on_tick_failure(self, exc: BaseException):
        """Contain an unexpected tick error: the in-flight requests are
        FAILED (their KV state is suspect), their blocks reclaimed, and
        the replica degrades — it keeps serving new requests, but the
        readiness probe goes red so the balancer backs off."""
        _res.M_TICK_FAILURES.inc()
        self.tick_failures += 1
        detail = f"tick {self._ticks} failed: {exc!r}"
        for slot, req in enumerate(self.slots):
            if req is None:
                continue
            try:
                self._release_slot(slot)
            except Exception:
                self.slots[slot] = None   # never mask the containment
            self._finish_request(req, RequestStatus.FAILED, detail=detail)
        # the decode call DONATES kc/vc: a crash inside the executable
        # may have invalidated those buffers with the new ones never
        # assigned. Reallocate fresh pages — every slot was discarded
        # above, so later admissions re-prefill from their prompts; a
        # stale-buffer engine would otherwise fail every future tick
        # while still admitting.
        self.kc = [self._fresh_cache() for _ in range(self.cfg.num_layers)]
        self.vc = [self._fresh_cache() for _ in range(self.cfg.num_layers)]
        self.lifecycle.degrade(detail)

    def _drain_done(self) -> Dict[int, List[int]]:
        """Hand completed requests to the caller and DROP them — a
        long-running server must not retain every request ever served."""
        out = dict(self._spillover)   # client traffic served mid-warmup
        self._spillover.clear()
        out.update((req.rid, req.generated) for req in self._done)
        self._done.clear()
        return out

    def run_to_completion(self, max_ticks: int = 10_000):
        """Tick until no work remains; returns {rid: generated_tokens}
        for FINISHED requests. Requests that ended SHED / DEADLINE_MISSED
        / CANCELLED / FAILED are absent here — read ``self.outcomes``
        (or ``drain_outcomes()``) for their terminal records; never-
        fitting submissions also appear in ``self.rejected``."""
        out: Dict[int, List[int]] = {}
        ticks = 0
        while self.has_work():
            out.update(self.step())
            ticks += 1
            if ticks > max_ticks:
                raise RuntimeError("serving engine did not converge")
        return out

    # ------------------------------------------------ replica operations
    def request_status(self, rid: int) -> Optional[str]:
        """Current status of a submitted request (terminal statuses stay
        readable until ``drain_outcomes`` pops them); None = unknown."""
        oc = self.outcomes.get(rid)
        if oc is not None:
            return oc.status
        for req in self.queue:
            if req.rid == rid:
                return req.status
        for req in self.slots:
            if req is not None and req.rid == rid:
                return req.status
        return None

    def drain_outcomes(self) -> Dict[int, RequestOutcome]:
        """Hand terminal outcomes to the caller and drop them (same
        retention contract as ``_drain_done``: a long-running replica
        must not retain every request ever served)."""
        out, self.outcomes = self.outcomes, {}
        for rid in out:          # rejected mirrors submit-time FAILED
            self.rejected.pop(rid, None)
        return out

    def cancel(self, rid: int, reason: str = "cancelled by caller") -> bool:
        """Cancel a queued or in-flight request; its KV blocks return to
        the free list immediately. False if ``rid`` is not live."""
        for i, req in enumerate(self.queue):
            if req.rid == rid:
                self.queue.pop(i)
                self._finish_request(req, RequestStatus.CANCELLED,
                                     detail=reason)
                return True
        for slot, req in enumerate(self.slots):
            if req is not None and req.rid == rid:
                self._release_slot(slot)
                self._finish_request(req, RequestStatus.CANCELLED,
                                     detail=reason)
                return True
        return False

    # --------------------------------------------------------- streaming
    def open_stream(self, rid: int) -> List[int]:
        """Attach (or fetch) the incremental token buffer for ``rid``;
        every token the request generates from now on is appended.
        Tokens generated before the stream opened are replayed first, so
        a late-attaching client still sees the whole completion. The
        buffer object stays valid after the request ends (the engine
        drops its own reference at terminal — the stream keeps the
        list)."""
        buf = self._stream_bufs.get(rid)
        if buf is not None:
            return buf
        buf = []
        oc = self.outcomes.get(rid)
        if oc is not None:               # already terminal: replay only
            buf.extend(oc.tokens)
            return buf
        for req in list(self.queue) + [s for s in self.slots
                                       if s is not None]:
            if req.rid == rid:
                buf.extend(req.generated)
                self._stream_bufs[rid] = buf
                return buf
        return buf                       # unknown rid: empty, terminal

    def stream(self, rid: int):
        """Incremental token stream for one request: iterate tokens as
        ticks produce them (the iterator pumps ``step()`` while the
        request is live); iteration ends at the terminal status, left on
        ``stream.status``. See ``paddle_tpu.serving.TokenStream``."""
        from ..serving.stream import TokenStream
        return TokenStream(
            rid, self.open_stream(rid), self.step,
            lambda: self.request_status(rid),
            lambda s: s is None or s in TERMINAL_STATUSES,
            trace_hook=lambda ev, **meta: self._rt_event(rid, ev, **meta))

    def warmup(self, prompt_len: Optional[int] = None,
               max_new_tokens: int = 2) -> "PagedEngine":
        """Compile the steady-state programs (full prefill chunk + the
        batched decode step) before real traffic:
        STARTING→WARMING→READY. Idempotent on a READY replica.

        Traffic that arrived before READY (admission is open from
        STARTING — those requests wait for exactly these compiles) is
        served alongside the synthetic warmup request; its results are
        re-delivered by the next ``step()``/``run_to_completion``."""
        if self.lifecycle.state == ReplicaState.READY:
            return self
        self.lifecycle.to(ReplicaState.WARMING, "warmup")
        n = prompt_len if prompt_len is not None else self.block_size
        rid = self.add_request([1] * max(1, n),
                               max_new_tokens=max_new_tokens)
        # the synthetic request is operator work: no SLO deadlines
        # (expiring it mid-compile would block READY), and it jumps to
        # the queue head so a pre-READY client burst can neither starve
        # nor shed it
        for i, req in enumerate(self.queue):
            if req.rid == rid:
                req.ttft_deadline_s = req.deadline_s = None
                self.queue.insert(0, self.queue.pop(i))
                break
        while self.outcomes.get(rid) is None and self.has_work():
            res = self.step()
            res.pop(rid, None)          # warmup is not traffic
            self._spillover.update(res)
        oc = self.outcomes.pop(rid, None)
        if oc is None or oc.status != RequestStatus.FINISHED:
            # stay in WARMING (still admits): READY would advertise a
            # replica whose steady-state programs never compiled
            raise RuntimeError(
                f"warmup request ended "
                f"{oc.status if oc else '<missing>'}: "
                f"{oc.detail if oc else ''}")
        self.lifecycle.to(ReplicaState.READY, "warmup complete")
        return self

    def drain(self, max_ticks: int = 10_000) -> Dict[int, List[int]]:
        """Graceful shutdown: stop admission, finish in-flight decodes,
        then STOP. Queued requests that never got a slot are CANCELLED
        (their clients retry on another replica); running requests
        decode to completion. Returns {rid: tokens} finished during the
        drain."""
        if self.lifecycle.state == ReplicaState.STOPPED:
            return {}
        self.lifecycle.to(ReplicaState.DRAINING, "drain()")
        for req in self.queue:
            self._finish_request(req, RequestStatus.CANCELLED,
                                 detail="drained before admission")
        self.queue = []
        out: Dict[int, List[int]] = {}
        ticks = 0
        # loop on has_work(), not num_active: livelock preemption can
        # bounce an in-flight request back through the queue mid-drain,
        # and it still must reach a terminal status
        while self.has_work():
            out.update(self.step())
            ticks += 1
            if ticks > max_ticks:
                # fail whatever is still live rather than spin forever
                for req in self.queue:
                    self._finish_request(req, RequestStatus.FAILED,
                                         detail="drain did not converge")
                self.queue = []
                for slot, req in enumerate(self.slots):
                    if req is not None:
                        self._release_slot(slot)
                        self._finish_request(
                            req, RequestStatus.FAILED,
                            detail="drain did not converge")
                break
        self.lifecycle.to(ReplicaState.STOPPED, "drained")
        _res.M_QUEUE_DEPTH.set(0)
        _res.M_KV_BLOCKS.set(self._total_usable - self.bm.available)
        return out

    def recover(self, reason: str = "operator recover"):
        """DEGRADED → READY once the operator (or an orchestrator health
        check) has decided the stall/crash cause is gone."""
        self.lifecycle.to(ReplicaState.READY, reason)

    def attach_watchdog(self, watchdog) -> "PagedEngine":
        """Wire a :class:`~paddle_tpu.distributed.watchdog.Watchdog`
        into the tick loop: every tick brackets begin_work/end_work (so
        an idle engine stays quiet), and a tick stalled past the
        watchdog timeout flips this replica DEGRADED while the watchdog
        dumps thread stacks + the span-buffer tail."""
        self._watchdog = watchdog
        prev = watchdog.on_hang

        def _on_hang(wd):
            self.lifecycle.degrade(
                f"tick stalled > {wd.timeout}s (watchdog)")
            if prev is not None:
                prev(wd)

        watchdog.on_hang = _on_hang
        return self

    def health(self) -> dict:
        """Liveness/readiness probe payload (what an HTTP /healthz in
        front of this replica returns)."""
        lc = self.lifecycle
        h = {"state": lc.state, "ready": lc.ready(),
             "live": lc.live(),
             "queue_depth": len(self.queue),
             "active": self.num_active,
             "prefilling": len(self._prefilling),
             "kv_blocks_free": self.bm.available,
             "kv_blocks_total": self._total_usable,
             "kv_dtype": str(self.kv_dtype),
             "kv_bytes_per_token": self.kv_bytes_per_token,
             "ticks": self._ticks,
             "tick_failures": self.tick_failures,
             "phase_share": self.scheduler.phase_share(),
             # the probe path doubles as the burn-rate decay poll: an
             # idle replica's windows age out here, so the gauges fall
             # back to 0 after an incident instead of pinning high
             "slo_burn_rate": self._slo.burn_rates(self._clock())}
        if self._spec is not None:
            h["spec_acceptance_rate"] = (
                self.spec_accepted / self.spec_proposed
                if self.spec_proposed else None)
        return h


# Backward-compatible names: the generic engine picks the adapter itself.
LlamaPagedEngine = PagedEngine
GPTPagedEngine = PagedEngine
