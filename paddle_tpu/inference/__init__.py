"""paddle.inference — the deployment predictor facade.

Capability parity with the reference inference API (reference:
paddle/fluid/inference/api/analysis_predictor.cc + python/paddle/inference/
— Config(model_file, params_file), create_predictor, get_input_handle /
run / get_output_handle). TPU-native: the "analysis + optimization passes"
role is XLA compilation of the saved StableHLO program (paddle_tpu.jit
artifacts); the predictor wraps a TranslatedLayer with the reference's
handle-style API so serving code ports directly.
"""
from __future__ import annotations

from typing import List, Optional

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor


class Config:
    """reference inference Config (model + params paths, device knobs)."""

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        # accept either the artifact prefix or explicit file names
        if prog_file and prog_file.endswith(".pdmodel"):
            prog_file = prog_file[:-len(".pdmodel")]
        self.prefix = prog_file
        self.params_file = params_file
        self._device = "tpu"
        self._device_id = 0

    def set_prog_file(self, path: str):
        self.prefix = path[:-len(".pdmodel")] if path.endswith(".pdmodel") \
            else path

    def enable_use_gpu(self, memory_pool_mb: int = 100, device_id: int = 0):
        self._device, self._device_id = "tpu", device_id   # accel alias

    def disable_gpu(self):
        self._device = "cpu"

    def enable_memory_optim(self):
        pass    # XLA owns buffer assignment

    def switch_ir_optim(self, flag: bool = True):
        pass    # XLA pipeline always on


class _Handle:
    """Input/output tensor handle (reference ZeroCopyTensor)."""

    def __init__(self):
        self._value: Optional[np.ndarray] = None

    def copy_from_cpu(self, arr: np.ndarray):
        self._value = np.asarray(arr)

    def reshape(self, shape):
        if self._value is None:
            self._value = np.zeros(shape, np.float32)
        else:
            self._value = self._value.reshape(shape)

    def copy_to_cpu(self) -> np.ndarray:
        return self._value

    def shape(self):
        return list(self._value.shape) if self._value is not None else []


class Predictor:
    def __init__(self, config: Config):
        from ..jit.api import load as jit_load
        if config.prefix is None:
            raise ValueError("Config needs the saved model prefix")
        self._layer = jit_load(config.prefix)
        if isinstance(self._layer, dict):
            raise ValueError(
                f"{config.prefix}.pdmodel not found — jit.save the program "
                "artifact, not just parameters, for inference")
        n = int(getattr(self._layer, "n_inputs", 1))
        self._inputs: List[_Handle] = [_Handle() for _ in range(n)]
        self._outputs: List[_Handle] = []

    def get_input_names(self):
        return [f"input_{i}" for i in range(len(self._inputs))]

    def get_input_handle(self, name: str) -> _Handle:
        idx = int(name.rsplit("_", 1)[-1]) if name.rsplit(
            "_", 1)[-1].isdigit() else 0
        while len(self._inputs) <= idx:
            self._inputs.append(_Handle())
        return self._inputs[idx]

    def run(self):
        missing = [i for i, h in enumerate(self._inputs)
                   if h._value is None]
        if missing:
            raise RuntimeError(
                f"input handle(s) {missing} were never set; the model "
                f"expects {len(self._inputs)} inputs")
        args = [Tensor(jnp.asarray(h._value)) for h in self._inputs]
        out = self._layer(*args)
        outs = out if isinstance(out, (list, tuple)) else [out]
        self._outputs = []
        for o in outs:
            h = _Handle()
            h.copy_from_cpu(np.asarray(  # tpulint: disable=TPU104 — host-by-design: the Predictor ABI returns host ndarrays (copy_to_cpu contract)
                o._data if isinstance(o, Tensor) else o))
            self._outputs.append(h)
        return True

    def get_output_names(self):
        return [f"output_{i}" for i in range(len(self._outputs))]

    def get_output_handle(self, name: str) -> _Handle:
        idx = int(name.rsplit("_", 1)[-1]) if name.rsplit(
            "_", 1)[-1].isdigit() else 0
        return self._outputs[idx]


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


from .resilience import (Overloaded, ReplicaLifecycle,  # noqa: E402
                         ReplicaState, RequestOutcome, RequestStatus,
                         ResilienceConfig)
from .serving import (BlockManager, GPTPagedEngine,  # noqa: E402
                      LlamaPagedEngine, PagedEngine, Request)

__all__ = ["Config", "Predictor", "create_predictor", "BlockManager",
           "PagedEngine", "LlamaPagedEngine", "GPTPagedEngine",
           "Request", "Overloaded", "ReplicaLifecycle", "ReplicaState",
           "RequestOutcome", "RequestStatus", "ResilienceConfig"]
