"""Serving-tier resilience primitives — statuses, SLOs, replica lifecycle.

The continuous-batching engine (``inference/serving.py``) is the data
plane; this module is its control-plane vocabulary, shaped after the
reference's serving watchdog layer (comm_task_manager.cc hang handling +
the block-attention serving family PaddleNLP's tier drives):

* :class:`RequestStatus` — every submitted request ends in exactly one
  terminal status (``FINISHED/SHED/DEADLINE_MISSED/CANCELLED/FAILED``);
  overload, memory races, deadline expiry and injected faults are
  per-request outcomes, never exceptions out of the tick loop.
* :class:`Overloaded` — the one exception a *submitter* sees: explicit
  backpressure from the bounded admission queue (or a draining/stopped
  replica). Callers retry against another replica; the engine never
  dies of admission pressure.
* :class:`ResilienceConfig` — the SLO knobs: queue bound, shed
  high-water mark, default TTFT/total deadlines.
* :class:`ReplicaLifecycle` — explicit replica states
  (``STARTING→WARMING→READY→DEGRADED→DRAINING→STOPPED``) with validated
  transitions and health/readiness probes, so a load balancer can stop
  routing to a stalled or draining replica without killing it.

Serving metric instruments (``paddle_tpu_serving_*``) are declared here
once; collection is gated by ``FLAGS_enable_metrics`` as everywhere else.
"""
from __future__ import annotations

import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Tuple

from ..observability import metrics as _metrics

__all__ = ["RequestStatus", "TERMINAL_STATUSES", "Overloaded",
           "RequestOutcome", "ResilienceConfig", "ReplicaState",
           "ReplicaLifecycle"]


class RequestStatus:
    """String constants for the per-request state machine.

    ``QUEUED → RUNNING → FINISHED`` is the happy path; every other
    terminal is a degraded-but-accounted outcome. A request may bounce
    ``RUNNING → QUEUED`` under recompute preemption.
    """

    QUEUED = "QUEUED"                  # accepted, waiting for a slot
    RUNNING = "RUNNING"                # holds a slot and KV blocks
    FINISHED = "FINISHED"              # completed normally (eos / budget)
    SHED = "SHED"                      # dropped by overload shedding
    DEADLINE_MISSED = "DEADLINE_MISSED"  # TTFT or total deadline expired
    CANCELLED = "CANCELLED"            # caller cancel() or drain()
    FAILED = "FAILED"                  # never-fitting / tick crash


#: statuses a request can never leave
TERMINAL_STATUSES = frozenset({
    RequestStatus.FINISHED, RequestStatus.SHED,
    RequestStatus.DEADLINE_MISSED, RequestStatus.CANCELLED,
    RequestStatus.FAILED,
})


class Overloaded(RuntimeError):
    """Submit-time backpressure: the admission queue is full or the
    replica is draining/stopped. The request was NOT accepted — retry on
    another replica (or later)."""


@dataclass
class RequestOutcome:
    """Terminal record handed back for every submitted request."""

    rid: int
    status: str
    detail: str = ""
    tokens: List[int] = field(default_factory=list)
    submit_t: Optional[float] = None
    first_token_t: Optional[float] = None
    finish_t: Optional[float] = None
    token_times: List[float] = field(default_factory=list)

    @property
    def ttft(self) -> Optional[float]:
        if self.submit_t is None or self.first_token_t is None:
            return None
        return self.first_token_t - self.submit_t

    @property
    def itls(self) -> List[float]:
        """Inter-token latencies (seconds) between consecutive tokens."""
        ts = self.token_times
        return [b - a for a, b in zip(ts, ts[1:])]


@dataclass
class ResilienceConfig:
    """SLO / overload knobs for one engine replica.

    ``max_queue``
        Bounded admission queue: ``add_request`` past this depth raises
        :class:`Overloaded` (explicit backpressure to the client).
    ``queue_high_water``
        Load-shedding threshold checked each tick: queued requests past
        this depth (newest first — they would wait longest) are marked
        ``SHED``. ``None`` disables shedding below the queue bound.
    ``default_ttft_deadline_s`` / ``default_deadline_s``
        Applied to requests submitted without explicit deadlines.
        ``None`` means unbounded.
    ``slo_target`` / ``slo_fast_window_s`` / ``slo_slow_window_s``
        The availability objective the deadlines serve and the two
        sliding windows behind the
        ``paddle_tpu_serving_slo_{fast,slow}_burn_rate`` gauges (SRE
        multiwindow pattern; see ``observability/reqtrace.py``). A
        terminal outcome other than FINISHED burns error budget.
    """

    max_queue: int = 256
    queue_high_water: Optional[int] = None
    default_ttft_deadline_s: Optional[float] = None
    default_deadline_s: Optional[float] = None
    slo_target: float = 0.99
    slo_fast_window_s: float = 60.0
    slo_slow_window_s: float = 600.0

    def __post_init__(self):
        if self.max_queue < 1:
            raise ValueError("max_queue must be >= 1")
        if (self.queue_high_water is not None
                and not 0 <= self.queue_high_water <= self.max_queue):
            raise ValueError(
                f"queue_high_water must be in [0, max_queue="
                f"{self.max_queue}]")
        if not 0.0 < self.slo_target < 1.0:
            raise ValueError("slo_target must be in (0, 1)")
        if not 0.0 < self.slo_fast_window_s <= self.slo_slow_window_s:
            raise ValueError(
                "need 0 < slo_fast_window_s <= slo_slow_window_s")


class ReplicaState:
    """Replica lifecycle states (ordinal order = the normal progression;
    the gauge exports the ordinal)."""

    STARTING = "STARTING"    # constructed, programs not compiled
    WARMING = "WARMING"      # warmup request compiling prefill/decode
    READY = "READY"          # serving, readiness probe green
    DEGRADED = "DEGRADED"    # serving, but a tick stalled/crashed —
    #                          readiness red so the LB drains traffic away
    DRAINING = "DRAINING"    # admission closed, finishing in-flight work
    STOPPED = "STOPPED"      # drained; liveness red

    ORDER = (STARTING, WARMING, READY, DEGRADED, DRAINING, STOPPED)


_ALLOWED_TRANSITIONS = {
    ReplicaState.STARTING: {ReplicaState.WARMING, ReplicaState.READY,
                            ReplicaState.DEGRADED,   # first tick can crash
                            ReplicaState.DRAINING, ReplicaState.STOPPED},
    ReplicaState.WARMING: {ReplicaState.READY, ReplicaState.DEGRADED,
                           ReplicaState.DRAINING, ReplicaState.STOPPED},
    ReplicaState.READY: {ReplicaState.DEGRADED, ReplicaState.DRAINING,
                         ReplicaState.STOPPED},
    ReplicaState.DEGRADED: {ReplicaState.READY, ReplicaState.DRAINING,
                            ReplicaState.STOPPED},
    ReplicaState.DRAINING: {ReplicaState.STOPPED},
    ReplicaState.STOPPED: set(),
}

#: states in which new submissions are accepted (queueing before READY is
#: fine — the warmup compiles are exactly what they wait for)
_ADMITTING = frozenset({ReplicaState.STARTING, ReplicaState.WARMING,
                        ReplicaState.READY, ReplicaState.DEGRADED})


#: default replica-name ordinals (stable within one process)
_REPLICA_COUNTER = itertools.count(0)


class ReplicaLifecycle:
    """Validated replica state machine + probes.

    Thread-safe: the watchdog flips ``DEGRADED`` from its poll thread
    while the tick loop runs. Invalid transitions raise — a replica that
    silently resurrects from ``STOPPED`` is a routing bug.
    """

    def __init__(self, clock: Callable[[], float] = time.monotonic,
                 name: Optional[str] = None):
        self._clock = clock
        self._lock = threading.Lock()
        self.state = ReplicaState.STARTING
        #: stable per-replica metric label — several engines in one
        #: process (multi-replica serving) must not clobber each
        #: other's probe gauges
        self.name = name if name is not None else \
            f"replica{next(_REPLICA_COUNTER)}"
        self.history: List[Tuple[float, str, str]] = []  # (t, state, why)
        self._export_state()

    def _export_state(self, prev: Optional[str] = None):
        """Metrics on every transition: state ordinal + the probe
        results (what /readyz and /livez would answer right now) + a
        labeled transition counter, so a router/dashboard can follow a
        replica without polling health() — and so ``fleet.snapshot()``
        carries it per rank. The probe gauges are labeled per replica;
        the (pre-existing) state ordinal gauge stays unlabeled,
        last-writer-wins, for dashboard back-compat."""
        M_REPLICA_STATE.set(ReplicaState.ORDER.index(self.state))
        M_REPLICA_READY.set(1.0 if self.state == ReplicaState.READY
                            else 0.0, replica=self.name)
        M_REPLICA_LIVE.set(0.0 if self.state == ReplicaState.STOPPED
                           else 1.0, replica=self.name)
        if prev is not None:
            M_REPLICA_TRANSITIONS.inc(from_state=prev,
                                      to_state=self.state)

    def to(self, state: str, reason: str = "") -> str:
        with self._lock:
            if state == self.state:
                return self.state
            if state not in _ALLOWED_TRANSITIONS[self.state]:
                raise RuntimeError(
                    f"invalid replica transition {self.state} -> {state}"
                    + (f" ({reason})" if reason else ""))
            prev = self.state
            self.state = state
            self.history.append((self._clock(), state, reason))
            self._export_state(prev)
            return state

    # ------------------------------------------------------------- probes
    def ready(self) -> bool:
        """Readiness: should the load balancer route NEW traffic here."""
        return self.state == ReplicaState.READY

    def live(self) -> bool:
        """Liveness: the replica process is worth keeping."""
        return self.state != ReplicaState.STOPPED

    def admitting(self) -> bool:
        return self.state in _ADMITTING

    def degrade(self, reason: str = ""):
        """Best-effort flip to DEGRADED (no-op once draining/stopped) —
        the watchdog path must never raise from its poll thread."""
        with self._lock:
            if ReplicaState.DEGRADED in _ALLOWED_TRANSITIONS[self.state]:
                prev = self.state
                self.state = ReplicaState.DEGRADED
                self.history.append(
                    (self._clock(), ReplicaState.DEGRADED, reason))
                self._export_state(prev)


# --------------------------------------------------------------------------
# Serving metric instruments (stable names — see README "Serving
# resilience"). Declared once at import; recording is FLAGS_enable_metrics
# gated at dict-lookup cost like every other subsystem.
# --------------------------------------------------------------------------
M_QUEUE_DEPTH = _metrics.gauge(
    "paddle_tpu_serving_queue_depth",
    "Requests waiting in the admission queue (sampled each tick and on "
    "submit).")
M_ADMITTED = _metrics.counter(
    "paddle_tpu_serving_admitted",
    "Requests admitted into a decode slot (re-admissions after "
    "preemption count again).")
M_SHED = _metrics.counter(
    "paddle_tpu_serving_shed",
    "Queued requests dropped by overload shedding past "
    "queue_high_water.")
M_DEADLINE_MISSED = _metrics.counter(
    "paddle_tpu_serving_deadline_missed",
    "Requests cancelled because their TTFT or total deadline expired.")
M_EVICTIONS = _metrics.counter(
    "paddle_tpu_serving_evictions",
    "Recompute preemptions: a running request evicted to free KV blocks "
    "and requeued.")
M_TTFT = _metrics.histogram(
    "paddle_tpu_serving_ttft_seconds",
    "Time from submit to first generated token.")
M_ITL = _metrics.histogram(
    "paddle_tpu_serving_itl_seconds",
    "Inter-token latency between consecutive generated tokens of one "
    "request.")
M_KV_BLOCKS = _metrics.gauge(
    "paddle_tpu_serving_kv_blocks_in_use",
    "Physical KV-cache blocks currently allocated to requests.")
M_KV_BYTES_PER_TOKEN = _metrics.gauge(
    "paddle_tpu_serving_kv_bytes_per_token",
    "Resident KV bytes one cached token costs across all layers "
    "(int8 page pools roughly halve this vs bf16 — the resident-batch "
    "multiplier).")
M_REQUESTS = _metrics.counter(
    "paddle_tpu_serving_requests",
    "Requests reaching a terminal status, by outcome.",
    labelnames=("outcome",))
M_TICK_SECONDS = _metrics.histogram(
    "paddle_tpu_serving_tick_seconds",
    "Wall time of one engine tick (admit + prefill + batched decode).")
M_TICK_FAILURES = _metrics.counter(
    "paddle_tpu_serving_tick_failures",
    "Engine ticks that raised internally; the tick loop absorbed the "
    "error, failed the in-flight requests and degraded the replica.")
M_REPLICA_STATE = _metrics.gauge(
    "paddle_tpu_serving_replica_state",
    "Replica lifecycle state ordinal: 0=STARTING 1=WARMING 2=READY "
    "3=DEGRADED 4=DRAINING 5=STOPPED.")
M_REPLICA_READY = _metrics.gauge(
    "paddle_tpu_serving_replica_ready",
    "Readiness probe as a metric (1 = route new traffic here), updated "
    "on every lifecycle transition, per replica.",
    labelnames=("replica",))
M_REPLICA_LIVE = _metrics.gauge(
    "paddle_tpu_serving_replica_live",
    "Liveness probe as a metric (0 = STOPPED), updated on every "
    "lifecycle transition, per replica.", labelnames=("replica",))
M_REPLICA_TRANSITIONS = _metrics.counter(
    "paddle_tpu_serving_replica_transitions_total",
    "Replica lifecycle transitions, by (from_state, to_state).",
    labelnames=("from_state", "to_state"))
