"""paddle.regularizer — weight-decay regularizers.

Reference: ``python/paddle/regularizer.py`` (``L1Decay`` :51, ``L2Decay``
:169 — both applied by folding into the gradient inside the optimizer;
ParamAttr-level regularizers take priority over the optimizer-level one).

TPU-native: the fold happens inside the jitted optimizer step
(``Optimizer._apply_decay``), so the decay term fuses into the update
kernel instead of materializing a separate regularizer op graph.
"""
from __future__ import annotations

__all__ = ["WeightDecayRegularizer", "L1Decay", "L2Decay"]


class WeightDecayRegularizer:
    """Base (reference WeightDecayRegularizer)."""

    def __init__(self, coeff: float = 0.0):
        self._coeff = float(coeff)

    @property
    def coeff(self) -> float:
        return self._coeff

    def __repr__(self):
        return f"{type(self).__name__}(coeff={self._coeff})"


class L1Decay(WeightDecayRegularizer):
    """loss += coeff * sum(|w|): gradient fold g + coeff * sign(w)."""


class L2Decay(WeightDecayRegularizer):
    """loss += 0.5 * coeff * sum(w^2): gradient fold g + coeff * w."""
