"""paddle_tpu.strings — the string kernel surface as a python namespace.

Reference: ``paddle/phi/kernels/strings/`` exposes these kernels at the C++
level (``strings_empty``, ``strings_copy``, ``strings_lower``,
``strings_upper``); here they are host functions over
:class:`~paddle_tpu.core.string_tensor.StringTensor`.
"""
from .core.string_tensor import (StringTensor, copy, empty, empty_like,
                                 lower, to_string_tensor, upper)

__all__ = ["StringTensor", "to_string_tensor", "empty", "empty_like",
           "copy", "lower", "upper"]
