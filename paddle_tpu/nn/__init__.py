"""paddle_tpu.nn — neural network layers.

Reference surface: python/paddle/nn/__init__.py.
"""
from . import functional
from . import initializer
from . import quant
from .parameter import Parameter, ParamAttr, create_parameter
from .layer import *  # noqa: F401,F403
from .layer.layers import Layer
from .decode import BeamSearchDecoder, Decoder, dynamic_decode
from .clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue
from .utils import clip_grad_norm_, clip_grad_value_, parameters_to_vector, vector_to_parameters
