"""LazyGuard — deferred parameter initialization.

Reference: ``python/paddle/nn/initializer/lazy_init.py`` (``LazyGuard``
context: layers constructed under it record their initializers instead
of running them; materialization happens later — the big-model workflow
where per-shard init must wait for placement decisions).

TPU-native: a lazy Parameter carries a ``jax.ShapeDtypeStruct`` payload
(shape/dtype inspection works, compute does not — identical contract to
the reference's unallocated tensor) plus its recorded initializer.
Materialization is automatic at the layer's first forward, or explicit
via ``materialize_layer`` (which a sharded-init path can call per shard
after choosing placements).
"""
from __future__ import annotations

__all__ = ["LazyGuard", "in_lazy_mode", "materialize_layer",
           "materialize_parameter"]

import weakref

#: lazy params awaiting materialization — id-keyed weak refs (a WeakSet
#: would trip over Tensor's elementwise __eq__), so an abandoned
#: LazyGuard model stops taxing every Layer.__call__ once it's GC'd
_STATE = {"on": False}
_PENDING: dict = {}


class LazyGuard:
    """Context manager: defer parameter initialization inside."""

    def __enter__(self):
        _STATE["on"] = True
        return self

    def __exit__(self, *exc):
        _STATE["on"] = False
        return False


def in_lazy_mode() -> bool:
    return _STATE["on"]


def _register(param, init, shape, dtype) -> None:
    param._lazy_init = (init, tuple(shape), dtype)
    key = id(param)
    _PENDING[key] = weakref.ref(
        param, lambda _ref, _k=key: _PENDING.pop(_k, None))


def has_outstanding() -> bool:
    return bool(_PENDING)


def materialize_parameter(param) -> bool:
    """Run the recorded initializer; True if this call materialized."""
    lazy = getattr(param, "_lazy_init", None)
    if lazy is None:
        return False
    init, shape, dtype = lazy
    param._swap_payload(init(shape, dtype))
    del param._lazy_init
    _PENDING.pop(id(param), None)
    return True


def materialize_layer(layer) -> int:
    """Materialize every lazy parameter under ``layer``; returns count."""
    n = 0
    for p in layer.parameters():
        if p is not None and materialize_parameter(p):
            n += 1
    return n
