"""Conv layers (reference: python/paddle/nn/layer/conv.py)."""
from __future__ import annotations

import math

import numpy as np

from .. import functional as F
from ..initializer import Uniform
from .layers import Layer


def _ntuple(v, n):
    return tuple(v) if isinstance(v, (list, tuple)) else (v,) * n


class _ConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, nd,
                 stride=1, padding=0, dilation=1, groups=1,
                 padding_mode="zeros", weight_attr=None, bias_attr=None,
                 data_format="NCHW", transpose=False, output_padding=0):
        super().__init__()
        if in_channels % groups != 0:
            raise ValueError("in_channels must be divisible by groups")
        self._in_channels = in_channels
        self._out_channels = out_channels
        self._kernel_size = _ntuple(kernel_size, nd)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._groups = groups
        self._padding_mode = padding_mode
        self._data_format = data_format
        self._nd = nd
        self._transpose = transpose
        self._output_padding = output_padding

        if transpose:
            shape = [in_channels, out_channels // groups] + list(self._kernel_size)
        else:
            shape = [out_channels, in_channels // groups] + list(self._kernel_size)
        fan_in = (in_channels // groups) * int(np.prod(self._kernel_size))
        bound = 1.0 / math.sqrt(fan_in)
        self.weight = self.create_parameter(
            shape, attr=weight_attr,
            default_initializer=Uniform(-bound, bound))
        self.bias = self.create_parameter(
            [out_channels], attr=bias_attr, is_bias=True,
            default_initializer=Uniform(-bound, bound))

    def extra_repr(self):
        return (f"{self._in_channels}, {self._out_channels}, "
                f"kernel_size={list(self._kernel_size)}")


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, self._stride,
                        self._padding, self._dilation, self._groups,
                        self._data_format)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self._stride,
                        self._padding, self._dilation, self._groups,
                        self._data_format)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self._stride,
                        self._padding, self._dilation, self._groups,
                        self._data_format)


class Conv1DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transpose=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv1d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding,
                                  self._groups, self._dilation, output_size,
                                  self._data_format)


class Conv2DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transpose=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv2d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding,
                                  self._groups, self._dilation, output_size,
                                  self._data_format)


class Conv3DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transpose=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv3d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding,
                                  self._groups, self._dilation, output_size,
                                  self._data_format)
