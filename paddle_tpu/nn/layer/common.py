"""Common layers (reference: python/paddle/nn/layer/common.py, distance.py)."""
from __future__ import annotations

from ...core import dtype as dtypes
from .. import functional as F
from ..initializer import Constant, Normal, Uniform, XavierNormal
from .layers import Layer


class Identity(Layer):
    def forward(self, x):
        return x


class Linear(Layer):
    """y = xW + b with W (in_features, out_features)
    (reference: nn/layer/common.py Linear)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=XavierNormal())
        self.bias = self.create_parameter(
            [out_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self.in_features}, out_features={self.out_features}"


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, p=self.p, axis=self.axis, training=self.training,
                         mode=self.mode)

    def extra_repr(self):
        return f"p={self.p}"


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout2d(x, p=self.p, training=self.training,
                           data_format=self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout3d(x, p=self.p, training=self.training,
                           data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, p=self.p, training=self.training)


class Embedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self._padding_idx = (None if padding_idx is None else
                             padding_idx if padding_idx >= 0
                             else num_embeddings + padding_idx)
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=Normal(0.0, 1.0))
        if self._padding_idx is not None:
            import jax.numpy as jnp
            self.weight._swap_payload(
                self.weight._data.at[self._padding_idx].set(0.0))

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self._padding_idx)

    def extra_repr(self):
        return f"{self._num_embeddings}, {self._embedding_dim}"


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, x):
        from ...ops import manipulation
        return manipulation.flatten(x, self.start_axis, self.stop_axis)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, align_mode=0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.mode = mode
        self.align_corners = align_corners
        self.align_mode = align_mode
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode,
                             self.align_corners, self.align_mode,
                             self.data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size, scale_factor, "nearest", False, 0, data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size, scale_factor, "bilinear", True, 0, data_format)


class _PadNd(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW"):
        super().__init__()
        self._pad = padding
        self._mode = mode
        self._value = value
        self._data_format = data_format

    def forward(self, x):
        return F.pad(x, self._pad, mode=self._mode, value=self._value,
                     data_format=self._data_format)


class Pad1D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCL",
                 name=None):
        if isinstance(padding, int):
            padding = [padding, padding]
        super().__init__(padding, mode, value, data_format)


class Pad2D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW",
                 name=None):
        if isinstance(padding, int):
            padding = [padding] * 4
        super().__init__(padding, mode, value, data_format)


class Pad3D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCDHW",
                 name=None):
        if isinstance(padding, int):
            padding = [padding] * 6
        super().__init__(padding, mode, value, data_format)


class ZeroPad2D(Pad2D):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__(padding, "constant", 0.0, data_format)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis = axis
        self.eps = eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, axis=self.axis, eps=self.eps)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p = p
        self.epsilon = epsilon
        self.keepdim = keepdim

    def forward(self, x, y):
        import jax.numpy as jnp
        from ...core import dispatch

        def f(a, b):
            d = a - b + self.epsilon
            return jnp.sum(jnp.abs(d) ** self.p,
                           axis=-1, keepdims=self.keepdim) ** (1.0 / self.p)
        from ...core.tensor import as_tensor, Tensor
        xt = x if isinstance(x, Tensor) else as_tensor(x)
        yt = y if isinstance(y, Tensor) else as_tensor(y)
        return dispatch.call("pairwise_distance", f, [xt, yt])


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            [out_features, in1_features, in2_features], attr=weight_attr,
            default_initializer=XavierNormal())
        self.bias = self.create_parameter([out_features], attr=bias_attr,
                                          is_bias=True)

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.factor = upscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self.factor, self.data_format)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.factor = downscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_unshuffle(x, self.factor, self.data_format)


class ChannelShuffle(Layer):
    def __init__(self, groups, data_format="NCHW", name=None):
        super().__init__()
        self.groups = groups
        self.data_format = data_format

    def forward(self, x):
        return F.channel_shuffle(x, self.groups, self.data_format)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1,
                 name=None):
        super().__init__()
        self.kernel_sizes = kernel_sizes
        self.strides = strides
        self.paddings = paddings
        self.dilations = dilations

    def forward(self, x):
        return F.unfold(x, self.kernel_sizes, self.strides, self.paddings,
                        self.dilations)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self.output_sizes = output_sizes
        self.kernel_sizes = kernel_sizes
        self.strides = strides
        self.paddings = paddings
        self.dilations = dilations

    def forward(self, x):
        return F.fold(x, self.output_sizes, self.kernel_sizes, self.strides,
                      self.paddings, self.dilations)
