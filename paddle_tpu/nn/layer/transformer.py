"""Transformer layers (reference: python/paddle/nn/layer/transformer.py).

MultiHeadAttention computes through F.scaled_dot_product_attention so the
TPU Pallas flash kernel is picked up automatically on TPU backends.
"""
from __future__ import annotations

import copy

import numpy as np

from ...core.tensor import Tensor
from .. import functional as F
from .common import Dropout, Linear
from .container import LayerList
from .layers import Layer
from .norm import LayerNorm


def _convert_attention_mask(attn_mask, dtype):
    if attn_mask is None:
        return None
    import jax.numpy as jnp
    from ...core import dispatch
    if attn_mask.dtype == np.dtype(bool):
        return dispatch.call(
            "mask_to_bias",
            lambda m: jnp.where(m, 0.0, -1e30).astype(jnp.float32),
            [attn_mask], differentiable_mask=[False])
    return attn_mask


class MultiHeadAttention(Layer):
    """Reference: nn/layer/transformer.py MultiHeadAttention (q/k/v/out
    projections + cache support)."""

    class Cache:
        def __init__(self, k, v):
            self.k, self.v = k, v

    class StaticCache:
        def __init__(self, k, v):
            self.k, self.v = k, v

    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None, vdim=None,
                 need_weights=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.kdim = kdim or embed_dim
        self.vdim = vdim or embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        assert self.head_dim * num_heads == embed_dim
        self.dropout = dropout
        self.need_weights = need_weights
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(self.kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(self.vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)

    def _reshape_heads(self, x):
        from ...ops import manipulation
        b, s = x.shape[0], x.shape[1]
        return manipulation.reshape(x, [b, s, self.num_heads, self.head_dim])

    def gen_cache(self, key, value=None, type=None):
        if type == MultiHeadAttention.StaticCache:
            k = self._reshape_heads(self.k_proj(key))
            v = self._reshape_heads(self.v_proj(value if value is not None else key))
            return self.StaticCache(k, v)
        import jax.numpy as jnp
        b = key.shape[0]
        k = Tensor(jnp.zeros((b, 0, self.num_heads, self.head_dim)))
        return self.Cache(k, Tensor(jnp.zeros((b, 0, self.num_heads,
                                               self.head_dim))))

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        from ...ops import manipulation
        key = query if key is None else key
        value = key if value is None else value
        if (key is query and value is query and cache is None
                and self.kdim == self.embed_dim
                and self.vdim == self.embed_dim):
            # self-attention fast path: one fused (h, 3h) projection
            # instead of three h x h GEMMs (reference fused_attention op;
            # the weight concat is trivially fused by XLA, the single
            # wider matmul keeps the MXU busier)
            from .. import functional as F
            w = manipulation.concat(
                [self.q_proj.weight, self.k_proj.weight,
                 self.v_proj.weight], axis=1)
            b = None
            if self.q_proj.bias is not None:
                b = manipulation.concat(
                    [self.q_proj.bias, self.k_proj.bias,
                     self.v_proj.bias], axis=0)
            qkv = F.linear(query, w, b)
            q, k, v = manipulation.split(qkv, 3, axis=-1)
            q = self._reshape_heads(q)
            k = self._reshape_heads(k)
            v = self._reshape_heads(v)
        else:
            q = self._reshape_heads(self.q_proj(query))
            if isinstance(cache, self.StaticCache):
                k, v = cache.k, cache.v
            else:
                k = self._reshape_heads(self.k_proj(key))
                v = self._reshape_heads(self.v_proj(value))
                if isinstance(cache, self.Cache):
                    k = manipulation.concat([cache.k, k], axis=1)
                    v = manipulation.concat([cache.v, v], axis=1)
                    cache = self.Cache(k, v)

        mask = _convert_attention_mask(attn_mask, None)
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=mask, dropout_p=self.dropout,
            training=self.training)
        b, s = out.shape[0], out.shape[1]
        out = manipulation.reshape(out, [b, s, self.embed_dim])
        out = self.out_proj(out)
        if isinstance(cache, self.Cache):
            return (out, cache) if not self.need_weights else (out, None, cache)
        if self.need_weights:
            return out, None
        return out


class TransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 layer_norm_eps=1e-5):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                            weight_attr=weight_attr,
                                            bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.dropout = Dropout(act_dropout)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.norm2 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.activation = getattr(F, activation)

    def forward(self, src, src_mask=None, cache=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        if cache is None:
            src = self.self_attn(src, src, src, src_mask)
        else:
            src, cache = self.self_attn(src, src, src, src_mask, cache)
        src = residual + self.dropout1(src)
        if not self.normalize_before:
            src = self.norm1(src)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        src = self.linear2(self.dropout(self.activation(self.linear1(src))))
        src = residual + self.dropout2(src)
        if not self.normalize_before:
            src = self.norm2(src)
        return src if cache is None else (src, cache)

    def gen_cache(self, src):
        return self.self_attn.gen_cache(src)


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        self.layers = LayerList(
            [encoder_layer] + [copy.deepcopy(encoder_layer)
                               for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None, cache=None):
        output = src
        new_caches = []
        for i, mod in enumerate(self.layers):
            if cache is None:
                output = mod(output, src_mask)
            else:
                output, new_cache = mod(output, src_mask, cache[i])
                new_caches.append(new_cache)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, src):
        return [layer.gen_cache(src) for layer in self.layers]


class TransformerDecoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 layer_norm_eps=1e-5):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                            weight_attr=weight_attr,
                                            bias_attr=bias_attr)
        self.cross_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                             weight_attr=weight_attr,
                                             bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.dropout = Dropout(act_dropout)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.norm2 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.norm3 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout3 = Dropout(dropout)
        self.activation = getattr(F, activation)

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None,
                cache=None):
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        if cache is None:
            tgt = self.self_attn(tgt, tgt, tgt, tgt_mask)
            incremental_cache = None
        else:
            tgt, incremental_cache = self.self_attn(tgt, tgt, tgt, tgt_mask,
                                                    cache[0])
        tgt = residual + self.dropout1(tgt)
        if not self.normalize_before:
            tgt = self.norm1(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        if cache is None:
            tgt = self.cross_attn(tgt, memory, memory, memory_mask)
            static_cache = None
        else:
            tgt = self.cross_attn(tgt, memory, memory, memory_mask, cache[1])
            static_cache = cache[1]
        tgt = residual + self.dropout2(tgt)
        if not self.normalize_before:
            tgt = self.norm2(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        tgt = self.linear2(self.dropout(self.activation(self.linear1(tgt))))
        tgt = residual + self.dropout3(tgt)
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        return tgt if cache is None else (tgt, (incremental_cache,
                                                static_cache))

    def gen_cache(self, memory):
        incremental = self.self_attn.gen_cache(memory)
        static = self.cross_attn.gen_cache(memory, memory,
                                           MultiHeadAttention.StaticCache)
        return incremental, static


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        self.layers = LayerList(
            [decoder_layer] + [copy.deepcopy(decoder_layer)
                               for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None,
                cache=None):
        output = tgt
        new_caches = []
        for i, mod in enumerate(self.layers):
            if cache is None:
                output = mod(output, memory, tgt_mask, memory_mask)
            else:
                output, new_cache = mod(output, memory, tgt_mask, memory_mask,
                                        cache[i])
                new_caches.append(new_cache)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, memory, do_zip=False):
        cache = [layer.gen_cache(memory) for layer in self.layers]
        if do_zip:
            cache = list(zip(*cache))
        return cache


class Transformer(Layer):
    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6,
                 num_decoder_layers=6, dim_feedforward=2048, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 custom_encoder=None, custom_decoder=None):
        super().__init__()
        if custom_encoder is not None:
            self.encoder = custom_encoder
        else:
            enc_layer = TransformerEncoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr,
                bias_attr)
            enc_norm = LayerNorm(d_model) if normalize_before else None
            self.encoder = TransformerEncoder(enc_layer, num_encoder_layers,
                                              enc_norm)
        if custom_decoder is not None:
            self.decoder = custom_decoder
        else:
            dec_layer = TransformerDecoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr,
                bias_attr)
            dec_norm = LayerNorm(d_model) if normalize_before else None
            self.decoder = TransformerDecoder(dec_layer, num_decoder_layers,
                                              dec_norm)
        self.d_model = d_model
        self.nhead = nhead

    def forward(self, src, tgt, src_mask=None, tgt_mask=None,
                memory_mask=None):
        memory = self.encoder(src, src_mask)
        return self.decoder(tgt, memory, tgt_mask, memory_mask)

    @staticmethod
    def generate_square_subsequent_mask(length):
        import jax.numpy as jnp
        return Tensor(jnp.where(
            jnp.tril(jnp.ones((length, length), bool)), 0.0, -1e30))
