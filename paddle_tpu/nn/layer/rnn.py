"""Recurrent layers: cells, RNN/BiRNN drivers, SimpleRNN/LSTM/GRU stacks.

Capability parity with the reference recurrent stack (reference:
python/paddle/nn/layer/rnn.py — RNNCellBase:551, SimpleRNNCell:697,
LSTMCell:874, GRUCell:1100, RNN:1293, BiRNN:1366, RNNBase cudnn-flattened
multi-layer driver:1694, SimpleRNN:1758, LSTM:1881, GRU:2018). TPU-native:
the time loop is ONE ``lax.scan`` per direction (compiled once, no Python
step loop), gate matmuls are batched [B, 4H]-style MXU ops, and the whole
multi-layer stack stays inside a single dispatch op so XLA fuses gates +
activations per step.
"""
from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...core import dispatch
from ...core.tensor import Tensor
from ..initializer import Uniform
from ..parameter import ParamAttr
from .layers import Layer


def _uniform_attr(hidden_size):
    k = 1.0 / math.sqrt(hidden_size)
    return ParamAttr(initializer=Uniform(-k, k))


class RNNCellBase(Layer):
    """reference rnn.py:551 — get_initial_states helper."""

    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        batch = batch_ref.shape[batch_dim_idx]
        shape = shape or (self.hidden_size,)
        return Tensor(jnp.full((batch,) + tuple(shape), init_value,
                               jnp.float32))

    @property
    def state_shape(self):
        raise NotImplementedError

    def gate_params(self):
        """(weight_ih, weight_hh, bias_ih, bias_hh) tensors."""
        return (self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh)


class SimpleRNNCell(RNNCellBase):
    """h' = act(W_ih x + b_ih + W_hh h + b_hh) (reference rnn.py:697)."""

    n_gates = 1

    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        if activation not in ("tanh", "relu"):
            raise ValueError("activation must be tanh or relu")
        self.activation = activation
        attr = _uniform_attr(hidden_size)
        self.weight_ih = self.create_parameter(
            [hidden_size, input_size], attr=weight_ih_attr or attr)
        self.weight_hh = self.create_parameter(
            [hidden_size, hidden_size], attr=weight_hh_attr or attr)
        self.bias_ih = self.create_parameter(
            [hidden_size], attr=bias_ih_attr or attr, is_bias=True)
        self.bias_hh = self.create_parameter(
            [hidden_size], attr=bias_hh_attr or attr, is_bias=True)

    @staticmethod
    def step(params, x, h, activation="tanh"):
        w_ih, w_hh, b_ih, b_hh = params
        z = x @ w_ih.T + b_ih + h @ w_hh.T + b_hh
        h_new = jnp.tanh(z) if activation == "tanh" else jax.nn.relu(z)
        return h_new, h_new

    def forward(self, inputs, states=None):
        h = states if states is not None else \
            self.get_initial_states(inputs)
        def f(x, hh, w_ih, w_hh, b_ih, b_hh):
            return self.step((w_ih, w_hh, b_ih, b_hh), x, hh,
                             self.activation)
        out = dispatch.call(
            "simple_rnn_cell", f,
            [inputs if isinstance(inputs, Tensor) else Tensor(inputs),
             h, *self.gate_params()])
        return out[0], out[1]

    @property
    def state_shape(self):
        return (self.hidden_size,)


class LSTMCell(RNNCellBase):
    """i,f,g,o gates (reference rnn.py:874)."""

    n_gates = 4

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        attr = _uniform_attr(hidden_size)
        self.weight_ih = self.create_parameter(
            [4 * hidden_size, input_size], attr=weight_ih_attr or attr)
        self.weight_hh = self.create_parameter(
            [4 * hidden_size, hidden_size], attr=weight_hh_attr or attr)
        self.bias_ih = self.create_parameter(
            [4 * hidden_size], attr=bias_ih_attr or attr, is_bias=True)
        self.bias_hh = self.create_parameter(
            [4 * hidden_size], attr=bias_hh_attr or attr, is_bias=True)

    @staticmethod
    def step(params, x, state, activation=None):
        w_ih, w_hh, b_ih, b_hh = params
        h, c = state
        gates = x @ w_ih.T + b_ih + h @ w_hh.T + b_hh
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = (jax.nn.sigmoid(v) for v in (i, f, o))
        g = jnp.tanh(g)
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        return h_new, (h_new, c_new)

    def forward(self, inputs, states=None):
        if states is None:
            h = self.get_initial_states(inputs)
            c = self.get_initial_states(inputs)
        else:
            h, c = states

        def f(x, hh, cc, w_ih, w_hh, b_ih, b_hh):
            h_new, (_, c_new) = self.step((w_ih, w_hh, b_ih, b_hh), x,
                                          (hh, cc))
            return h_new, c_new
        out = dispatch.call(
            "lstm_cell", f,
            [inputs if isinstance(inputs, Tensor) else Tensor(inputs),
             h, c, *self.gate_params()])
        return out[0], (out[0], out[1])

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))


class GRUCell(RNNCellBase):
    """r,z,c gates (reference rnn.py:1100; paddle gate order r,z,c)."""

    n_gates = 3

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        attr = _uniform_attr(hidden_size)
        self.weight_ih = self.create_parameter(
            [3 * hidden_size, input_size], attr=weight_ih_attr or attr)
        self.weight_hh = self.create_parameter(
            [3 * hidden_size, hidden_size], attr=weight_hh_attr or attr)
        self.bias_ih = self.create_parameter(
            [3 * hidden_size], attr=bias_ih_attr or attr, is_bias=True)
        self.bias_hh = self.create_parameter(
            [3 * hidden_size], attr=bias_hh_attr or attr, is_bias=True)

    @staticmethod
    def step(params, x, h, activation=None):
        w_ih, w_hh, b_ih, b_hh = params
        gx = x @ w_ih.T + b_ih
        gh = h @ w_hh.T + b_hh
        rx, zx, cx = jnp.split(gx, 3, axis=-1)
        rh, zh, ch = jnp.split(gh, 3, axis=-1)
        r = jax.nn.sigmoid(rx + rh)
        z = jax.nn.sigmoid(zx + zh)
        c = jnp.tanh(cx + r * ch)
        h_new = (1.0 - z) * c + z * h
        return h_new, h_new

    def forward(self, inputs, states=None):
        h = states if states is not None else \
            self.get_initial_states(inputs)

        def f(x, hh, w_ih, w_hh, b_ih, b_hh):
            return self.step((w_ih, w_hh, b_ih, b_hh), x, hh)
        out = dispatch.call(
            "gru_cell", f,
            [inputs if isinstance(inputs, Tensor) else Tensor(inputs),
             h, *self.gate_params()])
        return out[0], out[1]

    @property
    def state_shape(self):
        return (self.hidden_size,)


def _scan_direction(cell_cls, params, xs, init_state, activation,
                    reverse=False):
    """lax.scan over time. xs: [T, B, I]; returns (outs [T,B,H], final)."""
    def body(state, x):
        out, new_state = cell_cls.step(params, x, state, activation)
        return new_state, out

    if reverse:
        xs = xs[::-1]
    final, outs = jax.lax.scan(body, init_state, xs)
    if reverse:
        outs = outs[::-1]
    return outs, final


class RNN(Layer):
    """Single-cell driver (reference rnn.py:1293): scans the cell over the
    time dim."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        if sequence_length is not None:
            raise NotImplementedError(
                "variable-length sequences: pad + mask externally")
        cell = self.cell
        x = inputs if isinstance(inputs, Tensor) else Tensor(inputs)
        is_lstm = isinstance(cell, LSTMCell)

        if initial_states is None:
            batch = x.shape[0] if not self.time_major else x.shape[1]
            h0 = jnp.zeros((batch, cell.hidden_size), jnp.float32)
            init = (h0, h0) if is_lstm else h0
            init_tensors = [Tensor(h0), Tensor(h0)] if is_lstm \
                else [Tensor(h0)]
        else:
            init_tensors = list(initial_states) if is_lstm \
                else [initial_states]

        params = cell.gate_params()
        act = getattr(cell, "activation", None)
        time_major = self.time_major
        reverse = self.is_reverse

        def f(xa, *rest):
            n_state = 2 if is_lstm else 1
            state = rest[:n_state]
            w = rest[n_state:]
            xs = xa if time_major else jnp.swapaxes(xa, 0, 1)
            init = tuple(state) if is_lstm else state[0]
            outs, final = _scan_direction(type(cell), w, xs, init, act,
                                          reverse)
            if not time_major:
                outs = jnp.swapaxes(outs, 0, 1)
            return (outs,) + (tuple(final) if is_lstm else (final,))

        res = dispatch.call("rnn_scan", f, [x, *init_tensors, *params])
        if is_lstm:
            return res[0], (res[1], res[2])
        return res[0], res[1]


class BiRNN(Layer):
    """Two cells, opposite directions, concatenated outputs (reference
    rnn.py:1366)."""

    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.cell_fw = cell_fw
        self.cell_bw = cell_bw
        self.rnn_fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.rnn_bw = RNN(cell_bw, is_reverse=True, time_major=time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        states = initial_states or (None, None)
        out_fw, st_fw = self.rnn_fw(inputs, states[0], sequence_length)
        out_bw, st_bw = self.rnn_bw(inputs, states[1], sequence_length)
        from ... import ops
        return ops.concat([out_fw, out_bw], axis=-1), (st_fw, st_bw)


_CELLS = {"SimpleRNN": SimpleRNNCell, "LSTM": LSTMCell, "GRU": GRUCell}


class RNNBase(Layer):
    """Multi-layer (optionally bidirectional) stack (reference
    rnn.py:1694). States are [num_layers*num_directions, B, H]."""

    def __init__(self, mode, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None):
        super().__init__()
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        if direction in ("forward",):
            self.num_directions = 1
        elif direction in ("bidirect", "bidirectional"):
            self.num_directions = 2
        else:
            raise ValueError(f"unknown direction {direction!r}")
        cell_cls = _CELLS[mode]
        kw = dict(weight_ih_attr=weight_ih_attr,
                  weight_hh_attr=weight_hh_attr,
                  bias_ih_attr=bias_ih_attr, bias_hh_attr=bias_hh_attr)
        if mode == "SimpleRNN":
            kw["activation"] = activation
        from .container import LayerList
        self.cells = LayerList()
        for layer in range(num_layers):
            in_sz = input_size if layer == 0 else \
                hidden_size * self.num_directions
            for _ in range(self.num_directions):
                self.cells.append(cell_cls(in_sz, hidden_size, **kw))

    def forward(self, inputs, initial_states=None, sequence_length=None):
        if sequence_length is not None:
            raise NotImplementedError(
                "variable-length sequences: pad + mask externally")
        x = inputs if isinstance(inputs, Tensor) else Tensor(inputs)
        is_lstm = self.mode == "LSTM"
        nl, nd = self.num_layers, self.num_directions
        batch = x.shape[1] if self.time_major else x.shape[0]

        if initial_states is None:
            z = jnp.zeros((nl * nd, batch, self.hidden_size), jnp.float32)
            init_tensors = [Tensor(z), Tensor(z)] if is_lstm else [Tensor(z)]
        else:
            init_tensors = list(initial_states) if is_lstm \
                else [initial_states]

        all_params = []
        for cell in self.cells:
            all_params.extend(cell.gate_params())
        cell0 = self.cells[0]
        act = getattr(cell0, "activation", None)
        cell_cls = type(cell0)
        time_major = self.time_major
        n_per = 4
        # inter-layer dropout (reference RNNBase: applied to every
        # non-final layer's output while training)
        dropout_p = float(self.dropout or 0.0)
        drop_keys = None
        if dropout_p > 0.0 and self.training and nl > 1:
            from ...core.generator import next_key
            drop_keys = jax.random.split(next_key(), nl - 1)

        def f(xa, *rest):
            n_state = 2 if is_lstm else 1
            states = rest[:n_state]
            flat = rest[n_state:]
            xs = xa if time_major else jnp.swapaxes(xa, 0, 1)
            final_h, final_c = [], []
            for layer in range(nl):
                outs_dir = []
                for d in range(nd):
                    idx = layer * nd + d
                    w = flat[idx * n_per:(idx + 1) * n_per]
                    if is_lstm:
                        init = (states[0][idx], states[1][idx])
                    else:
                        init = states[0][idx]
                    outs, final = _scan_direction(cell_cls, w, xs, init,
                                                  act, reverse=(d == 1))
                    outs_dir.append(outs)
                    if is_lstm:
                        final_h.append(final[0])
                        final_c.append(final[1])
                    else:
                        final_h.append(final)
                xs = outs_dir[0] if nd == 1 else jnp.concatenate(
                    outs_dir, axis=-1)
                if drop_keys is not None and layer < nl - 1:
                    keep = jax.random.bernoulli(
                        drop_keys[layer], 1.0 - dropout_p, xs.shape)
                    xs = jnp.where(keep, xs / (1.0 - dropout_p), 0.0)
            out = xs if time_major else jnp.swapaxes(xs, 0, 1)
            if is_lstm:
                return out, jnp.stack(final_h), jnp.stack(final_c)
            return out, jnp.stack(final_h)

        res = dispatch.call(f"{self.mode.lower()}_stack", f,
                            [x, *init_tensors, *all_params])
        if is_lstm:
            return res[0], (res[1], res[2])
        return res[0], res[1]


class SimpleRNN(RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kw):
        super().__init__("SimpleRNN", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, activation, **kw)


class LSTM(RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0, **kw):
        super().__init__("LSTM", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kw)


class GRU(RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0, **kw):
        super().__init__("GRU", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kw)
