"""Layer: the module base class.

Capability parity with the reference Layer (reference:
python/paddle/nn/layer/layers.py — parameter/sublayer registration via
__setattr__, state_dict/set_state_dict, forward pre/post hooks, train/eval,
to/astype casting, apply). TPU-native notes: ``to(dtype=...)`` casts the
wrapped jax buffers (used by amp.decorate for bf16-O2), and parameters are
pytree-flattenable so whole layers can cross a jit boundary.
"""
from __future__ import annotations

import collections
from typing import Callable, Dict, Iterator, Optional, Tuple

import numpy as np

from ...core import dtype as dtypes
from ...core.tensor import Tensor
from ..lazy_init import has_outstanding, materialize_layer
from ..parameter import Parameter, ParamAttr, create_parameter


class _HookRemoveHelper:
    def __init__(self, hooks: dict, hook_id: int):
        self._hooks = hooks
        self._hook_id = hook_id

    def remove(self):
        self._hooks.pop(self._hook_id, None)


class Layer:
    def __init__(self, name_scope: Optional[str] = None, dtype="float32"):
        self.training = True
        self._dtype = dtypes.convert_dtype(dtype)
        self._name_scope = name_scope or self.__class__.__name__.lower()
        self._parameters: Dict[str, Optional[Parameter]] = collections.OrderedDict()
        self._sub_layers: Dict[str, Optional["Layer"]] = collections.OrderedDict()
        self._buffers: Dict[str, Optional[Tensor]] = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks: Dict[int, Callable] = collections.OrderedDict()
        self._forward_post_hooks: Dict[int, Callable] = collections.OrderedDict()
        self._hook_id = 0
        self._casted_by_pure_fp16 = False

    # ------------------------------------------------------------ attributes
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning parameters")
            for d in (layers, buffers):
                if d is not None:
                    d.pop(name, None)
            params[name] = value
            object.__getattribute__(self, "__dict__").pop(name, None)
            return
        if isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ before assigning sublayers")
            for d in (params, buffers):
                if d is not None:
                    d.pop(name, None)
            layers[name] = value
            object.__getattribute__(self, "__dict__").pop(name, None)
            return
        if params is not None and name in params:
            if value is None:
                params[name] = None
                return
            if isinstance(value, Tensor):
                params[name].set_value(value)
                return
            params.pop(name)
        if layers is not None and name in layers and value is None:
            layers[name] = None
            return
        if buffers is not None and name in buffers:
            if value is None or isinstance(value, Tensor):
                buffers[name] = value
                return
            buffers.pop(name)
        object.__setattr__(self, name, value)

    def __getattr__(self, name):
        # only called when normal lookup fails
        d = self.__dict__
        for store in ("_parameters", "_sub_layers", "_buffers"):
            s = d.get(store)
            if s is not None and name in s:
                return s[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute {name!r}")

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            s = self.__dict__.get(store)
            if s is not None and name in s:
                del s[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        extra = []
        for store in ("_parameters", "_sub_layers", "_buffers"):
            extra += list(self.__dict__.get(store, ()))
        return list(super().__dir__()) + extra

    # ------------------------------------------------------------- creation
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None) -> Optional[Parameter]:
        dtype = dtype or self._dtype
        return create_parameter(shape, dtype=dtype, attr=attr, is_bias=is_bias,
                                default_initializer=default_initializer)

    def add_parameter(self, name: str, parameter: Optional[Parameter]):
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name: str, sublayer: "Layer"):
        self._sub_layers[name] = sublayer
        return sublayer

    def register_buffer(self, name: str, tensor: Optional[Tensor],
                        persistable: bool = True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    # ------------------------------------------------------------ iteration
    def parameters(self, include_sublayers: bool = True):
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_parameters(self, prefix: str = "",
                         include_sublayers: bool = True
                         ) -> Iterator[Tuple[str, Parameter]]:
        seen = set()
        for layer_name, layer in self.named_sublayers(prefix=prefix,
                                                      include_self=True):
            if not include_sublayers and layer is not self:
                continue
            for pname, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (layer_name + "." + pname if layer_name else pname), p

    def buffers(self, include_sublayers: bool = True):
        return [b for _, b in self.named_buffers(
            include_sublayers=include_sublayers)]

    def named_buffers(self, prefix: str = "", include_sublayers: bool = True):
        seen = set()
        for layer_name, layer in self.named_sublayers(prefix=prefix,
                                                      include_self=True):
            if not include_sublayers and layer is not self:
                continue
            for bname, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (layer_name + "." + bname if layer_name else bname), b

    def children(self) -> Iterator["Layer"]:
        for _, l in self.named_children():
            yield l

    def named_children(self):
        for name, l in self._sub_layers.items():
            if l is not None:
                yield name, l

    def sublayers(self, include_self: bool = False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_sublayers(self, prefix: str = "", include_self: bool = False,
                        layers_set=None):
        if layers_set is None:
            layers_set = set()
        if id(self) in layers_set:
            return
        layers_set.add(id(self))
        if include_self:
            yield prefix, self
        for name, l in self._sub_layers.items():
            if l is None:
                continue
            sub_prefix = prefix + "." + name if prefix else name
            yield from l.named_sublayers(prefix=sub_prefix, include_self=True,
                                         layers_set=layers_set)

    def apply(self, fn):
        for l in self.children():
            l.apply(fn)
        fn(self)
        return self

    def full_name(self):
        return self._name_scope

    # ------------------------------------------------------------ training
    def train(self):
        self.training = True
        for l in self.sublayers():
            l.training = True
        return self

    def eval(self):
        self.training = False
        for l in self.sublayers():
            l.training = False
        return self

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    # ----------------------------------------------------------- state dict
    def state_dict(self, destination=None, include_sublayers: bool = True,
                   structured_name_prefix: str = "", use_hook: bool = True):
        dest = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters(prefix=structured_name_prefix.rstrip(".")):
            dest[name] = p
        for name, b in self.named_buffers(prefix=structured_name_prefix.rstrip(".")):
            bare = name.rsplit(".", 1)[-1]
            owner = self._locate_owner(name)
            if owner is not None and bare in owner._non_persistable_buffer_names:
                continue
            dest[name] = b
        return dest

    def _locate_owner(self, qualified: str) -> Optional["Layer"]:
        parts = qualified.split(".")[:-1]
        layer = self
        for p in parts:
            nxt = layer._sub_layers.get(p)
            if nxt is None:
                return None
            layer = nxt
        return layer

    def set_state_dict(self, state_dict, use_structured_name: bool = True):
        missing, unexpected = [], []
        own = self.state_dict()
        matched = set()
        for name, value in state_dict.items():
            if name not in own:
                unexpected.append(name)
                continue
            target = own[name]
            v = value
            if isinstance(v, Tensor):
                v = v._data
            v = np.asarray(v) if not hasattr(v, "shape") else v
            if tuple(v.shape) != tuple(target.shape):
                raise ValueError(
                    f"shape mismatch for {name}: got {tuple(v.shape)}, "
                    f"expected {tuple(target.shape)}")
            target.set_value(v)
            matched.add(name)
        for name in own:
            if name not in matched:
                missing.append(name)
        return missing, unexpected

    load_dict = set_state_dict

    # ------------------------------------------------------------------ cast
    def _apply_to_tensors(self, fn):
        for layer in self.sublayers(include_self=True):
            for k, p in layer._parameters.items():
                if p is not None:
                    fn(p)
            for k, b in layer._buffers.items():
                if b is not None:
                    fn(b)
        return self

    def to(self, device=None, dtype=None, blocking=None):
        if dtype is None:
            return self
        target = dtypes.convert_dtype(dtype)

        def cast(t):
            cur = t.dtype
            if (np.issubdtype(cur, np.floating) or cur == dtypes.bfloat16) \
                    and cur != target:
                t._swap_payload(t._data.astype(target))
        self._apply_to_tensors(cast)
        self._dtype = target
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype=dtypes.float32)

    def bfloat16(self):
        return self.to(dtype=dtypes.bfloat16)

    def float16(self):
        return self.to(dtype=dtypes.float16)

    # ---------------------------------------------------------------- hooks
    def register_forward_pre_hook(self, hook):
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return _HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook):
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return _HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    # ----------------------------------------------------------------- call
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        if has_outstanding():  # LazyGuard-deferred params: init now
            materialize_layer(self)
        for hook in list(self._forward_pre_hooks.values()):
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            result = hook(self, inputs, outputs)
            if result is not None:
                outputs = result
        return outputs

    # ---------------------------------------------------------------- extra
    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, l in self._sub_layers.items():
            if l is None:
                continue
            mod_str = repr(l)
            mod_str = "\n".join(
                ("  " + ln if i else ln) for i, ln in enumerate(mod_str.split("\n")))
            lines.append(f"({name}): {mod_str}")
        main = self.__class__.__name__
        if not lines:
            return f"{main}({extra})"
        body = "\n  ".join([extra] if extra else []) + ("\n  " if extra and lines else "")
        return f"{main}(\n  " + "\n  ".join(([extra] if extra else []) + lines) + "\n)"
