"""nn layer tail: the remaining reference Layer classes.

Reference parity: python/paddle/nn/layer/{loss,pooling,common,
activation}.py classes present in the reference ``nn.__all__`` but
previously absent — thin Layer wrappers over the (tested) functional
surface, matching the reference's constructor/forward contracts.
"""
from __future__ import annotations

from typing import Optional, Sequence

from .layers import Layer
from .. import functional as F

__all__ = [
    "PoissonNLLLoss", "MultiLabelSoftMarginLoss", "MultiMarginLoss",
    "SoftMarginLoss", "GaussianNLLLoss", "TripletMarginWithDistanceLoss",
    "AdaptiveLogSoftmaxWithLoss", "RNNTLoss", "HSigmoidLoss",
    "MaxUnPool1D", "MaxUnPool2D", "MaxUnPool3D", "FractionalMaxPool2D",
    "FractionalMaxPool3D", "LPPool1D", "LPPool2D", "Softmax2D",
    "Unflatten", "ZeroPad1D", "ZeroPad3D",
]


class PoissonNLLLoss(Layer):
    def __init__(self, log_input=True, full=False, epsilon=1e-8,
                 reduction="mean", name=None):
        super().__init__()
        self._args = (log_input, full, epsilon, reduction)

    def forward(self, input, label):
        return F.poisson_nll_loss(input, label, *self._args)


class MultiLabelSoftMarginLoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):
        return F.multi_label_soft_margin_loss(
            input, label, self.weight, self.reduction)


class MultiMarginLoss(Layer):
    def __init__(self, p=1, margin=1.0, weight=None, reduction="mean",
                 name=None):
        super().__init__()
        self._args = (p, margin, weight, reduction)

    def forward(self, input, label):
        return F.multi_margin_loss(input, label, *self._args)


class SoftMarginLoss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.soft_margin_loss(input, label, self.reduction)


class GaussianNLLLoss(Layer):
    def __init__(self, full=False, epsilon=1e-6, reduction="mean",
                 name=None):
        super().__init__()
        self._args = (full, epsilon, reduction)

    def forward(self, input, label, variance):
        return F.gaussian_nll_loss(input, label, variance, *self._args)


class TripletMarginWithDistanceLoss(Layer):
    def __init__(self, distance_function=None, margin=1.0, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self._args = (distance_function, margin, swap, reduction)

    def forward(self, input, positive, negative):
        return F.triplet_margin_with_distance_loss(
            input, positive, negative, *self._args)


class AdaptiveLogSoftmaxWithLoss(Layer):
    """reference loss.py AdaptiveLogSoftmaxWithLoss: owns the head and
    per-cluster tail projections; ``cutoffs`` EXCLUDES n_classes (the
    reference constructor contract)."""

    def __init__(self, in_features, n_classes, cutoffs,
                 div_value=4.0, head_bias=False, name=None):
        super().__init__()
        cutoffs = list(cutoffs)
        if not cutoffs or cutoffs != sorted(cutoffs) \
                or cutoffs[-1] > n_classes - 1:
            raise ValueError("cutoffs must be sorted and < n_classes")
        self.cutoffs = cutoffs + [n_classes]
        self.shortlist = cutoffs[0]
        n_clusters = len(self.cutoffs) - 1
        head_size = self.shortlist + n_clusters
        self.head_weight = self.create_parameter(
            [in_features, head_size])
        self.head_bias = (self.create_parameter([head_size], is_bias=True)
                          if head_bias else None)
        self.tail_weights = []
        for i in range(n_clusters):
            proj = max(1, int(in_features / (div_value ** (i + 1))))
            size = self.cutoffs[i + 1] - self.cutoffs[i]
            w1 = self.create_parameter([in_features, proj])
            w2 = self.create_parameter([proj, size])
            self.add_parameter(f"tail_{i}_proj", w1)
            self.add_parameter(f"tail_{i}_out", w2)
            self.tail_weights.append((w1, w2))

    def forward(self, input, label):
        return F.adaptive_log_softmax_with_loss(
            input, label, self.head_weight, self.tail_weights,
            self.cutoffs, head_bias=self.head_bias)


class RNNTLoss(Layer):
    def __init__(self, blank=0, fastemit_lambda=0.001, reduction="mean",
                 name=None):
        super().__init__()
        self._args = (blank, fastemit_lambda, reduction)

    def forward(self, logits, labels, logit_lengths, label_lengths):
        blank, fastemit, reduction = self._args
        return F.rnnt_loss(logits, labels, logit_lengths, label_lengths,
                           blank=blank, fastemit_lambda=fastemit,
                           reduction=reduction)


class HSigmoidLoss(Layer):
    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 name=None):
        super().__init__()
        self.num_classes = num_classes
        self.weight = self.create_parameter(
            [num_classes - 1, feature_size], attr=weight_attr)
        self.bias = (None if bias_attr is False else
                     self.create_parameter([num_classes - 1],
                                           attr=bias_attr, is_bias=True))

    def forward(self, input, label, path_table=None, path_code=None):
        return F.hsigmoid_loss(input, label, self.num_classes,
                               self.weight, bias=self.bias,
                               path_table=path_table,
                               path_code=path_code)


def _unpool(fname):
    class _UnPool(Layer):
        def __init__(self, kernel_size, stride=None, padding=0,
                     data_format=None, output_size=None, name=None):
            super().__init__()
            self._args = (kernel_size, stride, padding, output_size)

        def forward(self, x, indices):
            kernel_size, stride, padding, output_size = self._args
            return getattr(F, fname)(
                x, indices, kernel_size, stride=stride, padding=padding,
                output_size=output_size)
    _UnPool.__name__ = fname.title().replace("_", "").replace(
        "Maxunpool", "MaxUnPool")
    return _UnPool


MaxUnPool1D = _unpool("max_unpool1d")
MaxUnPool2D = _unpool("max_unpool2d")
MaxUnPool3D = _unpool("max_unpool3d")


class FractionalMaxPool2D(Layer):
    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self._args = (output_size, kernel_size, random_u, return_mask)

    def forward(self, x):
        output_size, kernel_size, random_u, return_mask = self._args
        return F.fractional_max_pool2d(
            x, output_size, kernel_size=kernel_size, random_u=random_u,
            return_mask=return_mask)


class FractionalMaxPool3D(Layer):
    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self._args = (output_size, kernel_size, random_u, return_mask)

    def forward(self, x):
        output_size, kernel_size, random_u, return_mask = self._args
        return F.fractional_max_pool3d(
            x, output_size, kernel_size=kernel_size, random_u=random_u,
            return_mask=return_mask)


class LPPool1D(Layer):
    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NCL", name=None):
        super().__init__()
        self._args = (norm_type, kernel_size, stride, padding, ceil_mode)

    def forward(self, x):
        norm_type, kernel_size, stride, padding, ceil_mode = self._args
        return F.lp_pool1d(x, norm_type, kernel_size, stride=stride,
                           padding=padding, ceil_mode=ceil_mode)


class LPPool2D(Layer):
    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NCHW", name=None):
        super().__init__()
        self._args = (norm_type, kernel_size, stride, padding, ceil_mode)

    def forward(self, x):
        norm_type, kernel_size, stride, padding, ceil_mode = self._args
        return F.lp_pool2d(x, norm_type, kernel_size, stride=stride,
                           padding=padding, ceil_mode=ceil_mode)


class Softmax2D(Layer):
    """softmax over the channel axis of NCHW input (reference
    Softmax2D)."""

    def forward(self, x):
        if x.ndim not in (3, 4):
            raise ValueError(
                f"Softmax2D expects 3D/4D input, got {x.ndim}D")
        return F.softmax(x, axis=-3)


class Unflatten(Layer):
    def __init__(self, axis, shape, name=None):
        super().__init__()
        self.axis = axis
        self.shape = shape

    def forward(self, x):
        from ... import ops
        from ...ops.tail import unflatten
        return unflatten(x, self.axis, self.shape)


class _ZeroPadNd(Layer):
    def __init__(self, padding, data_format, name=None):
        super().__init__()
        self.padding = padding
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding, mode="constant", value=0.0,
                     data_format=self.data_format)


class ZeroPad1D(_ZeroPadNd):
    def __init__(self, padding, data_format="NCL", name=None):
        super().__init__(padding, data_format)


class ZeroPad3D(_ZeroPadNd):
    def __init__(self, padding, data_format="NCDHW", name=None):
        super().__init__(padding, data_format)
